"""Disaggregated prefill/decode: prefill-only engine + KV handoff format.

The reference *declares* "disaggregated inference" — a preproc/decode split
that never got code (``/root/reference/README.md:15,96-98``; SURVEY.md §2.3
last row). This module is the TPU-native realisation (BASELINE.json
configs[4]): a **prefill pool** computes each prompt's KV state and first
token on its own chips, then hands the KV off over DCN to a **decode pool**
whose slots only ever run the memory-bound decode loop. Prefill's
compute-bound batched matmuls and decode's latency-sensitive small steps stop
interfering (SURVEY.md §7 hard-part #3 — disaggregation is the escape
hatch).

Split of responsibilities:

- ``PrefillEngine`` (this file): bucketed batch prefill → per-request
  ``PrefillHandoff`` (first sampled token + prompt KV, trimmed to the true
  prompt length, in the decode pool's KV dtype).
- ``ContinuousEngine.submit_prefilled``: admits a handoff into a paged slot
  — scatters the KV into pages and resumes decoding as if it had prefetched
  the prompt itself.
- Wire form (``handoff_to_wire``/``handoff_from_wire``): raw little-endian
  bytes + dtype/shape metadata, carried inside the framed RPC's msgpack
  payload (``utils/framing.py``). The host RPC plane is the DCN transport;
  tensor traffic *within* a pool stays XLA collectives (SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import EngineConfig
from ..models.base import (
    ModelSpec,
    Params,
    init_params,
    unembed,
)
from ..ops.sampling import (
    SamplingParams,
    sample_tokens_with_logprobs,
)
from ..utils.hotpath import hot_path
from ..utils.tracing import LatencyStats
from .engine import _next_bucket, _pow2_buckets
from .types import GenerationRequest


@dataclasses.dataclass
class PrefillHandoff:
    """Everything a decode worker needs to resume a prefilled sequence.

    ``k``/``v`` are ``[L, T - kv_start, Hkv, Dh]`` numpy arrays in the
    KV-cache dtype: positions ``[kv_start, prompt_len)`` of the prompt
    (``kv_start`` is 0 for a full handoff — the common case). A nonzero
    ``kv_start`` is the prefix-aware delta handoff: the sender probed the
    decode pool's prefix cache (``WorkerServer._rpc_prefix_probe``) and
    omitted the page-aligned head the pool already holds. ``first_token``
    was sampled from the prefill logits with the request's own sampling
    params, so the decode side starts at position T with ``produced == 1``.
    """

    request_id: str
    prompt_len: int
    first_token: int
    k: np.ndarray
    v: np.ndarray
    first_logprob: float = 0.0       # untempered log p of first_token
    kv_start: int = 0                # prompt positions [0, kv_start) omitted

    def nbytes(self) -> int:
        return self.k.nbytes + self.v.nbytes


def trim_handoff(h: PrefillHandoff, kv_start: int) -> PrefillHandoff:
    """Delta form of ``h``: drop the KV for positions < ``kv_start`` (which
    the receiver's prefix cache already holds). No-op for kv_start <= 0."""
    if kv_start <= 0:
        return h
    if not 0 < kv_start < h.prompt_len:
        raise ValueError(
            f"kv_start {kv_start} out of range for prompt_len {h.prompt_len}")
    if h.kv_start:
        raise ValueError("handoff is already trimmed")
    return dataclasses.replace(
        h, k=h.k[:, kv_start:], v=h.v[:, kv_start:], kv_start=kv_start)


def handoff_to_wire(h: PrefillHandoff) -> Dict[str, Any]:
    """Marshal for the framed RPC plane (msgpack carries bytes natively)."""
    return {
        "request_id": h.request_id,
        "prompt_len": h.prompt_len,
        "first_token": h.first_token,
        "first_logprob": h.first_logprob,
        "kv_start": h.kv_start,
        "dtype": jnp.dtype(h.k.dtype).name,
        "shape": list(h.k.shape),
        "k": h.k.tobytes(),
        "v": h.v.tobytes(),
    }


def handoff_from_wire(d: Dict[str, Any]) -> PrefillHandoff:
    dtype = jnp.dtype(d["dtype"])           # resolves bfloat16 via ml_dtypes
    shape = tuple(int(s) for s in d["shape"])

    def _arr(b: Any) -> np.ndarray:
        if isinstance(b, str):              # JSON-codec fallback: base64
            import base64

            b = base64.b64decode(b)
        return np.frombuffer(b, dtype=dtype).reshape(shape)

    return PrefillHandoff(
        request_id=str(d["request_id"]),
        prompt_len=int(d["prompt_len"]),
        first_token=int(d["first_token"]),
        first_logprob=float(d.get("first_logprob", 0.0)),
        kv_start=int(d.get("kv_start", 0)),
        k=_arr(d["k"]),
        v=_arr(d["v"]),
    )


class PrefillEngine:
    """Prefill-only engine for the prefill pool of a disaggregated pair.

    Same bucketed batch assembly as ``Engine.generate`` (one compiled
    program per (batch, seq) bucket pair), but stops after the first sampled
    token: instead of seeding a decode loop it exports each request's KV
    state as a ``PrefillHandoff``.
    """

    def __init__(
        self,
        spec: ModelSpec,
        params: Optional[Params] = None,
        config: Optional[EngineConfig] = None,
        seed: int = 0,
        shard_fn=None,
        sp_mesh=None,    # optional: sequence-parallel ring-attention
                         # prefill (parallel/long_context.py) — the natural
                         # fit for a long-prompt prefill pool
    ) -> None:
        self.spec = spec.validate()
        self.config = config or EngineConfig()
        if params is None:
            params = init_params(spec, jax.random.key(seed))
        if shard_fn is not None:
            params = shard_fn(params)
        self.params = params
        self._rng = jax.random.key(seed + 1)

        cfg = self.config
        self.batch_buckets = _pow2_buckets(cfg.max_slots)
        # bucket rule must MATCH the decode pool's (ContinuousEngine): top
        # bucket is max_seq itself, so a prompt the decode pool would admit
        # is never silently truncated here (they share EngineConfig on a
        # disaggregated deploy)
        self.max_seq_len = min(cfg.max_seq_len, spec.max_seq_len)
        self.prefill_buckets = sorted(
            {b for b in cfg.prefill_buckets if b < self.max_seq_len}
            | {self.max_seq_len}
        )
        self.kv_dtype = jnp.dtype(cfg.kv_dtype)

        spec_ = self.spec
        from ..parallel.long_context import prefill_fn_for
        from .engine import _check_same_mesh

        if sp_mesh is not None:
            # no-op when params carry no mesh — covers pre-sharded
            # params passed without a shard_fn too
            _check_same_mesh(self.params, sp_mesh)
        fwd_prefill = prefill_fn_for(spec_, sp_mesh, self.prefill_buckets)

        @jax.jit
        def _prefill(params, tokens, seq_lens, sampling, key):
            hidden, ks, vs = fwd_prefill(spec_, params, tokens, seq_lens)
            b = tokens.shape[0]
            last = hidden[jnp.arange(b), seq_lens - 1]
            logits = unembed(spec_, params, last)
            # first token + its logprob sampled in-program (eager sampling
            # costs a chain of device dispatches — ruinous on
            # remote/tunnelled devices), packed into one [2, B] buffer
            first, lp = sample_tokens_with_logprobs(logits, sampling, key)
            first = jnp.stack(
                [first, jax.lax.bitcast_convert_type(lp, jnp.int32)])
            # [L, B, T, Hkv, Dh] -> [B, L, T, Hkv, Dh] so per-request slices
            # on the host are contiguous reads
            ks = jnp.swapaxes(ks, 0, 1).astype(self.kv_dtype)
            vs = jnp.swapaxes(vs, 0, 1).astype(self.kv_dtype)
            return first, ks, vs

        self._prefill = _prefill
        self.prefill_stats = LatencyStats()
        self._total_requests = 0
        self._total_prompt_tokens = 0
        self._total_handoff_bytes = 0

    def warmup(self, batch: Optional[int] = None) -> int:
        """Pre-compile one prefill program per (batch bucket × prefill
        bucket) (see ``Engine.warmup``). Returns the number of warmup
        prefills run."""
        sizes = [batch] if batch else self.batch_buckets
        runs = 0
        for n in sizes:
            for tb in self.prefill_buckets:
                prompt_len = min(tb, self.max_seq_len - 1)
                self.prefill([
                    GenerationRequest(prompt=[1] * prompt_len,
                                      max_new_tokens=1,
                                      request_id=f"warmup-{n}-{tb}-{i}")
                    for i in range(n)
                ])
                runs += 1
        return runs

    @hot_path
    def prefill(self, requests: List[GenerationRequest]) -> List[PrefillHandoff]:
        """Run one bucketed prefill batch; one handoff per request."""
        if not requests:
            return []
        if min(len(r.prompt) for r in requests) < 1:
            raise ValueError("empty prompt")
        self._total_requests += len(requests)
        n = len(requests)
        bb = _next_bucket(n, self.batch_buckets)
        # same sliding-window policy as ContinuousEngine admission: overlong
        # prompts keep their tail, capped so the decode pool has ≥1 position
        max_keep = self.max_seq_len - 1
        tb = _next_bucket(
            min(max(len(r.prompt) for r in requests), max_keep),
            self.prefill_buckets,
        )

        tokens = np.zeros((bb, tb), dtype=np.int32)
        seq_lens = np.ones((bb,), dtype=np.int32)
        temps = np.zeros((bb,), dtype=np.float32)
        top_k = np.zeros((bb,), dtype=np.int32)
        top_p = np.ones((bb,), dtype=np.float32)
        min_p = np.zeros((bb,), dtype=np.float32)
        for i, r in enumerate(requests):
            p = r.prompt[-min(tb, max_keep):]      # overlong: keep the tail
            tokens[i, : len(p)] = p
            seq_lens[i] = len(p)
            temps[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            min_p[i] = r.min_p
        sampling = SamplingParams(
            jnp.asarray(temps), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(min_p),
        )

        t0 = time.perf_counter()
        self._rng, k0 = jax.random.split(self._rng)
        first_dev, ks, vs = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(seq_lens),
            sampling, k0,
        )
        # graftlint: ok[host-sync-hot-path] ONE first-token read per prefill batch
        fp = np.asarray(first_dev)                 # [2, bb]: tokens; lp bits
        first = fp[0]
        first_lps = fp[1].view(np.float32)
        # graftlint: ok[host-sync-hot-path] handoff export IS a device→host bulk copy by design: the KV ships to the decode worker
        ks_np = np.asarray(jax.device_get(ks))     # [bb, L, tb, Hkv, Dh]
        # graftlint: ok[host-sync-hot-path] second half of the same handoff export
        vs_np = np.asarray(jax.device_get(vs))
        self.prefill_stats.add(time.perf_counter() - t0)

        out: List[PrefillHandoff] = []
        for i, r in enumerate(requests):
            t = int(seq_lens[i])
            # copy(): frombuffer on the receive side needs C-contiguous data,
            # and the slice must not pin the full padded batch buffer alive
            h = PrefillHandoff(
                request_id=r.request_id or f"prefill-{self._total_requests}-{i}",
                prompt_len=t,
                first_token=int(first[i]),
                first_logprob=float(first_lps[i]),
                k=ks_np[i, :, :t].copy(),                     # [L, T, Hkv, Dh]
                v=vs_np[i, :, :t].copy(),
            )
            self._total_prompt_tokens += t
            self._total_handoff_bytes += h.nbytes()
            out.append(h)
        return out

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "role": "prefill",
            "total_requests": self._total_requests,
            "total_prompt_tokens": self._total_prompt_tokens,
            "total_handoff_bytes": self._total_handoff_bytes,
            "prefill": self.prefill_stats.snapshot(),
        }
