"""Token sampling: greedy, temperature, top-k, top-p — all static-shape and
jit/scan-safe so the whole decode loop stays on-device.

The knobs are carried in a ``SamplingParams`` pytree of arrays (not Python
scalars), so one compiled decode program serves every request mix: greedy is
temperature==0, top-k off is k==vocab, top-p off is p==1. No recompilation
when a request changes its sampling settings.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-slot sampling knobs, each [B] fp32/int32 arrays."""

    temperature: jnp.ndarray   # 0.0 => greedy
    top_k: jnp.ndarray         # 0 or >= vocab => disabled
    top_p: jnp.ndarray         # 1.0 => disabled
    min_p: jnp.ndarray = None  # 0.0 => disabled; keep p >= min_p * p_max

    @classmethod
    def make(cls, batch: int, temperature=0.0, top_k=0, top_p=1.0,
             min_p=0.0) -> "SamplingParams":
        full = lambda v, dt: jnp.full((batch,), v, dtype=dt)
        return cls(full(temperature, jnp.float32), full(top_k, jnp.int32),
                   full(top_p, jnp.float32), full(min_p, jnp.float32))

    def min_p_or_zeros(self) -> jnp.ndarray:
        """min_p defaults to None so older positional constructions keep
        working; sampling treats None as disabled."""
        if self.min_p is None:
            return jnp.zeros_like(self.temperature)
        return self.min_p


def _mask_topk_topp(scaled: jnp.ndarray, params: SamplingParams
                    ) -> jnp.ndarray:
    """Apply top-k and top-p masks to tempered logits (three O(V log V)
    sorts — only worth running when some row actually uses the knobs)."""
    b, v = scaled.shape
    # ---- top-k mask: keep the k highest (temperature preserves order, so
    # this is identical on raw or scaled logits)
    k = jnp.where(params.top_k <= 0, v, params.top_k)            # [B]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]             # [B, V]
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(k - 1, 0, v - 1)[:, None], axis=-1
    )                                                            # [B, 1]
    keep_topk = scaled >= kth

    # ---- top-p (nucleus) mask: smallest prefix of sorted tempered probs
    # covering p
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    # token ranks: position of each logit in the descending sort
    ranks = jnp.argsort(jnp.argsort(-scaled, axis=-1), axis=-1)  # [B, V]
    # keep ranks whose cumulative prob (exclusive) is < p  => always keeps rank 0
    cum_excl = cum - probs_sorted
    keep_sorted = cum_excl < params.top_p[:, None]
    keep_topp = jnp.take_along_axis(keep_sorted, ranks, axis=-1)

    # ---- min-p mask: keep tokens whose tempered prob is at least
    # min_p * max prob. Reuses the sorted softmax above: p_max is its first
    # column and per-token probs come back through the same ranks gather —
    # no second softmax on the decode hot path. Clamped to [0, 1]: an
    # out-of-range client value must not mask the argmax itself (min_p>1
    # would -inf the whole row and sample uniform noise).
    minp = jnp.clip(params.min_p_or_zeros(), 0.0, 1.0)
    probs = jnp.take_along_axis(probs_sorted, ranks, axis=-1)
    keep_minp = (minp[:, None] <= 0.0) | \
        (probs >= minp[:, None] * probs_sorted[:, :1])
    return jnp.where(keep_topk & keep_topp & keep_minp, scaled, -jnp.inf)


def _masked_scaled_logits(logits: jnp.ndarray,
                          params: SamplingParams) -> jnp.ndarray:
    """Temper then mask: the shared front half of every sampling path
    ([N, V] logits, [N] params). One definition so the distribution the
    speculative engine verifies against is bit-identical to the one
    ``sample_tokens`` draws from — including the temperature clamp.

    The mask step costs three [N, V] sorts, so it hides behind a
    ``lax.cond``: the common greedy / pure-temperature batch skips the
    sorts entirely at runtime (one compiled program either way — the
    branch predicate is data).
    """
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp
    needs_mask = (jnp.any(params.top_k > 0) | jnp.any(params.top_p < 1.0)
                  | jnp.any(params.min_p_or_zeros() > 0.0))
    return jax.lax.cond(
        needs_mask,
        lambda s: _mask_topk_topp(s, params),
        lambda s: s,
        scaled,
    )


def masked_sampling_probs(logits: jnp.ndarray,
                          params: SamplingParams) -> jnp.ndarray:
    """Tempered, top-k/top-p/min-p-masked, renormalized probabilities.

    This is THE sampling distribution (what ``sample_tokens`` draws from),
    materialized — the speculative engine's acceptance test needs p and q
    as explicit distributions, and masking both with the same request knobs
    makes rejection sampling exact for the knob-modified target
    distribution (VERDICT r1 item 6), not just for plain temperature.

    ``logits`` is [B, V] or [B, P, V] (P scoring positions per row, each
    masked with its row's knobs); params are [B]. Greedy rows (temp 0)
    come back near-one-hot at the argmax — callers keep their explicit
    argmax path for exactness.
    """
    lg = logits.astype(jnp.float32)
    squeeze = lg.ndim == 2
    if squeeze:
        lg = lg[:, None, :]
    b, p, v = lg.shape
    rep = lambda x: jnp.repeat(x, p, axis=0)
    flat = SamplingParams(rep(params.temperature), rep(params.top_k),
                          rep(params.top_p), rep(params.min_p_or_zeros()))
    masked = _masked_scaled_logits(lg.reshape(b * p, v), flat)
    probs = jax.nn.softmax(masked, axis=-1).reshape(b, p, v)
    return probs[:, 0] if squeeze else probs


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] fp32
    params: SamplingParams,
    key: jax.Array,
) -> jnp.ndarray:
    """Sample one token per row. Returns [B] int32.

    Strategy composition: temperature scales, then top-k and top-p masks,
    then a Gumbel-max draw — which avoids materializing a renormalized
    distribution. Greedy rows (temperature 0) take an argmax on the
    *masked* logits, so greedy + top-k interact correctly.

    The mask step costs three [B, V] sorts, so it hides behind a
    ``lax.cond``: the common greedy / pure-temperature batch skips the
    sorts entirely at runtime (one compiled program either way — the
    branch predicate is data).
    """
    b, v = logits.shape
    logits = logits.astype(jnp.float32)

    # temperature FIRST (HF semantics): nucleus membership is judged on
    # the tempered distribution, so high temperature widens the nucleus
    masked = _masked_scaled_logits(logits, params)

    # ---- Gumbel-max draw on the masked tempered logits
    gumbel = -jnp.log(-jnp.log(jax.random.uniform(key, (b, v), minval=1e-20, maxval=1.0)))
    stochastic = jnp.argmax(masked + gumbel, axis=-1)
    greedy = jnp.argmax(masked, axis=-1)
    return jnp.where(params.temperature <= 0.0, greedy, stochastic).astype(jnp.int32)


def sample_tokens_with_logprobs(
    logits: jnp.ndarray,        # [B, V] fp32
    params: SamplingParams,
    key: jax.Array,
) -> tuple:
    """``sample_tokens`` plus the chosen token's UNTEMPERED log-probability
    ([B] fp32) — the quantity scoring/confidence APIs report (log p under
    the model, independent of the sampling knobs used to pick the token)."""
    toks = sample_tokens(logits, params, key)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen = jnp.take_along_axis(logp, toks[:, None].astype(jnp.int32),
                                 axis=-1)[:, 0]
    return toks, chosen
