"""Response cache tests.

Covers the reference's kvstore test intent (``tests/test_kvstore.py``) —
including the API surface those tests expected but the reference never
implemented (close/item access/context manager — SURVEY.md §4).
"""

import time

import pytest

from distributed_inference_engine_tpu.serving.cache import (
    EvictionPolicy,
    ResponseCache,
    KVStore,
    create_kv_store,
)


def test_basic_set_get_delete():
    c = ResponseCache(max_size=10)
    c.set("a", 1)
    c.set("b", {"x": [1, 2]})
    assert c.get("a") == 1
    assert c.get("b") == {"x": [1, 2]}
    assert c.get("missing") is None
    assert c.get("missing", 42) == 42
    assert c.delete("a") is True
    assert c.delete("a") is False
    assert "a" not in c
    assert "b" in c


def test_item_access_and_context_manager():
    with ResponseCache(max_size=4) as c:
        c["k"] = "v"
        assert c["k"] == "v"
        del c["k"]
        with pytest.raises(KeyError):
            c["k"]
        with pytest.raises(KeyError):
            del c["nope"]
    # closed on exit
    with pytest.raises(RuntimeError):
        c.set("x", 1)


def test_ttl_expiry():
    c = ResponseCache(max_size=10, default_ttl=0.05)
    c.set("short", 1)
    c.set("long", 2, ttl=10.0)
    c.set("forever", 3, ttl=None)  # explicit None still uses default
    assert c.get("short") == 1
    time.sleep(0.07)
    assert c.get("short") is None
    assert c.get("long") == 2
    stats = c.get_stats()
    assert stats["expirations"] >= 1


def test_len_sweeps_expired():
    c = ResponseCache(max_size=10)
    c.set("a", 1, ttl=0.01)
    c.set("b", 2)
    time.sleep(0.03)
    assert len(c) == 1


def test_lru_eviction_order():
    c = ResponseCache(max_size=3, policy="lru")
    c.set("a", 1)
    c.set("b", 2)
    c.set("c", 3)
    c.get("a")          # refresh a → b is now least recent
    c.set("d", 4)       # evicts b
    assert "b" not in c
    assert all(k in c for k in ("a", "c", "d"))
    assert c.get_stats()["evictions"] == 1


def test_lfu_eviction():
    c = ResponseCache(max_size=3, policy=EvictionPolicy.LFU)
    c.set("a", 1)
    c.set("b", 2)
    c.set("c", 3)
    for _ in range(3):
        c.get("a")
    c.get("b")
    c.set("d", 4)       # c has 0 accesses → evicted
    assert "c" not in c
    assert all(k in c for k in ("a", "b", "d"))


def test_fifo_eviction():
    c = ResponseCache(max_size=3, policy="fifo")
    c.set("a", 1)
    c.set("b", 2)
    c.set("c", 3)
    c.get("a")          # access must NOT save "a" under FIFO
    c.set("d", 4)
    assert "a" not in c
    assert all(k in c for k in ("b", "c", "d"))


def test_batch_ops():
    c = ResponseCache(max_size=10)
    c.batch_set({"a": 1, "b": 2, "c": 3})
    out = c.batch_get(["a", "c", "zz"])
    assert out == {"a": 1, "c": 3}


def test_stats_hit_rate():
    c = ResponseCache(max_size=10)
    c.set("a", 1)
    c.get("a")
    c.get("a")
    c.get("miss")
    s = c.get_stats()
    assert s["hits"] == 2 and s["misses"] == 1
    assert abs(s["hit_rate"] - 2 / 3) < 1e-9


def test_clear_and_overwrite():
    c = ResponseCache(max_size=10)
    c.set("a", 1)
    c.set("a", 2)
    assert c.get("a") == 2
    c.set("b", 1)
    assert c.clear() == 2
    assert len(c) == 0


def test_type_round_trips():
    c = ResponseCache(max_size=10)
    values = [1, 1.5, "s", b"bytes", [1, 2], {"k": "v"}, (1, 2), None, True]
    for i, v in enumerate(values):
        c.set(f"k{i}", v)
    for i, v in enumerate(values):
        assert c.get(f"k{i}", "MISSING") == v


def test_aliases():
    assert KVStore is ResponseCache
    assert create_kv_store is ResponseCache


def test_eviction_prefers_expired():
    c = ResponseCache(max_size=2, policy="lru")
    c.set("fresh", 1)
    c.set("stale", 2, ttl=0.01)
    time.sleep(0.03)
    c.set("new", 3)     # stale is expired → evicted even though fresh is LRU
    assert "fresh" in c and "new" in c


def test_thread_safety_smoke():
    import threading

    c = ResponseCache(max_size=64)
    errors = []

    def worker(tid):
        try:
            for i in range(500):
                c.set(f"{tid}-{i % 70}", i)
                c.get(f"{tid}-{(i + 1) % 70}")
                len(c)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors


def test_closed_cache_rejects_all_reads():
    c = ResponseCache(max_size=4)
    c.set("k", 1)
    c.close()
    for op in (lambda: "k" in c, lambda: len(c), lambda: c.keys(), lambda: c.get("k")):
        with pytest.raises(RuntimeError):
            op()


def test_expiry_during_eviction_counts_as_expiration():
    c = ResponseCache(max_size=2)
    c.set("stale", 1, ttl=0.01)
    c.set("fresh", 2)
    time.sleep(0.03)
    c.set("new", 3)
    s = c.get_stats()
    assert s["expirations"] == 1 and s["evictions"] == 0


# ------------------------------------------------------------- persistence


def test_save_load_roundtrip_entries_and_ttls(tmp_path):
    """VERDICT r1 item 9 / reference README's declared 'optional
    persistence': a restart round-trips entries, and TTLs persist as
    REMAINING time (monotonic created_at can't cross processes)."""
    p = str(tmp_path / "cache.pkl")
    c = ResponseCache(max_size=10)
    c.set("plain", {"tokens": [1, 2, 3]})
    c.set("ttl", "v", ttl=30.0)
    c.set("dead", "x", ttl=0.01)
    time.sleep(0.05)                      # "dead" expires before save
    assert c.save(p) == 2

    c2 = ResponseCache(max_size=10)
    assert c2.load(p) == 2
    assert c2.get("plain") == {"tokens": [1, 2, 3]}
    assert c2.get("ttl") == "v"
    assert c2.get("dead") is None
    # remaining TTL carried over: well under the original 30 s
    e = c2._entries["ttl"]
    assert e.ttl is not None and 25.0 < e.ttl <= 30.0
    # no-TTL entry stays immortal
    assert c2._entries["plain"].ttl is None


def test_load_respects_capacity_and_overwrites(tmp_path):
    p = str(tmp_path / "cache.pkl")
    big = ResponseCache(max_size=10)
    for i in range(6):
        big.set(f"k{i}", i)
    big.save(p)
    small = ResponseCache(max_size=4)
    small.set("k0", "old")
    small.load(p)
    assert len(small) <= 4                # capacity enforced during load
    assert small.get("k5") == 5           # newest snapshot entries survive
    assert small.get("k0") != "old" or small.get("k0") is None


def test_save_is_atomic_over_existing_snapshot(tmp_path):
    p = str(tmp_path / "cache.pkl")
    c = ResponseCache()
    c.set("a", 1)
    c.save(p)
    c.set("b", 2)
    c.save(p)                             # overwrite in place
    c2 = ResponseCache()
    assert c2.load(p) == 2


def test_snapshot_json_tuple_keys_and_collider_dicts(tmp_path):
    """JSON snapshots (the non-executable default): tuple keys round-trip
    via the tagged encoding, and a VALUE that happens to be a dict shaped
    like the tag (single key "__tuple__" or "__esc__") is escaped so it
    comes back as the same dict, not silently converted to a tuple."""
    p = str(tmp_path / "snap.json")
    c = ResponseCache()
    key = ("m", "1.0", (1, 2, 3), 8)
    c.set(key, {"tokens": [7], "inner": ("a", "b")})
    c.set("collider", {"__tuple__": [1, 2]})
    c.set("collider2", {"__esc__": {"x": 1}})
    c.save(p)
    with open(p, "rb") as f:
        assert f.read(1) == b"{"              # JSON, not pickle
    c2 = ResponseCache()
    assert c2.load(p) == 3
    assert c2.get(key) == {"tokens": [7], "inner": ("a", "b")}
    assert c2.get("collider") == {"__tuple__": [1, 2]}
    assert c2.get("collider2") == {"__esc__": {"x": 1}}


def test_snapshot_pickle_requires_opt_in(tmp_path):
    """Unpickling executes code from the file: loading a pickle snapshot
    demands an explicit allow_pickle=True acknowledgement of the trust
    boundary (ADVICE r2), and non-JSON payloads demand format='pickle'."""
    import pytest

    p = str(tmp_path / "snap.bin")
    c = ResponseCache()
    c.set("k", {1, 2, 3})                     # a set is not JSON-shaped
    with pytest.raises(TypeError, match="pickle"):
        c.save(p)                             # JSON default refuses
    c.save(p, format="pickle")
    c2 = ResponseCache()
    with pytest.raises(ValueError, match="allow_pickle"):
        c2.load(p)
    assert c2.load(p, allow_pickle=True) == 1
    assert c2.get("k") == {1, 2, 3}
