"""Speculative decoding tests (engine/speculative.py).

Correctness bar: greedy speculative output is TOKEN-FOR-TOKEN the target
engine's own greedy chain for any draft and any k — speculation may only
change latency, never content. Acceptance math is validated with
draft == target (everything must be accepted)."""

import jax
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig, ModelConfig
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.speculative import (
    SpeculativeEngine,
)
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import engine_from_config
from distributed_inference_engine_tpu.models.base import init_params
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=128)
DRAFT = llama_spec("llama-tiny", max_seq_len=128, n_layers=2, d_model=128,
                   n_heads=4, n_kv_heads=2, d_ff=256)


def _cfg():
    return EngineConfig(max_slots=4, max_seq_len=128)


def _reqs():
    return [
        GenerationRequest(prompt=[1, 2, 3, 4, 5], max_new_tokens=16,
                          temperature=0.0, request_id="a"),
        GenerationRequest(prompt=[9, 8, 7], max_new_tokens=12,
                          temperature=0.0, request_id="b"),
    ]


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.key(0))


@pytest.mark.parametrize("k", [1, 3, 4])
def test_greedy_speculative_matches_plain_engine(params, k):
    base = {r.request_id: r.tokens
            for r in Engine(SPEC, params=params, config=_cfg()
                            ).generate(_reqs())}
    se = SpeculativeEngine(SPEC, DRAFT, params=params, config=_cfg(),
                           speculate_k=k)
    out = {r.request_id: r.tokens for r in se.generate(_reqs())}
    assert out == base


def test_identical_draft_accepts_everything(params):
    k, n = 4, 20
    se = SpeculativeEngine(SPEC, SPEC, params=params, draft_params=params,
                           config=_cfg(), speculate_k=k)
    se.generate([GenerationRequest(prompt=[1, 2, 3, 4, 5],
                                   max_new_tokens=n, temperature=0.0)])
    m = se.get_metrics()
    # an identical draft never suffers a REAL rejection — the only loss
    # is the final round's clip at max_new_tokens, at most k-1 proposals.
    # Derive the bound from the observed round count instead of a fixed
    # 0.95: k=4, n=20 legitimately lands on 15/16 = 0.9375 accepted.
    rounds = m["rounds"]
    proposed = rounds * k
    assert m["draft_acceptance_rate"] >= (proposed - (k - 1)) / proposed
    # full-acceptance throughput: k+1 tokens per round until the clip
    rounds_ceiling = -(-n // (k + 1)) + 1
    assert rounds <= rounds_ceiling
    assert m["tokens_per_round"] >= n / rounds_ceiling


def test_eos_respected(params):
    # find the greedy chain, then set eos to its third token
    base = Engine(SPEC, params=params, config=_cfg()).generate(
        [GenerationRequest(prompt=[1, 2, 3, 4, 5], max_new_tokens=10,
                           temperature=0.0)])[0].tokens
    eos = base[2]
    se = SpeculativeEngine(SPEC, SPEC, params=params, draft_params=params,
                           config=_cfg(), speculate_k=4)
    out = se.generate([GenerationRequest(prompt=[1, 2, 3, 4, 5],
                                         max_new_tokens=10,
                                         temperature=0.0, eos_id=eos)])[0]
    assert out.tokens == base[:3]
    assert out.finish_reason == "stop"


def test_sampled_mode_runs_and_respects_max_new(params):
    se = SpeculativeEngine(SPEC, DRAFT, params=params, config=_cfg(),
                           speculate_k=3, seed=7)
    outs = se.generate([GenerationRequest(prompt=[4, 5, 6],
                                          max_new_tokens=9,
                                          temperature=0.9,
                                          request_id=f"s{i}")
                        for i in range(3)])
    for r in outs:
        assert len(r.tokens) == 9
        assert all(0 <= t < SPEC.vocab_size for t in r.tokens)


def test_topk1_sampled_matches_greedy_chain(params):
    """Knob exactness (VERDICT r1 item 6): top_k=1 with temperature > 0
    makes the knob-modified target distribution one-hot at the argmax, so
    speculative output must be deterministically the same chain the static
    engine produces for the same knobs — for any draft (accepted proposals
    in-support, rejections resampled from the one-hot residual)."""
    req = lambda: GenerationRequest(prompt=[1, 2, 3, 4, 5],
                                    max_new_tokens=14, temperature=0.8,
                                    top_k=1)
    base = Engine(SPEC, params=params, config=_cfg()).generate(
        [req()])[0].tokens
    se = SpeculativeEngine(SPEC, DRAFT, params=params, config=_cfg(),
                           speculate_k=3, seed=11)
    assert se.generate([req()])[0].tokens == base


def test_topp_masks_target_support(params):
    """A tiny top_p must confine sampled output to the nucleus: every
    emitted token has to be one the static sampler could emit. Checked
    against the masked target distribution position by position."""
    import jax.numpy as jnp

    from distributed_inference_engine_tpu.models.base import (
        forward_prefill, unembed,
    )
    from distributed_inference_engine_tpu.ops.sampling import (
        SamplingParams, masked_sampling_probs,
    )

    se = SpeculativeEngine(SPEC, DRAFT, params=params, config=_cfg(),
                           speculate_k=3, seed=3)
    prompt = [1, 2, 3, 4, 5]
    knobs = dict(temperature=0.9, top_p=0.3)
    out = se.generate([GenerationRequest(prompt=prompt, max_new_tokens=8,
                                         **knobs)])[0].tokens
    sp = SamplingParams.make(1, **knobs)
    ctx = list(prompt)
    for tok in out:
        toks = jnp.asarray([ctx], jnp.int32)
        lens = jnp.asarray([len(ctx)], jnp.int32)
        hid, _, _ = forward_prefill(SPEC, params, toks, lens)
        logits = unembed(SPEC, params, hid[:, len(ctx) - 1])
        probs = masked_sampling_probs(logits, sp)
        assert float(probs[0, tok]) > 0.0, \
            f"token {tok} outside the top-p nucleus"
        ctx.append(tok)


def test_vocab_mismatch_rejected(params):
    bad = llama_spec("llama-tiny", max_seq_len=128, vocab_size=999)
    with pytest.raises(ValueError, match="vocab"):
        SpeculativeEngine(SPEC, bad, params=params, config=_cfg())


def test_engine_from_config_speculative():
    cfg = ModelConfig(
        name="s", architecture="llama", dtype="float32", max_seq_len=64,
        max_batch_size=2,
        metadata={"size": "llama-tiny", "speculative": 3,
                  "draft_size": "llama-tiny"},
    )
    eng = engine_from_config(cfg)
    assert isinstance(eng, SpeculativeEngine)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=5)])
    assert len(out[0].tokens) == 5
    assert eng.get_metrics()["speculate_k"] == 3


def test_truncated_draft_greedy_parity_and_acceptance():
    """Draft = the target's own first layers (VERDICT r2 item 4): output
    stays token-for-token the target's greedy chain (the speculative
    invariant), and the shared structure yields nonzero acceptance even
    at random init — the property an independent random draft lacks."""
    from distributed_inference_engine_tpu.engine.speculative import (
        truncated_draft,
    )

    params = init_params(SPEC, jax.random.key(0))
    d_spec, d_params = truncated_draft(SPEC, params, 2)
    assert d_spec.n_layers == 2
    assert d_params["blocks"]["wq"].shape[0] == 2
    assert d_params["tok_emb"] is params["tok_emb"]       # shared, no copy
    eng = SpeculativeEngine(SPEC, d_spec, params=params,
                            draft_params=d_params, config=_cfg(),
                            speculate_k=3)
    ref = Engine(SPEC, params=params, config=_cfg())
    out_s = {r.request_id: r.tokens for r in eng.generate(_reqs())}
    out_r = {r.request_id: r.tokens for r in ref.generate(_reqs())}
    assert out_s == out_r
    assert eng.get_metrics()["draft_acceptance_rate"] > 0.0


def test_truncated_draft_quantized_tree():
    """QuantizedTensor leaves slice payload and scales together."""
    from distributed_inference_engine_tpu.engine.speculative import (
        truncated_draft,
    )
    from distributed_inference_engine_tpu.ops.quant import (
        quantize_params,
        QuantizedTensor,
    )

    qparams = quantize_params(SPEC, init_params(SPEC, jax.random.key(1)))
    d_spec, d_params = truncated_draft(SPEC, qparams, 3)
    wq = d_params["blocks"]["wq"]
    assert isinstance(wq, QuantizedTensor)
    assert wq.q.shape[0] == 3 and wq.s.shape[0] == 3
    with pytest.raises(ValueError, match="draft layers"):
        truncated_draft(SPEC, qparams, SPEC.n_layers)


def test_scale_top_blocks_eps0_matches_draft_logits():
    """eps=0 makes every block above n_shared an exact identity on the
    residual stream: full-model logits == truncated-draft logits, so
    greedy acceptance is exactly 1 — the sweep's ceiling anchor."""
    import numpy as np

    from distributed_inference_engine_tpu.engine.speculative import (
        scale_top_blocks,
        truncated_draft,
    )
    from distributed_inference_engine_tpu.models.base import (
        forward_train,
        init_params,
    )

    import jax.numpy as jnp

    params = init_params(SPEC, jax.random.key(9))
    d_spec, d_params = truncated_draft(SPEC, params, 1)
    tp = scale_top_blocks(SPEC, params, 1, 0.0)
    toks = jnp.asarray([[1, 2, 3, 4, 5, 6]], jnp.int32)
    lens = jnp.full((1,), 6, jnp.int32)
    full = np.asarray(forward_train(SPEC, tp, toks, lens))
    draft = np.asarray(forward_train(d_spec, d_params, toks, lens))
    np.testing.assert_allclose(full, draft, rtol=1e-5, atol=1e-5)

    # eps>0 must diverge (the construction is not degenerate)
    tp2 = scale_top_blocks(SPEC, params, 1, 0.5)
    full2 = np.asarray(forward_train(SPEC, tp2, toks, lens))
    assert np.abs(full2 - draft).max() > 1e-3


def test_scale_top_blocks_quantized_scales_only():
    """Quantized trees scale only the per-channel scale arrays — the
    payload is shared with the base tree (no second 8-GB copy)."""
    from distributed_inference_engine_tpu.engine.speculative import (
        scale_top_blocks,
    )
    from distributed_inference_engine_tpu.ops.quant import (
        random_quantized_params,
    )

    base = random_quantized_params(SPEC, jax.random.key(1))
    tp = scale_top_blocks(SPEC, base, 1, 0.25)
    assert tp["blocks"]["wo"].q is base["blocks"]["wo"].q
    import numpy as np

    s0 = np.asarray(base["blocks"]["wo"].s)
    s1 = np.asarray(tp["blocks"]["wo"].s)
    np.testing.assert_allclose(s1[:1], s0[:1])
    np.testing.assert_allclose(s1[1:], s0[1:] * 0.25)
