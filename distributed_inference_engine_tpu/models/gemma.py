"""Gemma family specs.

The family that stresses the spec axes the Llama shape doesn't: head_dim
decoupled from d_model/n_heads (``head_dim_override``), embeddings scaled by
sqrt(d_model) (``emb_scale``), RMSNorm weights stored as (w - 1)
(``norm_plus_one``), GeGLU MLP (gelu-activated gate), tied embeddings, and
multi-query attention on the 2B size.

Capability-extension beyond the reference (no real models exist in it —
SURVEY.md §0); "-tiny" keeps every quirk at CPU-test scale, including a
head_dim that d_model/n_heads would NOT produce.
"""

from __future__ import annotations

from .base import ModelSpec

_FAMILY = {
    # name: (layers, d_model, heads, kv_heads, head_dim, d_ff, vocab, max_seq)
    "gemma-7b": (28, 3072, 16, 16, 256, 24576, 256000, 8192),
    "gemma-2b": (18, 2048, 8, 1, 256, 16384, 256000, 8192),
    "gemma-tiny": (4, 256, 4, 1, 32, 512, 1024, 512),
}


def gemma_spec(size: str = "gemma-7b", **overrides) -> ModelSpec:
    if size not in _FAMILY:
        raise ValueError(
            f"unknown gemma size {size!r}; choose from {sorted(_FAMILY)}")
    layers, d_model, heads, kv_heads, head_dim, d_ff, vocab, max_seq = _FAMILY[size]
    base = dict(
        vocab_size=vocab,
        d_model=d_model,
        n_layers=layers,
        n_heads=heads,
        n_kv_heads=kv_heads,
        d_ff=d_ff,
        max_seq_len=max_seq,
        pos_emb="rope",
        norm="rmsnorm",
        mlp="geglu",
        use_bias=False,
        tie_embeddings=True,
        rope_theta=10000.0,
        norm_eps=1e-6,
        head_dim_override=head_dim,
        emb_scale=True,
        norm_plus_one=True,
    )
    base.update(overrides)
    return ModelSpec(**base).validate()
