"""Fused qkv / gate+up stacked-int4 payloads (ops.quant.fuse_block_weights,
r5) — the decode-profile lever "one kernel launch for gate+up" plus the
small-N attention projections (int8 profile: qkv at N∈{1024,4096} ran at
~48% of HBM peak; fused N=(H+2Hkv)·Dh escapes that regime).

Fusion is a build-time layout choice, never a numerics choice: the fused
tensor is an ordinary stacked QuantizedTensor whose matmul output columns
are exactly the members' outputs side by side.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.models.llama import llama_spec
from distributed_inference_engine_tpu.ops import quant
from distributed_inference_engine_tpu.ops.int4_matmul import set_kernel_mode


@pytest.fixture
def kernel_on():
    set_kernel_mode("on")
    yield
    set_kernel_mode("auto")


def _spec():
    return llama_spec("llama-tiny", max_seq_len=64).replace(
        d_model=256, d_ff=256, n_heads=4, n_kv_heads=2, dtype="float32")


def _params(spec):
    return quant.random_quantized_params(spec, jax.random.key(0), bits=4)


def test_fuse_builds_expected_keys_and_shapes(kernel_on):
    spec = _spec()
    fused = quant.fuse_block_weights(_params(spec))["blocks"]
    assert "w_qkv" in fused and "w_gate_up" in fused
    for gone in ("wq", "wk", "wv", "w_gate", "w_up"):
        assert gone not in fused
    L, D, F = spec.n_layers, spec.d_model, spec.d_ff
    n_qkv = (spec.n_heads + 2 * spec.n_kv_heads) * spec.head_dim
    assert fused["w_qkv"].q.shape == (L, D // 2, n_qkv)
    assert fused["w_qkv"].s.shape == (L, 1, n_qkv)
    assert fused["w_gate_up"].q.shape == (L, D // 2, 2 * F)
    # untouched members survive
    assert fused["w_down"].q.shape == (L, F // 2, D)


def test_fuse_is_identity_when_kernel_off():
    set_kernel_mode("off")
    try:
        params = _params(_spec())
        assert quant.fuse_block_weights(params) is params
    finally:
        set_kernel_mode("auto")


def test_fuse_is_idempotent(kernel_on):
    params = _params(_spec())
    once = quant.fuse_block_weights(params)
    assert quant.fuse_block_weights(once) is once


def test_fuse_skipped_for_int8(kernel_on):
    params = quant.random_quantized_params(_spec(), jax.random.key(0), bits=8)
    assert quant.fuse_block_weights(params) is params


def test_fuse_skipped_when_biases_present(kernel_on):
    params = _params(_spec())
    blocks = dict(params["blocks"])
    blocks["bq"] = jnp.zeros((2, 256))
    fused = quant.fuse_block_weights({**params, "blocks": blocks})["blocks"]
    assert "w_qkv" not in fused and "wq" in fused
    assert "w_gate_up" in fused          # mlp group fuses independently


def test_fused_forward_matches_unfused(kernel_on):
    """Same quantized values, same scales, concat-then-split: each fused
    output column sums the same products as its unfused counterpart, so
    the trees agree to dot-reassociation noise (XLA tiles the wider-N
    dot differently — bitwise equality does NOT hold, tolerance does)."""
    from distributed_inference_engine_tpu.models.base import forward_prefill

    spec = _spec()
    params = _params(spec)
    fused = quant.fuse_block_weights(params)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, spec.vocab_size, size=(2, 16)))
    lens = jnp.asarray([16, 9])
    h_ref, k_ref, v_ref = forward_prefill(spec, params, tokens, lens)
    h_got, k_got, v_got = forward_prefill(spec, fused, tokens, lens)
    np.testing.assert_allclose(np.asarray(h_got), np.asarray(h_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(k_got), np.asarray(k_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(v_got), np.asarray(v_ref),
                               rtol=1e-4, atol=1e-4)
