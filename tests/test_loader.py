"""Checkpoint loader tests: fabricate a tiny HF-named checkpoint on disk and
round-trip it (zero-egress environment — no downloads, SURVEY.md §5
checkpoint row)."""

import json

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_engine_tpu.models.base import ModelSpec, init_params, forward_train
from distributed_inference_engine_tpu.models.loader import (
    load_checkpoint,
    save_checkpoint_gpt2,
    spec_from_hf_config,
)

TINY_GPT2 = ModelSpec(
    vocab_size=64, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4, d_ff=64,
    max_seq_len=32, pos_emb="learned", norm="layernorm", mlp="gelu",
    use_bias=True, tie_embeddings=True, dtype="float32",
)


def test_gpt2_round_trip(tmp_path):
    params = init_params(TINY_GPT2, jax.random.key(0))
    save_checkpoint_gpt2(str(tmp_path), params, TINY_GPT2)
    loaded = load_checkpoint(str(tmp_path), TINY_GPT2)
    # same tree structure, same values
    # jax.tree.leaves_with_path is missing on older jax; the tree_util
    # spelling exists on every version in support
    from jax.tree_util import tree_leaves_with_path
    flat1 = tree_leaves_with_path(params)
    flat2 = tree_leaves_with_path(loaded)
    assert len(flat1) == len(flat2)
    for (p1, a1), (p2, a2) in zip(sorted(flat1, key=lambda x: str(x[0])),
                                  sorted(flat2, key=lambda x: str(x[0]))):
        assert str(p1) == str(p2)
        np.testing.assert_allclose(np.asarray(a1), np.asarray(a2), rtol=1e-6)
    # and the loaded params compute identical logits
    toks = jnp.asarray([[1, 2, 3, 4]], dtype=jnp.int32)
    l1 = forward_train(TINY_GPT2, params, toks, jnp.array([4]))
    l2 = forward_train(TINY_GPT2, loaded, toks, jnp.array([4]))
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-5)


def test_llama_mapping(tmp_path):
    """Fabricate HF-Llama-named tensors, check transpose + stacking."""
    from safetensors.numpy import save_file

    spec = ModelSpec(
        vocab_size=32, d_model=16, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=24,
        max_seq_len=32, pos_emb="rope", norm="rmsnorm", mlp="swiglu",
        use_bias=False, tie_embeddings=False, dtype="float32",
    )
    rs = np.random.RandomState(0)
    D, F, V = spec.d_model, spec.d_ff, spec.vocab_size
    Hd, Kd = spec.n_heads * spec.head_dim, spec.n_kv_heads * spec.head_dim
    raw = {
        "model.embed_tokens.weight": rs.randn(V, D).astype(np.float32),
        "model.norm.weight": np.ones(D, dtype=np.float32),
        "lm_head.weight": rs.randn(V, D).astype(np.float32),
    }
    for l in range(2):
        raw[f"model.layers.{l}.input_layernorm.weight"] = np.ones(D, np.float32)
        raw[f"model.layers.{l}.post_attention_layernorm.weight"] = np.ones(D, np.float32)
        raw[f"model.layers.{l}.self_attn.q_proj.weight"] = rs.randn(Hd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.k_proj.weight"] = rs.randn(Kd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.v_proj.weight"] = rs.randn(Kd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.o_proj.weight"] = rs.randn(D, Hd).astype(np.float32)
        raw[f"model.layers.{l}.mlp.gate_proj.weight"] = rs.randn(F, D).astype(np.float32)
        raw[f"model.layers.{l}.mlp.up_proj.weight"] = rs.randn(F, D).astype(np.float32)
        raw[f"model.layers.{l}.mlp.down_proj.weight"] = rs.randn(D, F).astype(np.float32)
    save_file(raw, str(tmp_path / "model.safetensors"))

    params = load_checkpoint(str(tmp_path), spec)
    assert params["blocks"]["wq"].shape == (2, D, Hd)       # stacked + transposed
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["wq"][1]),
        raw["model.layers.1.self_attn.q_proj.weight"].T,
        rtol=1e-6,
    )
    assert params["lm_head"].shape == (D, V)
    # loaded tree must run
    logits = forward_train(spec, params, jnp.asarray([[1, 2, 3]]), jnp.array([3]))
    assert logits.shape == (1, 3, V)
    assert np.isfinite(np.asarray(logits)).all()


def test_spec_from_hf_config(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "architectures": ["LlamaForCausalLM"],
        "vocab_size": 128256, "hidden_size": 4096, "num_hidden_layers": 32,
        "num_attention_heads": 32, "num_key_value_heads": 8,
        "intermediate_size": 14336, "max_position_embeddings": 8192,
        "rope_theta": 500000.0, "rms_norm_eps": 1e-5,
    }))
    spec = spec_from_hf_config(str(tmp_path))
    assert spec.n_kv_heads == 8 and spec.rope_theta == 500000.0
    assert spec.mlp == "swiglu" and spec.pos_emb == "rope"

    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "gpt2", "architectures": ["GPT2LMHeadModel"],
        "vocab_size": 50257, "n_embd": 768, "n_layer": 12, "n_head": 12,
        "n_positions": 1024,
    }))
    spec = spec_from_hf_config(str(tmp_path))
    assert spec.tie_embeddings and spec.use_bias and spec.norm == "layernorm"
