"""Mosaic (Pallas-TPU) matmul with in-register int4 unpack.

Closes the one SURVEY §2.2 "Pallas where XLA is insufficient" obligation
left open in round 3: packed-int4 weights through XLA's einsum decode at
1,584 tok/s vs int8's 3,661 at the 8B bs64 rung, because XLA materializes
the unpacked int8 operand in HBM — the decode step then streams the 2-byte
traffic AND the packed read. This kernel keeps the weight packed in HBM
and VMEM and unpacks nibbles in registers on the way into the MXU feed, so
HBM sees only the 0.5-byte/weight stream. (The reference has no analogue:
its "model" is an asyncio sleep, ``src/mock_models/fake_model.py:47``.)

Layout contract (``ops.quant.quantize_weight``): a ``[K, N]`` weight packs
SPLIT-HALF along the contraction axis into ``[K/2, N]`` int8 — source row
``k < K/2`` in the low nibble of byte row ``k``, row ``K/2 + k`` in the
high nibble. The matmul then decomposes into two contiguous-slice dots,

    y = x[:, :K/2] @ lo(P) + x[:, K/2:] @ hi(P),    P = packed bytes

with no stride-2 gather anywhere (an interleaved layout would need one on
either the activations or the unpacked weight — both Mosaic-hostile).

Grid: ``(M/bm, N/bn, K2/bk)``, k innermost ("arbitrary"), accumulating in
a VMEM f32 scratch; weight blocks stream exactly once per (m, n) tile, so
a bs64 decode step streams each weight byte exactly once. Nibble unpack is
3 VPU int32 ops + 2 converts per byte, overlapped with the MXU by Mosaic's
usual software pipeline.

Inside a layer scan the kernel must NOT take the scanned per-layer slice:
a pallas_call is an opaque custom call, so XLA materializes the slice as
a real HBM copy first (the r4 profile showed ~25% of the int4 step in
s8 dynamic-slice fusions — the 3,308 tok/s plateau). The stacked variant
(``_int4_matmul_stacked``) takes the whole ``[L, K/2, N]`` payload plus
the layer index as a scalar-prefetch argument; the grid's index_maps pick
block ``(layer, k, j)`` straight from the stacked array in HBM. Measured:
1,584 (XLA) → 3,308 (sliced kernel) → 4,254 tok/s (stacked kernel) vs
int8's 3,661 at the 8B bs64 rung.

r5 added (a) per-shape tuned blocks + engine-init payload fusion
(``ops.quant.fuse_block_weights``): 4,254 → 4,639 at bs64, and the
flagship moved to bs128 (5,315 tok/s — int4's freed HBM fits bs128 with
bf16 KV); and (b) tensor-parallel composition (mode "cp"): the kernel
rides a ``custom_partitioning`` op whose Shardy rule passes x pre-split
as (xlo, xhi) so both halves' K/2 axis and the payload's packed axis
share one reduction factor — the split-half layout then shards
COHERENTLY for row-parallel weights (each device's packed rows hold the
lo nibbles of exactly its xlo shard's columns and the hi nibbles of its
xhi shard's) and trivially for column-parallel, with no repacking and
no gather. Engines stamp "cp" onto their OWN int4 tensors when params
land sharded (``ops.quant.resolve_kernel_modes`` — per-engine scope;
the module-level mode below is only the process default / env
override).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed across jax versions (TPUCompilerParams -> CompilerParams)
_CompilerParams = getattr(pltpu, "TPUCompilerParams", None) or \
    pltpu.CompilerParams

# kernel dispatch mode (read at TRACE time):
#   auto      — use the kernel on a single-device TPU process (the bench /
#               single-chip serving deploys); XLA einsum path elsewhere.
#   cp        — multi-device (tp) path: the kernel rides a
#               ``custom_partitioning`` op with a Shardy rule, so GSPMD
#               partitions the opaque pallas_call instead of gathering
#               around it (r5; engines select this automatically when
#               their int4 params land sharded across devices).
#   on        — always, direct (interpreted off-TPU: CPU kernel tests)
#   off       — never
_MODE = os.environ.get("INT4_MATMUL_KERNEL", "auto")


def set_kernel_mode(mode: str) -> None:
    """"auto" | "cp" | "on" | "off" — see module docstring."""
    global _MODE
    if mode not in ("auto", "cp", "on", "off"):
        raise ValueError(f"bad int4 kernel mode {mode!r}")
    _MODE = mode


def kernel_mode() -> str:
    return _MODE


def _block_of(size: int, candidates: Tuple[int, ...]) -> Optional[int]:
    for b in candidates:
        if size % b == 0:
            return b
    return None


def _tensor_mode(w) -> str:
    """Effective kernel mode for one weight: the per-tensor stamp
    (``ops.quant.resolve_kernel_modes`` — tp engines mark their OWN int4
    tensors "cp" instead of flipping process state) or the module
    default."""
    return getattr(w, "kernel_mode", "") or _MODE


def _mode_engaged(mode: str = "") -> bool:
    """Mode/backend half of kernel eligibility (shared by the per-layer
    and stacked predicates): "on"/"cp" always, "auto" only on a
    single-device TPU process. ("cp" wraps the kernel in a
    custom_partitioning op so GSPMD can partition it — without that a
    pallas_call is opaque and tp-sharded weights would force a gather;
    engines stamp "cp" onto their int4 params when placement lands them
    multi-device.)"""
    mode = mode or _MODE
    if mode == "off":
        return False
    return mode in ("on", "cp") or (jax.default_backend() == "tpu"
                                    and len(jax.devices()) == 1)


def pattern_fits(pattern: str, x, k2: int) -> bool:
    """Structural half of kernel eligibility (shared with ``matmul_any``'s
    ``IndexedQuant`` routing): contraction on x's LAST axis and the
    weight's axis 0, out = x batch dims + N, x width = 2·K/2."""
    lhs, out = pattern.split("->")
    xs, ws = lhs.split(",")
    if len(ws) != 2 or not xs.endswith(ws[0]) or ws[0] in out \
            or ws[1] not in out:
        return False     # contraction must be x's LAST axis and w's axis 0
    if not out.endswith(ws[1]) or xs.replace(ws[0], "") + ws[1] != out:
        return False                    # out = x batch dims + N
    return x.shape[-1] == 2 * k2


def kernel_wants(pattern: str, x, w) -> bool:
    """True when the Mosaic kernel should take this einsum: mode allows
    it, the weight is an unstacked ``[K/2, N]`` payload contracted on its
    packed axis, and the shapes tile cleanly (K/2 and N divisible by the
    block candidates). Everything else falls back to the XLA path."""
    if not _mode_engaged(_tensor_mode(w)):
        return False
    if w.q.ndim != 2 or w.pack_axis % w.q.ndim != 0:
        return False                    # payload must be packed on axis 0
    k2, n = w.q.shape
    if not pattern_fits(pattern, x, k2):
        return False
    return (_block_of(k2, _K_BLOCKS) is not None
            and _block_of(n, _N_BLOCKS) is not None)


# preference order measured on v5e at the 8B decode shape ([64,4096] @
# [4096,14336]): bk1024/bn2048 runs 24.9 us/iter vs 82.5 at bk512/bn512 —
# bigger blocks amortize the per-block VPU unpack + loop overhead; the
# unpack STYLE (int32 shifts vs xor-bias) measured within noise of itself.
# int8-typed shifts don't compile on this Mosaic — keep the int32 widen.
_K_BLOCKS = (1024, 512, 256, 128)
_N_BLOCKS = (2048, 1024, 512, 256, 128)

# measured per-shape winners, (K/2, N) -> (bk, bn): the r5 tuning sweep
# (examples/int4_kernel_tune.py, v5e, M=64 decode tile, median of 5
# device-side timed passes) found no single block pair wins every shape —
# the 8B fused gate+up stream runs 601 GB/s at bk2048/bn1024 vs ~495 at
# the table default, and the fused-qkv shape actively pathologies at
# bn=2048 (168-336 GB/s vs 461 at bk1024/bn1024). Shapes not listed fall
# back to the preference tables above.
_TUNED_BLOCKS = {
    (2048, 6144): (1024, 1024),     # qkv fused     461 GB/s
    (2048, 4096): (512, 4096),      # wo / wq       449 GB/s
    (2048, 28672): (2048, 1024),    # gate+up fused 601 GB/s
    (7168, 4096): (512, 4096),      # w_down        532 GB/s
    (2048, 129024): (2048, 2048),   # padded lm_head 619 GB/s (vs 551 at
                                    # the table default; measured with a
                                    # 4x-stacked payload — a single-layer
                                    # stack is loop-INVARIANT in the tune
                                    # scan and XLA hoists the call)
}


def _blocks_for(k2: int, n: int) -> Tuple[Optional[int], Optional[int]]:
    bk, bn = _TUNED_BLOCKS.get((k2, n), (None, None))
    return (bk or _block_of(k2, _K_BLOCKS), bn or _block_of(n, _N_BLOCKS))


def _int4_matmul_2d(x, packed, scale, *, interpret: bool = False):
    """``[M, K] @ unpack([K/2, N]) * scale -> [M, N]`` (dtype of x) —
    the degenerate L=1 case of the stacked kernel (one code path, one
    set of tuning constants)."""
    k2, n = packed.shape
    return _int4_matmul_stacked(x, packed[None], scale.reshape(1, 1, n),
                                jnp.int32(0), interpret=interpret)


def int4_einsum_kernel(pattern: str, x, w):
    """``matmul_any``'s kernel path: flatten x's batch dims to M, run the
    2-D kernel, restore. ``kernel_wants(pattern, x, w)`` must hold.
    Mode "cp" routes through the GSPMD-partitionable wrapper — a
    quantized lm_head is tp-sharded on vocab (``parallel/sharding.py``),
    and feeding the sharded payload to the direct (opaque) pallas call
    would force GSPMD to gather it every step."""
    k2, n = w.q.shape
    lead = x.shape[:-1]
    xm = x.reshape(-1, x.shape[-1])
    interpret = jax.default_backend() != "tpu"
    if _tensor_mode(w) == "cp":
        y = _cp_stacked(interpret)(xm[:, :k2], xm[:, k2:], w.q[None],
                                   w.s.astype(jnp.float32).reshape(1, 1, n),
                                   jnp.zeros((1,), jnp.int32))
    else:
        y = _int4_matmul_2d(xm, w.q, w.s.astype(jnp.float32),
                            interpret=interpret)
    return y.reshape(lead + (n,))


# ------------------------------------------------- stacked (layer-indexed)


def stacked_kernel_wants(w) -> bool:
    """True when a layer-stacked ``[L, K/2, N]`` int4 payload should ride
    the scalar-prefetch kernel: the layer slice then happens INSIDE the
    pallas grid (the index_map picks block (layer, k, j) straight from
    HBM). Pulling the weight through the scan xs instead would make XLA
    materialize each layer's slice as a real HBM copy before the opaque
    custom call — measured at ~25% of the int4 decode step (r4 profile:
    ~230 ms of s8 dynamic-slice fusions per 930 ms of chunks)."""
    from .quant import QuantizedTensor

    if not isinstance(w, QuantizedTensor) \
            or not _mode_engaged(_tensor_mode(w)):
        return False
    if w.bits != 4 or w.q.ndim != 3 or w.pack_axis % (w.q.ndim - 1) != 0:
        return False                # per-layer slice must pack on axis 0
    _l, k2, n = w.q.shape
    return (_block_of(k2, _K_BLOCKS) is not None
            and _block_of(n, _N_BLOCKS) is not None)


def _kernel_stacked(l_ref, xlo_ref, xhi_ref, p_ref, s_ref, o_ref, acc_ref):
    del l_ref                       # consumed by the index_maps
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    p = p_ref[0].astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(jax.lax.shift_left(p, 28), 28)
    hi = jax.lax.shift_right_arithmetic(p, 4)
    dt = xlo_ref.dtype
    acc_ref[...] += (
        jnp.dot(xlo_ref[...], lo.astype(dt),
                preferred_element_type=jnp.float32)
        + jnp.dot(xhi_ref[...], hi.astype(dt),
                  preferred_element_type=jnp.float32))

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * s_ref[0]).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "bk", "bn"))
def _int4_matmul_stacked(x, packed, scale, layer, *, interpret: bool = False,
                         bk: Optional[int] = None, bn: Optional[int] = None):
    """``[M, K] @ unpack(packed[layer]) * scale[layer] -> [M, N]``;
    ``packed [L, K/2, N]`` stays whole in HBM — the grid's index_map
    selects the layer via scalar prefetch, so no slice is materialized.

    ``bk``/``bn`` override the block-size preference tables — the tuning
    surface ``examples/int4_kernel_tune.py`` sweeps on hardware; defaults
    are the measured winners."""
    m, kdim = x.shape
    nl, k2, n = packed.shape
    if kdim != 2 * k2:
        raise ValueError(f"x K={kdim} vs packed K/2={k2}")
    tbk, tbn = _blocks_for(k2, n)
    bk = bk or tbk
    bn = bn or tbn
    if bk is None or bn is None:
        raise ValueError(f"untileable shapes K/2={k2} N={n}")
    if k2 % bk or n % bn:
        # explicit overrides must divide: a flooring grid would silently
        # drop trailing K rows / leave output columns unwritten
        raise ValueError(f"blocks bk={bk} bn={bn} do not divide "
                         f"K/2={k2} N={n}")
    # activations tile at (16, 128) for bf16 — pad M up, slice back after.
    # bm tops out at 128 to keep the f32 accumulator block ≤1 MB alongside
    # the 2 MB double-buffered weight blocks
    bm = _block_of(m, (128, 64, 32, 16))
    if bm is None:
        bm = min(-(-m // 16) * 16, 128)
        x = jnp.pad(x, ((0, -m % bm), (0, 0)))
    mp = x.shape[0]

    grid = (mp // bm, n // bn, k2 // bk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k, l: (i, k)),
            pl.BlockSpec((bm, bk), lambda i, j, k, l: (i, k)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k, l: (l[0], k, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j, k, l: (l[0], 0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k, l: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )
    out = pl.pallas_call(
        _kernel_stacked,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((mp, n), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            # the int32 nibble-widening temporaries ([bk, bn] lo+hi) top
            # 16 MB at the prefill tile (bm=128, bn=2048) — past the
            # default scoped-vmem limit but well inside v5e's 128 MB
            # physical VMEM (measured: compiles + runs at 64 MB)
            vmem_limit_bytes=64 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * n * kdim,
            bytes_accessed=(k2 * n) + 2 * mp * kdim * (n // bn)
                           + mp * n * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(jnp.atleast_1d(layer).astype(jnp.int32),
      x[:, :k2], x[:, k2:], packed,
      scale.reshape(nl, 1, n))
    return out[:m] if mp != m else out


def int4_einsum_kernel_stacked(pattern: str, x, w, layer):
    """Stacked-kernel path for a layer-indexed weight (``IndexedQuant``):
    flatten x's batch dims to M, run the scalar-prefetch kernel against
    the WHOLE stacked payload, restore. Pattern must satisfy
    ``kernel_wants`` on the per-layer 2-D slice shape. Mode "cp" routes
    through the GSPMD-partitionable wrapper instead of the direct call."""
    _l, k2, n = w.q.shape
    lead = x.shape[:-1]
    xm = x.reshape(-1, x.shape[-1])
    interpret = jax.default_backend() != "tpu"
    if _tensor_mode(w) == "cp":
        y = _cp_stacked(interpret)(xm[:, :k2], xm[:, k2:], w.q,
                                   w.s.astype(jnp.float32),
                                   jnp.atleast_1d(layer).astype(jnp.int32))
    else:
        y = _int4_matmul_stacked(xm, w.q, w.s.astype(jnp.float32), layer,
                                 interpret=interpret)
    return y.reshape(lead + (n,))


# ------------------------------------------ tp composition (mode "cp", r5)
#
# Under tensor parallelism the stacked payload arrives sharded: column-
# parallel weights (wq/wk/wv/w_gate/w_up) on N — P(None, None, tp) — and
# row-parallel ones (wo/w_down) on the packed contraction axis —
# P(None, tp, None). A plain pallas_call is an opaque unit, so GSPMD
# would all-gather the weight (the exact 1,584 tok/s loss the kernel
# exists to avoid). The fix is a ``custom_partitioning`` wrapper with a
# Shardy rule: x is passed PRE-SPLIT as (xlo, xhi) so both halves' K/2
# axis and the payload's packed axis share one factor "j" — the
# split-half layout then shards COHERENTLY (device d's packed rows hold
# the lo nibbles of source rows [d·K2/t, (d+1)·K2/t) and the hi nibbles
# of [K/2 + d·K2/t, ...), which is exactly device d's shard of xlo and
# xhi) — no repacking, no gather:
#
#   column (n sharded): local kernel on [L, K/2, N/t], out n-sharded;
#   row (j sharded):    local kernel on [L, K2/t, N] + psum over tp
#                       ("j" is declared a reduction factor).
#
# Local-shape tiling is re-checked inside the partition callback: a
# shard whose K2/N no longer divides the block candidates falls back to
# the XLA dequant einsum LOCALLY (correct, slower) rather than failing
# to lower.


def _cp_local_fallback(xlo, xhi, packed, scale):
    """Local-shard XLA path: nibble-unpack fused into two dots."""
    p = packed.astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(jax.lax.shift_left(p, 28), 28)
    hi = jax.lax.shift_right_arithmetic(p, 4)
    dt = xlo.dtype
    y = (jnp.einsum("mk,kn->mn", xlo, lo.astype(dt))
         + jnp.einsum("mk,kn->mn", xhi, hi.astype(dt)))
    return (y.astype(jnp.float32) * scale.reshape(1, -1)).astype(xlo.dtype)


@functools.lru_cache(maxsize=2)
def _cp_stacked(interpret: bool):
    from jax.experimental.custom_partitioning import custom_partitioning

    try:  # Shardy rule (jax with the Sdy partitioner); else GSPMD callbacks
        from jax.experimental.custom_partitioning import SdyShardingRule
    except ImportError:                               # pragma: no cover
        SdyShardingRule = None
    from jax.sharding import NamedSharding, PartitionSpec as P

    def _impl(xlo, xhi, packed, scale, layer):
        xx = jnp.concatenate([xlo, xhi], axis=-1)
        return _int4_matmul_stacked(xx, packed, scale, layer[0],
                                    interpret=interpret)

    cp = custom_partitioning(_impl)

    def _partition(mesh, arg_infos, result_infos):
        xs = arg_infos[0].sharding.spec if arg_infos[0].sharding else P()
        ps = (arg_infos[2].sharding.spec if arg_infos[2].sharding
              else P(None, None, None))
        m_ax = xs[0] if len(xs) > 0 else None
        j_ax = ps[1] if len(ps) > 1 else None
        n_ax = ps[2] if len(ps) > 2 else None
        arg_shardings = (NamedSharding(mesh, P(m_ax, j_ax)),
                         NamedSharding(mesh, P(m_ax, j_ax)),
                         NamedSharding(mesh, P(None, j_ax, n_ax)),
                         NamedSharding(mesh, P(None, None, n_ax)),
                         NamedSharding(mesh, P()))
        out_sharding = NamedSharding(mesh, P(m_ax, n_ax))

        def _axis_size(ax):
            if ax is None:
                return 1
            names = (ax,) if isinstance(ax, str) else ax
            size = 1
            for nm in names:
                size *= mesh.shape[nm]
            return size

        def lower_fn(xlo, xhi, packed, scale, layer):
            _nl, k2l, nloc = packed.shape
            if _block_of(k2l, _K_BLOCKS) and _block_of(nloc, _N_BLOCKS):
                y = _impl(xlo, xhi, packed, scale, layer)
            else:                       # untileable local shard
                sl = jax.lax.dynamic_index_in_dim(scale, layer[0], 0,
                                                  keepdims=False)
                y = _cp_local_fallback(
                    xlo, xhi,
                    jax.lax.dynamic_index_in_dim(packed, layer[0], 0,
                                                 keepdims=False), sl)
            if _axis_size(j_ax) > 1:
                y = jax.lax.psum(y, j_ax)
            return y

        return mesh, lower_fn, out_sharding, arg_shardings

    if SdyShardingRule is not None:
        rule = SdyShardingRule(
            operand_mappings=(("m", "j"), ("m", "j"), ("l", "j", "n"),
                              ("l", "z", "n"), ("o",)),
            result_mappings=(("m", "n"),),
            reduction_factors=("j",),
        )
        cp.def_partition(partition=_partition, sharding_rule=rule)
    else:
        # pre-Shardy jax: express the same rule through the GSPMD
        # callbacks — output inherits (m from x, n from the payload); the
        # j (reduction) factor is handled by _partition's psum
        def _infer(mesh, arg_infos, result_infos):
            xs = (arg_infos[0].sharding.spec if arg_infos[0].sharding
                  else P())
            ps = (arg_infos[2].sharding.spec if arg_infos[2].sharding
                  else P(None, None, None))
            m_ax = xs[0] if len(xs) > 0 else None
            n_ax = ps[2] if len(ps) > 2 else None
            return NamedSharding(mesh, P(m_ax, n_ax))

        cp.def_partition(partition=_partition,
                         infer_sharding_from_operands=_infer)
    return cp
