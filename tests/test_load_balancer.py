"""Load balancer tests — all four strategies, healthy-set filtering, pinned
workers, probe/request stat separation (reference pitfall,
``src/load_balancer.py:334-339``), live health probes."""

import asyncio

import pytest

from distributed_inference_engine_tpu.config import HealthConfig, ServerConfig
from distributed_inference_engine_tpu.cluster.load_balancer import (
    LoadBalancer,
    LoadBalancerStrategy,
    NoHealthyWorkerError,
)
from distributed_inference_engine_tpu.cluster.worker import WorkerServer


def make_lb(strategy=LoadBalancerStrategy.ROUND_ROBIN, n=3, **health_kw):
    lb = LoadBalancer(strategy=strategy, health=HealthConfig(**health_kw),
                      seed=0)
    for i in range(n):
        lb.register_worker(f"w{i}", "127.0.0.1", 20000 + i)
    return lb


def test_round_robin_cycles_evenly():
    lb = make_lb()
    picks = [lb.get_worker().worker_id for _ in range(9)]
    assert picks == ["w0", "w1", "w2"] * 3


def test_least_connections_prefers_idle():
    lb = make_lb(LoadBalancerStrategy.LEAST_CONNECTIONS)
    lb.acquire("w0")
    lb.acquire("w0")
    lb.acquire("w1")
    assert lb.get_worker().worker_id == "w2"
    lb.release("w0")
    lb.release("w0")
    assert lb.get_worker().worker_id in ("w0", "w2")


def test_random_is_seeded_and_healthy_only():
    lb = make_lb(LoadBalancerStrategy.RANDOM)
    picks = {lb.get_worker().worker_id for _ in range(50)}
    assert picks == {"w0", "w1", "w2"}


def test_least_latency_tracks_real_traffic():
    lb = make_lb(LoadBalancerStrategy.LEAST_LATENCY)
    lb.update_stats("w0", success=True, latency_s=0.5)
    lb.update_stats("w1", success=True, latency_s=0.1)
    lb.update_stats("w2", success=True, latency_s=0.9)
    assert lb.get_worker().worker_id == "w1"


def test_unhealthy_workers_filtered_and_recover():
    lb = make_lb(max_consecutive_failures=2)
    lb.update_stats("w0", success=False, latency_s=0.1)
    lb.update_stats("w0", success=False, latency_s=0.1)
    picks = {lb.get_worker().worker_id for _ in range(10)}
    assert "w0" not in picks
    lb.update_stats("w0", success=True, latency_s=0.1)   # recovery resets
    picks = {lb.get_worker().worker_id for _ in range(10)}
    assert "w0" in picks


def test_no_healthy_workers_raises():
    lb = make_lb(n=1, max_consecutive_failures=1)
    lb.update_stats("w0", success=False, latency_s=0.1)
    with pytest.raises(NoHealthyWorkerError):
        lb.get_worker()


def test_pinned_worker_path():
    lb = make_lb(max_consecutive_failures=1)
    assert lb.get_worker(pinned="w1").worker_id == "w1"
    lb.update_stats("w1", success=False, latency_s=0.1)
    with pytest.raises(NoHealthyWorkerError, match="pinned"):
        lb.get_worker(pinned="w1")
    with pytest.raises(NoHealthyWorkerError, match="pinned"):
        lb.get_worker(pinned="ghost")


def test_unregister_shrinks_rotation():
    lb = make_lb()
    assert lb.unregister_worker("w1") is True
    assert lb.unregister_worker("w1") is False
    picks = {lb.get_worker().worker_id for _ in range(6)}
    assert picks == {"w0", "w2"}


async def test_probes_never_touch_request_stats():
    """The reference's probes polluted avg-latency used by LEAST_LATENCY —
    here probe outcomes live in probe_* fields only."""
    lb = LoadBalancer(strategy=LoadBalancerStrategy.LEAST_LATENCY,
                      health=HealthConfig(check_timeout=1.0))
    server = WorkerServer(ServerConfig(worker_id="wl", port=0))
    host, port = await server.start()
    lb.register_worker("wl", host, port)
    try:
        for _ in range(5):
            assert await lb.check_worker("wl") is True
        s = lb.workers["wl"]
        assert s.probe_count == 5
        assert s.request_count == 0
        assert s.avg_latency_s == 0.0
    finally:
        await lb.stop()
        await server.stop()


async def test_probe_failures_mark_unhealthy_then_recover():
    lb = make_lb(n=0, max_consecutive_failures=2, check_timeout=0.5)
    lb.register_worker("w", "127.0.0.1", 1)      # dead port
    assert await lb.check_worker("w") is False
    assert await lb.check_worker("w") is False
    assert lb.healthy_workers() == []
    server = WorkerServer(ServerConfig(worker_id="w", port=0))
    host, port = await server.start()
    lb.workers["w"].host, lb.workers["w"].port = host, port
    lb._clients.pop("w", None)                   # drop stale client
    try:
        assert await lb.check_worker("w") is True
        assert [s.worker_id for s in lb.healthy_workers()] == ["w"]
    finally:
        await lb.stop()
        await server.stop()


def test_stats_schema():
    lb = make_lb()
    lb.update_stats("w0", success=True, latency_s=0.2)
    all_stats = lb.get_all_stats()
    assert all_stats["strategy"] == "round_robin"
    assert all_stats["healthy_count"] == 3
    w0 = all_stats["workers"]["w0"]
    assert w0["request_count"] == 1
    assert w0["avg_latency_s"] == pytest.approx(0.2)
    assert lb.get_worker_stats("ghost") is None
