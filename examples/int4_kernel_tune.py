"""Block-size tuning sweep for the stacked Mosaic int4 kernel on hardware
(r5, decode_profile.md "stream efficiency" lever: the kernel ran its
packed stream at ~510 GB/s, 62% of the 819 GB/s v5e peak).

Measurement discipline: host-side timing of single dispatches is
untrustworthy over the tunnelled chip — ``block_until_ready`` returns
early (measured 2.4 TB/s "throughput", 3x the physical HBM peak) and a
result fetch pays an ~90 ms round trip. So each config is timed as a
DEVICE-side ``lax.scan`` over all L layers x P passes inside ONE jit
returning one scalar, at two pass counts; the difference cancels the
dispatch + round-trip constant:

    per-layer-us = (t(2P) - t(P)) / (P * L)

Prints one JSON row per (shape, bk, bn) with achieved GB/s on the packed
stream. The defaults in ``ops/int4_matmul.py`` (``_K_BLOCKS``/
``_N_BLOCKS`` preference order) should be the winners printed here.

    python examples/int4_kernel_tune.py            # decode tile (M=64)
    BENCH_M=128 python examples/int4_kernel_tune.py
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import jax
import jax.numpy as jnp

from distributed_inference_engine_tpu.ops.int4_matmul import (
    _int4_matmul_stacked,
)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# 8B decode shapes: (name, L, K, N) — the r5 FUSED shapes (qkv N=6144,
# gate+up N=28672) plus wo / w_down and the vocab-PADDED lm_head
# (128256 → 129024 = 2048·63; the raw width tiles only at bn=256).
SHAPES = [
    ("qkv_fused", 32, 4096, 6144),
    ("wo", 32, 4096, 4096),
    ("gate_up_fused", 32, 4096, 28672),
    ("w_down", 32, 14336, 4096),
    ("lm_head_padded", 1, 4096, 129024),
]
BKS = (2048, 1024, 512)
BNS = (4096, 2048, 1024)
M = int(os.environ.get("BENCH_M", "64"))
PASSES = int(os.environ.get("BENCH_PASSES", "24"))


@functools.partial(jax.jit, static_argnames=("bk", "bn", "passes"))
def _loop(x, packed, scale, *, bk, bn, passes):
    """passes x L sequential kernel calls on-device; scalar out."""
    nl = packed.shape[0]

    def body(acc, l):
        y = _int4_matmul_stacked(x, packed, scale, l, bk=bk, bn=bn)
        # fold a few output elements into the carry: the scan carry is the
        # data dependency that keeps XLA from reordering/eliding calls
        return acc + y[0, :8].astype(jnp.float32).sum(), None

    acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                          jnp.tile(jnp.arange(nl, dtype=jnp.int32), passes))
    return acc


def _timed(x, packed, scale, bk, bn, passes):
    t0 = time.perf_counter()
    v = _loop(x, packed, scale, bk=bk, bn=bn, passes=passes)
    float(v)                       # scalar fetch = the only sync point
    return time.perf_counter() - t0


def main():
    log(f"devices: {jax.devices()}  M={M}  passes={PASSES}")
    key = jax.random.key(0)
    best = {}
    for name, nl, k, n in SHAPES:
        k2 = k // 2
        kq, kx = jax.random.split(jax.random.fold_in(key, hash(name) % 97))
        packed = jax.random.randint(kq, (nl, k2, n), -128, 128, jnp.int8)
        scale = jnp.full((nl, 1, n), 1e-3, jnp.float32)
        x = jax.random.normal(kx, (M, k), jnp.bfloat16)
        for bk in BKS:
            if k2 % bk:
                continue
            for bn in BNS:
                if n % bn:
                    continue
                try:
                    _timed(x, packed, scale, bk, bn, PASSES)   # compile
                    _timed(x, packed, scale, bk, bn, 2 * PASSES)
                    t1 = _timed(x, packed, scale, bk, bn, PASSES)
                    t2 = _timed(x, packed, scale, bk, bn, 2 * PASSES)
                except Exception as e:   # untileable/VMEM: record, move on
                    log(f"{name} bk={bk} bn={bn}: FAIL {type(e).__name__}: "
                        f"{str(e)[:120]}")
                    continue
                dt = max(t2 - t1, 1e-9) / (PASSES * nl)   # overhead cancels
                gbps = (k2 * n) / dt / 1e9
                row = {"shape": name, "bk": bk, "bn": bn, "M": M,
                       "us_per_layer": round(dt * 1e6, 1),
                       "packed_gbps": round(gbps, 1),
                       "pct_peak": round(gbps / 819.0, 3)}
                print(json.dumps(row), flush=True)
                cur = best.get(name)
                if cur is None or gbps > cur[2]:
                    best[name] = (bk, bn, gbps)
    log("--- best per shape ---")
    for name, (bk, bn, gbps) in best.items():
        log(f"{name}: bk={bk} bn={bn} {gbps:.0f} GB/s "
            f"({gbps / 819.0:.0%} of peak)")


if __name__ == "__main__":
    main()
