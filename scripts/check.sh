#!/usr/bin/env bash
# Repo gate: the tier-1 test suite (exactly the ROADMAP.md verify
# command) plus a static lint pass. Run from anywhere; exits non-zero
# if either stage fails.
#
#   ./scripts/check.sh            # lint + full tier-1 suite
#   SKIP_TESTS=1 ./scripts/check.sh   # lint only (fast pre-commit)
set -u
cd "$(dirname "$0")/.."

rc=0

# --- stage 1: static checks -------------------------------------------
# pyflakes when the environment has it; otherwise fall back to a
# bytecode-compile sweep, which still catches syntax errors everywhere
# (including files the tests never import).
if python -c "import pyflakes" 2>/dev/null; then
    echo "== pyflakes =="
    python -m pyflakes distributed_inference_engine_tpu tests bench.py \
        examples scripts 2>/dev/null || rc=1
else
    echo "== compileall (pyflakes not installed) =="
    python -m compileall -q distributed_inference_engine_tpu tests \
        bench.py examples scripts || rc=1
fi

if [ "$rc" -ne 0 ]; then
    echo "check.sh: static checks FAILED" >&2
    exit "$rc"
fi

if [ "${SKIP_TESTS:-0}" = "1" ]; then
    echo "check.sh: static checks OK (tests skipped)"
    exit 0
fi

# --- stage 2a: graftlint ----------------------------------------------
# AST analysis of the serving stack: host-sync reads in the hot call
# graph, jit-stability hazards, async hygiene, docs<->code drift
# (subsumes the old scripts/lint_metrics.py check). Bare interpreter,
# no jax — drift fails in milliseconds. Any unsuppressed finding fails;
# NEW findings must be fixed or pragma'd with a reason, never silently
# baselined (refreshing the baseline takes an explicit, reviewed
# `python -m scripts.graftlint --update-baseline`).
echo "== graftlint (python -m scripts.graftlint) =="
python -m scripts.graftlint distributed_inference_engine_tpu bench.py \
    || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: graftlint FAILED" >&2
    exit "$rc"
fi

# --- stage 2b: fast observability leg ---------------------------------
# registry/exposition/timeline/trace tests (-m obs) run standalone next:
# a telemetry regression fails here in seconds.
echo "== observability (-m 'obs and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'obs and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: observability leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2c: fast chaos leg -----------------------------------------
# fault-injection / failover tests (-m chaos): seeded FaultPlan faults,
# deadline budgets, graceful drain, mid-stream kill + resume. A broken
# failure path fails here in seconds, before the full sweep.
echo "== chaos (-m 'chaos and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'chaos and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: chaos leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2d: fast elastic-lifecycle leg -----------------------------
# serving-artifact round-trip/corruption/cold-start + supervisor
# respawn/crash-loop tests (-m elastic): a broken artifact or respawn
# path fails here before the full sweep.
echo "== elastic lifecycle (-m 'elastic and not slow') =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'elastic and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: elastic lifecycle leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2e: fast fleet-serving leg ---------------------------------
# prefix-affinity routing, disaggregated pools through the coordinator,
# rebind on drain/respawn/stream-failover (-m fleet): a broken routing
# or handoff path fails here before the full sweep.
echo "== fleet serving (-m 'fleet and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'fleet and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: fleet serving leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2f: fast autoscale leg -------------------------------------
# the SLO → fleet-size loop (-m autoscale): policy hysteresis/cooldown/
# guard rails, decision-ledger determinism, rolling upgrade with golden-
# probe rollback, fleet-level admission shed.
echo "== autoscaling (-m 'autoscale and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'autoscale and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: autoscale leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2g: fast KV-fabric leg -------------------------------------
# fleet-wide KV page migration (-m fabric): export/import wire
# bit-parity across KV dtypes, checksum rejection, pre-warm-before-
# half-open ordering, failover import, fault fallback.
echo "== kv fabric (-m 'fabric and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'fabric and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: kv fabric leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2g2: fast flight-recorder leg ------------------------------
# fleet flight recorder (-m slo): typed event rings (wrap mid-capture,
# canonical sequences), clock-sync merged-trace monotonicity with
# mixed-sign offsets, SLO burn-rate engine windows + ledger
# determinism, post-mortem bundle round-trip.
echo "== flight recorder (-m 'slo and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'slo and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: flight recorder leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2: fast kernel-parity leg ----------------------------------
# Pallas kernel tests (-m kernels) run standalone FIRST: a broken kernel
# fails here in seconds instead of minutes into the full tier-1 sweep.
echo "== kernel parity (-m 'kernels and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'kernels and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: kernel parity leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2h: fast streaming leg -------------------------------------
# sub-chunk streaming (-m streaming): device->host token ring round-trip,
# sub-chunk vs packed-harvest parity (greedy + sampled, stops trimmed
# identically), adaptive-chunk compile guard, mid-stream kill resume
# through the fabric path with no duplicate/missing token.
echo "== streaming (-m 'streaming and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'streaming and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: streaming leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2i: fast multimodel leg ------------------------------------
# multi-model workers (-m multimodel): resident-budget LRU eviction,
# background stage never displacing dispatch, golden-probe-gated hot
# swap, model-qualified affinity keys + KV isolation, supervisor respawn
# reloading the full resident catalog.
echo "== multimodel (-m 'multimodel and not slow') =="
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'multimodel and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: multimodel leg FAILED" >&2
    exit "$rc"
fi

# --- stage 2j: fast async-speculation leg -----------------------------
# bubble-scheduled speculation (-m spec): acceptance-math bit-parity vs
# the frozen r5 rule, greedy spec-vs-off token exactness (f32 + int4),
# drafter extremes, verify-program compile guard, saturation auto-idle,
# same-seed determinism, pump hook ordering.
echo "== async speculation (-m 'spec and not slow') =="
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'spec and not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
if [ "$rc" -ne 0 ]; then
    echo "check.sh: async speculation leg FAILED" >&2
    exit "$rc"
fi

# --- stage 3: tier-1 tests (verbatim ROADMAP.md verify command) -------
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
