"""Host-RAM second tier for the paged KV cache.

The device prefix cache is terminal without this module: when
``PagedKVCache._take_free`` runs dry it reclaims cached prefix pages and
their KV is simply gone — every later request sharing that prefix pays a
full prefill recompute. Here evicted pages drop one level instead of off a
cliff: their contents move device→host into a byte-budgeted LRU keyed by
the SAME page-chain hashes as the device index, and admission falls
through to this tier, uploading hits host→device so prefill runs only the
truly-uncached suffix (PRESERVE / async-KV-prefetch: the upload overlaps
batch formation, so its latency hides behind work the engine does
anyway).

The store also backs swap-based preemption: when the pool exhausts
mid-decode, the continuous engine parks a victim slot's pages here under a
separate reservation (``reserve_swap``) and later resumes the sequence by
re-uploading them — no recompute, no "length" finish. Swap bytes and LRU
bytes share one ``max_bytes`` budget; swap reservations are hard (never
evicted), the LRU yields to them.

Pure host-side bookkeeping: the only JAX calls are ``jax.device_put`` for
staged uploads. Transfers INTO the store are batched by the cache's
``sync_tiers`` (one ``device_get`` per flush, not per page).
"""

from __future__ import annotations

import collections
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np


class _Entry:
    __slots__ = ("k", "v", "nbytes", "k_dev", "v_dev")

    def __init__(self, k: np.ndarray, v: np.ndarray) -> None:
        self.k = k
        self.v = v
        self.nbytes = k.nbytes + v.nbytes
        # staged async uploads (jax.device_put results); populated by
        # start_upload, consumed by get
        self.k_dev = None
        self.v_dev = None


class HostKVOffload:
    """Byte-budgeted host LRU of KV pages, keyed by page-chain hash."""

    def __init__(self, max_bytes: int = 1 << 30,
                 upload_layers_per_chunk: int = 1) -> None:
        self.max_bytes = int(max_bytes)
        # layer-wise staging granularity: start_upload issues one async
        # device_put per chunk of this many layers (PRESERVE-style overlap
        # — each chunk's PCIe copy is in flight while the next is sliced),
        # and the sync_tiers scatter concatenates on device. 0 = whole-page
        # single device_put (the pre-fabric behavior).
        self.upload_layers_per_chunk = int(upload_layers_per_chunk)
        self._entries: "collections.OrderedDict[bytes, _Entry]" = (
            collections.OrderedDict()
        )
        self._lru_bytes = 0
        self._swap_bytes = 0        # hard reservations (preempted slots)
        self._offloaded_pages = 0
        self._offloaded_bytes = 0
        self._hit_pages = 0
        self._hit_bytes = 0
        self._staged_pages = 0
        self._evicted_pages = 0
        self._rejected_pages = 0
        # restage overlap: wall-clock between start_upload (prefetch) and
        # the get() that consumes the staged copy — the window the async
        # host→device transfer had to hide behind queue wait / decode
        self._staged_at: Dict[bytes, float] = {}
        self._restage_overlap_s = 0.0

    # --------------------------------------------------------------- LRU

    def __len__(self) -> int:
        return len(self._entries)

    def probe(self, key: bytes) -> bool:
        """Presence check WITHOUT recency touch (advisory probes must not
        reorder the LRU under the real consumers)."""
        return key in self._entries

    def admit(self, key: bytes) -> bool:
        """Should the cache bother offloading this page? False when the
        tier is disabled (budget 0) or the key is already stored — the
        stored copy was written at registration time and page contents are
        immutable once registered, so a re-offload is pure waste."""
        return self.max_bytes > 0 and key not in self._entries

    def put(self, key: bytes, k: np.ndarray, v: np.ndarray) -> bool:
        """Insert one page's KV (host arrays, ``[L, page_size, fused]``).
        Evicts oldest entries to fit the budget; returns False when the
        page can't fit even after evicting everything (swap reservations
        are never evicted)."""
        if key in self._entries:
            return True
        entry = _Entry(k, v)
        budget = self.max_bytes - self._swap_bytes
        while self._entries and self._lru_bytes + entry.nbytes > budget:
            self._evict_oldest()
        if self._lru_bytes + entry.nbytes > budget:
            self._rejected_pages += 1
            return False
        self._entries[key] = entry
        self._lru_bytes += entry.nbytes
        self._offloaded_pages += 1
        self._offloaded_bytes += entry.nbytes
        return True

    def get(self, key: bytes) -> Optional[Tuple[object, object]]:
        """Fetch a page's (k, v) for upload, touching recency. Returns the
        staged device arrays when ``start_upload`` already ran (the async
        prefetch case) — otherwise the host arrays; either feeds the same
        scatter."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        self._entries.move_to_end(key)
        self._hit_pages += 1
        self._hit_bytes += entry.nbytes
        if entry.k_dev is not None:
            t0 = self._staged_at.pop(key, None)
            if t0 is not None:
                self._restage_overlap_s += time.perf_counter() - t0
            return entry.k_dev, entry.v_dev
        return entry.k, entry.v

    def peek(self, key: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Host copy of a page's (k, v) WITHOUT recency touch or hit
        accounting — the KV-fabric export reader (an export must not
        perturb the LRU the serving path depends on)."""
        entry = self._entries.get(key)
        if entry is None:
            return None
        return entry.k, entry.v

    def start_upload(self, key: bytes) -> bool:
        """Begin an async host→device copy of the entry (non-blocking:
        ``device_put`` returns immediately; the transfer overlaps whatever
        the engine does until admission consumes it via ``get``). With
        ``upload_layers_per_chunk > 0`` the copy is issued as per-layer-
        chunk device_puts — each chunk's transfer is dispatched while the
        next is sliced, and the staged value is a list of device chunks
        that ``sync_tiers`` concatenates on device."""
        entry = self._entries.get(key)
        if entry is None:
            return False
        if entry.k_dev is None:
            step = self.upload_layers_per_chunk
            if step > 0 and entry.k.shape[0] > step:
                entry.k_dev = [jax.device_put(entry.k[i:i + step])
                               for i in range(0, entry.k.shape[0], step)]
                entry.v_dev = [jax.device_put(entry.v[i:i + step])
                               for i in range(0, entry.v.shape[0], step)]
            else:
                entry.k_dev = jax.device_put(entry.k)
                entry.v_dev = jax.device_put(entry.v)
            self._staged_pages += 1
            self._staged_at[key] = time.perf_counter()
        return True

    def _evict_oldest(self) -> None:
        key, entry = self._entries.popitem(last=False)
        self._lru_bytes -= entry.nbytes
        self._evicted_pages += 1
        self._staged_at.pop(key, None)

    # -------------------------------------------------- swap reservations

    def reserve_swap(self, nbytes: int) -> bool:
        """Reserve budget for a preempted slot's pages. Evicts LRU entries
        to make room; False when the reservation cannot fit (the engine
        then falls back to the old finish_reason="length" behavior)."""
        while (self._entries
               and self._lru_bytes + self._swap_bytes + nbytes > self.max_bytes):
            self._evict_oldest()
        if self._lru_bytes + self._swap_bytes + nbytes > self.max_bytes:
            return False
        self._swap_bytes += nbytes
        return True

    def release_swap(self, nbytes: int) -> None:
        self._swap_bytes = max(0, self._swap_bytes - nbytes)

    # ------------------------------------------------------------- stats

    def get_stats(self) -> Dict[str, float]:
        return {
            "host_max_bytes": self.max_bytes,
            "host_lru_bytes": self._lru_bytes,
            "host_swap_bytes": self._swap_bytes,
            "host_pages": len(self._entries),
            "offloaded_pages": self._offloaded_pages,
            "offloaded_bytes": self._offloaded_bytes,
            "host_hit_pages": self._hit_pages,
            "host_hit_bytes": self._hit_bytes,
            "host_staged_pages": self._staged_pages,
            "host_evicted_pages": self._evicted_pages,
            "host_rejected_pages": self._rejected_pages,
            "restage_overlap_s": self._restage_overlap_s,
        }
