"""Worker: the framed RPC server hosting inference engines on a TPU-VM.

Capability heir of the reference's ``src/worker.py``: an asyncio TCP server
with model load/unload lifecycle (``src/worker.py:164-184``), per-request
logging (``:126-133``), process + per-model metrics (``:186-209``), signal
handling (``:44-49``) and OS-assigned ports (``:58-59``). Three reference
defects are deliberately fixed (SURVEY.md §2.4, §5):

- **Framing.** The reference reads a single ``read(4096)`` per request
  (``src/worker.py:93``), silently truncating large payloads. Here every
  message is a length-prefixed frame (``utils/framing.py``).
- **Persistent connections.** The reference closes after one request
  (``src/worker.py:117-124``); this server loops frames on one connection,
  so the coordinator keeps a warm connection pool instead of paying a TCP
  handshake per request.
- **Probe pollution.** Reference health probes inflate the worker's request
  counter (``src/worker.py:87``) and the LB's latency stats
  (``src/load_balancer.py:334-339``). Here ``ping`` is a distinct method
  counted separately from ``generate``.

The engine behind each model is real JAX (``engine.Engine``) or the fake
(``models/fake.FakeEngine``) per ``ModelConfig.architecture``. Engine calls
are synchronous XLA dispatches, so they run on a single-thread executor:
the event loop stays responsive for pings while the device crunches, and
device access is serialized (one program on the chip at a time).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import signal
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ..config import ModelConfig, ServerConfig
from ..engine.types import GenerationRequest, GenerationResult
from .model_manager import ModelManager, ModelProbeError, ModelStageError
from ..utils.framing import FrameError, read_frame, write_frame
from ..utils.rpc import (
    FramedRPCClient,
    FramedServerMixin,
    RPCError,
    relay_stream,
)
from ..obs import collectors as obs_collectors
from ..obs.events import EventLog
from ..obs.registry import OPENMETRICS_CONTENT_TYPE, MetricsRegistry
from ..utils.tracing import LatencyStats

logger = logging.getLogger(__name__)

# machine-readable error class for the disaggregated relay: the decode peer
# could not be reached / died mid-decode. The coordinator reacts by marking
# the DECODE worker and retrying on an alternate shard (the prefill worker
# that reports this is itself healthy).
DECODE_PEER_UNREACHABLE = "decode_peer_unreachable"


class DecodePeerError(RuntimeError):
    """Transport failure between a prefill worker and its decode peer."""

    rpc_error_kind = DECODE_PEER_UNREACHABLE


class WorkerDrainingError(RuntimeError):
    """Admission refused: this worker is draining (finishing in-flight work
    before removal). Wire kind is ``overloaded`` with detail ``draining`` so
    the coordinator's existing shed machinery retries on an alternate replica
    — and, because sheds bypass health accounting, the drain doesn't dent
    this worker's health while it finishes."""

    rpc_error_kind = "overloaded"
    rpc_error_detail = "draining"


# --------------------------------------------------------------------------
# request/result wire marshalling (token-id space; tokenization is a client/
# coordinator concern)

def request_to_dict(r: GenerationRequest) -> Dict[str, Any]:
    return {
        "prompt": list(r.prompt),
        "max_new_tokens": r.max_new_tokens,
        "temperature": r.temperature,
        "top_k": r.top_k,
        "top_p": r.top_p,
        "min_p": r.min_p,
        "request_id": r.request_id,
        "eos_id": r.eos_id,
        "stop_ids": list(r.stop_ids),
        "stop_sequences": [list(s) for s in r.stop_sequences],
        "deadline_s": r.deadline_s,
    }


def request_from_dict(d: Dict[str, Any]) -> GenerationRequest:
    return GenerationRequest(
        prompt=list(d["prompt"]),
        max_new_tokens=int(d.get("max_new_tokens", 16)),
        temperature=float(d.get("temperature", 0.0)),
        top_k=int(d.get("top_k", 0)),
        top_p=float(d.get("top_p", 1.0)),
        min_p=float(d.get("min_p", 0.0)),
        request_id=str(d.get("request_id", "")),
        eos_id=int(d.get("eos_id", -1)),
        stop_ids=[int(t) for t in d.get("stop_ids", [])],
        stop_sequences=[[int(t) for t in s]
                        for s in d.get("stop_sequences", [])],
        deadline_s=(float(d["deadline_s"])
                    if d.get("deadline_s") is not None else None),
    )


def result_to_dict(r: GenerationResult) -> Dict[str, Any]:
    return {
        "request_id": r.request_id,
        "tokens": list(r.tokens),
        "finish_reason": r.finish_reason,
        "prompt_tokens": r.prompt_tokens,
        "logprobs": [float(x) for x in r.logprobs],
        "ttft_s": r.ttft_s,
        "decode_s": r.decode_s,
        "metadata": dict(r.metadata),
    }


def result_from_dict(d: Dict[str, Any]) -> GenerationResult:
    return GenerationResult(
        request_id=str(d.get("request_id", "")),
        tokens=list(d.get("tokens", [])),
        finish_reason=str(d.get("finish_reason", "")),
        prompt_tokens=int(d.get("prompt_tokens", 0)),
        logprobs=[float(x) for x in d.get("logprobs", [])],
        ttft_s=float(d.get("ttft_s", 0.0)),
        decode_s=float(d.get("decode_s", 0.0)),
        metadata=dict(d.get("metadata", {})),
    )


# --------------------------------------------------------------------------
# engine factory

def build_engine(cfg: ModelConfig):
    """Default engine factory — delegates to the single shared
    config-driven factory (``models.engine_from_config``); imported lazily
    so jax-free control planes can import this module."""
    from ..models import engine_from_config

    return engine_from_config(cfg)


EngineFactory = Callable[[ModelConfig], Any]


def _model_identity(cfg: ModelConfig):
    """The fields that determine WHICH model an engine serves. Engine-impl
    knobs (continuous mode, page sizes, batch limits, schemas) are worker-
    local choices and deliberately excluded — see ``load_model``."""
    return (cfg.name, cfg.version, cfg.architecture, cfg.path, cfg.dtype,
            cfg.quantized, str(cfg.metadata.get("size", "")))


def _engine_features(cfg: ModelConfig) -> frozenset:
    """The RPC surface an engine config provides. Idempotent re-load is
    allowed only when the hosted engine provides a SUPERSET of what the new
    deploy needs — unlike the engine knobs ``_model_identity`` ignores, a
    missing feature silently blackholes a pool's traffic (e.g. a static
    engine in a decode pool can't serve ``generate_prefilled``). The check
    is directional: a continuous preload is a fine target for a plain
    deploy, the reverse is not."""
    if cfg.metadata.get("role") == "prefill":
        return frozenset({"prefill"})
    if cfg.metadata.get("continuous"):
        return frozenset({"generate", "generate_prefilled"})
    return frozenset({"generate"})


# --------------------------------------------------------------------------
# server

class WorkerServer(FramedServerMixin):
    """Framed-RPC worker host (heir of reference ``Worker``, src/worker.py:26-209).

    Connection loop + dispatch envelope live in ``FramedServerMixin``
    (shared with ``CoordinatorServer``); this class supplies the worker
    policy via the mixin hooks."""

    def __init__(
        self,
        config: Optional[ServerConfig] = None,
        engine_factory: EngineFactory = build_engine,
    ) -> None:
        self.config = config or ServerConfig()
        self.worker_id = self.config.worker_id
        self.engine_factory = engine_factory
        # multi-model residency (cluster/model_manager.py): the manager
        # owns the resident set + staging/swap/eviction policy; the worker
        # aliases its dicts so every RPC path reads the same state
        self.model_manager = ModelManager(
            self._build_engine,
            max_resident_models=self.config.max_resident_models,
            resident_bytes=self.config.resident_bytes,
            busy_fn=self._model_busy,
            on_evict=self._on_model_evicted,
        )
        self.engines: Dict[str, Any] = self.model_manager.engines
        self.model_configs: Dict[str, ModelConfig] = self.model_manager.configs
        self._pumps: Dict[str, Any] = {}    # model -> EnginePump (continuous)
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_writers: set = set()
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"{self.worker_id}-engine"
        )
        self._started_at = 0.0
        self._shutdown_event = asyncio.Event()
        # generate-path counters, kept apart from probe counters (see module doc)
        self._request_count = 0
        self._error_count = 0
        self._overloaded_count = 0     # load sheds, apart from real errors
        self._handoff_bytes_shipped = 0  # relay KV actually sent (deltas
                                         # make this < prefill engine's
                                         # total_handoff_bytes)
        self._ping_count = 0
        self._active_connections = 0
        # graceful drain: when set, admission verbs refuse new work (typed
        # as a "draining" shed) while in-flight requests run to completion
        self._draining = False
        self._busy = 0                 # admission RPCs currently executing
        self._drain_count = 0
        self._deadline_expired_count = 0
        self.latency = LatencyStats()
        # elastic lifecycle (engine/artifact.py): engine-construction wall
        # time per load_model, and whether each artifact-configured load
        # actually cold-started from its artifact (hit) or fell back to
        # from-scratch init (miss) — the respawn-latency receipts
        self.model_load_stats = LatencyStats()
        self._last_load_s: Dict[str, float] = {}
        self._artifact_hits = 0
        self._artifact_misses = 0
        # KV fabric (engine/kv_fabric.py): pages migrated in/out of this
        # worker's host tier over the kv_export/kv_import verbs
        self._kv_fabric_exports = 0
        self._kv_fabric_imports = 0
        self._kv_fabric_export_bytes = 0
        self._kv_fabric_import_bytes = 0
        self._kv_fabric_import_fallbacks = 0
        self._methods: Dict[str, Callable[[Dict[str, Any]], Awaitable[Any]]] = {
            "ping": self._rpc_ping,
            "generate": self._rpc_generate,
            "prefill": self._rpc_prefill,
            "generate_prefilled": self._rpc_generate_prefilled,
            "prefill_generate": self._rpc_prefill_generate,
            "prefix_probe": self._rpc_prefix_probe,
            "kv_export": self._rpc_kv_export,
            "kv_import": self._rpc_kv_import,
            "load_model": self._rpc_load_model,
            "stage_model": self._rpc_stage_model,
            "swap_model": self._rpc_swap_model,
            "resident_models": self._rpc_resident_models,
            "unload_model": self._rpc_unload_model,
            "list_models": self._rpc_list_models,
            "metrics": self._rpc_metrics,
            "metrics_text": self._rpc_metrics_text,
            "profile": self._rpc_profile,
            "drain": self._rpc_drain,
            "shutdown": self._rpc_shutdown,
            "events": self._rpc_events,
        }
        # flight recorder (obs/events.py): bounded typed event ring,
        # collected on demand over the ``events`` verb and merged into the
        # coordinator's fleet trace
        self.events = EventLog(self.worker_id,
                               capacity=self.config.event_ring_capacity)
        # unified telemetry: this worker's dict metrics (incl. every loaded
        # engine's) mirrored into stable metric families at scrape time,
        # exposed as OpenMetrics text via the metrics_text RPC verb and
        # plain-HTTP GET /metrics on the same port (utils/rpc.py sniff)
        self.obs_registry = MetricsRegistry()
        obs_collectors.ensure_families(self.obs_registry)
        self.obs_registry.add_collector(self._obs_collect)
        # streaming methods write chunk frames ahead of the final envelope
        self._stream_methods = {
            "generate_stream": self._rpc_generate_stream,
        }
        self._profiling_dir: Optional[str] = None
        # prefill-pool side: persistent clients to decode-pool peers,
        # keyed by (host, port) — the KV handoff goes peer-to-peer over
        # DCN, not back through the coordinator
        self._peer_clients: Dict[Tuple[str, int], "WorkerClient"] = {}

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        if self._server is None:
            raise RuntimeError("worker not started")
        sock = self._server.sockets[0]
        host, port = sock.getsockname()[:2]
        return host, port

    async def start(self, install_signal_handlers: bool = False) -> Tuple[str, int]:
        self._server = await asyncio.start_server(
            self._handle_connection, self.config.host, self.config.port
        )
        self._started_at = time.time()
        if install_signal_handlers:
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, self._shutdown_event.set)
        host, port = self.address
        if self.fault_plan is not None:
            # flight recorder: record injections aimed at THIS worker in
            # its own event ring (the plan is shared fleet-wide)
            self.fault_plan.subscribe(self._on_injected_fault)
        logger.info("worker %s listening on %s:%d", self.worker_id, host, port)
        return host, port

    def _on_injected_fault(self, fault) -> None:
        """FaultPlan listener: mirror injections scoped to this worker
        into the event ring (the plan notifies on every injection)."""
        if fault.scope == self._fault_scope():
            self.events.emit("fault.injected", site=fault.site,
                             verb=fault.verb, kind=fault.kind,
                             ordinal=fault.ordinal)

    async def stop(self) -> None:
        if self.fault_plan is not None:
            self.fault_plan.unsubscribe(self._on_injected_fault)
        if self._server is not None:
            self._server.close()
            # persistent connections never exit on their own — close them, or
            # wait_closed() (which awaits all handlers on py3.12+) never returns
            self._close_all_connections()
            await self._server.wait_closed()
            self._server = None
        for pump in self._pumps.values():
            pump.shutdown_nowait()
        for client in self._peer_clients.values():
            await client.close()
        self._peer_clients.clear()
        self._executor.shutdown(wait=False, cancel_futures=True)
        self._shutdown_event.set()
        logger.info("worker %s stopped", self.worker_id)

    async def serve_forever(self) -> None:
        """Run until shutdown RPC or signal (reference src/worker.py:243-244)."""
        await self._shutdown_event.wait()
        await self.stop()

    # -- model lifecycle (reference src/worker.py:164-184) ------------------

    def _build_engine(self, cfg: ModelConfig):
        """Factory + artifact accounting + warmup — the full engine build,
        shared by the cold ``load_model`` path and the background staging
        thread (so a staged engine arrives pre-warmed: the swap installs
        it, it never compiles on the serving clock)."""
        engine = self.engine_factory(cfg)
        artifact_hit = getattr(engine, "artifact_manifest", None) is not None
        if cfg.metadata.get("artifact"):
            if artifact_hit:
                self._artifact_hits += 1
            else:
                self._artifact_misses += 1
        if cfg.metadata.get("warmup") and hasattr(engine, "warmup"):
            # pre-compile the serving programs at load time so the first
            # real request doesn't pay the XLA compile (metadata warmup=1).
            # An artifact cold-start warms only the bucket shapes its
            # writer recorded — the respawn path compiles what the dead
            # worker actually served, not the full grid.
            if artifact_hit and hasattr(engine, "warmup_from_manifest"):
                n = engine.warmup_from_manifest()
            else:
                n = engine.warmup()
            logger.info("worker %s warmed %s (%d rounds)",
                        self.worker_id, cfg.name, n)
        return engine

    def _model_busy(self, name: str) -> bool:
        """Eviction guard: a model with queued or decoding work is pinned
        resident — evicting it would drop in-flight generations."""
        pump = self._pumps.get(name)
        if pump is not None and pump.get_stats().get("in_flight", 0) > 0:
            return True
        engine = self.engines.get(name)
        if engine is not None and (getattr(engine, "n_live", 0)
                                   or getattr(engine, "n_waiting", 0)):
            return True
        return False

    def _on_model_evicted(self, name: str, engine) -> None:
        pump = self._pumps.pop(name, None)
        if pump is not None:
            pump.shutdown_nowait()
        logger.info("worker %s evicted model %s (resident budget)",
                    self.worker_id, name)

    def _install_engine(self, cfg: ModelConfig, engine) -> None:
        """Admit a built engine into the resident set (budget-evicting idle
        LRU models) and give continuous engines their rolling-batch pump."""
        self.model_manager.admit(cfg, engine)
        if hasattr(engine, "submit") and hasattr(engine, "step"):
            from ..serving.pump import EnginePump

            self._pumps[cfg.name] = EnginePump(
                engine,
                mixed_step_tokens=(
                    int(cfg.metadata.get("mixed_step_tokens", 0)) or None),
                event_log=self.events, model=cfg.name)

    def _check_idempotent(self, cfg: ModelConfig) -> bool:
        """True when ``cfg`` is already loaded with a compatible config;
        raises on an identity/feature mismatch (silently serving mismatched
        weights corrupts placement)."""
        if cfg.name not in self.engines:
            return False
        # idempotent when the MODEL IDENTITY matches (a worker preloaded
        # via CLI is a valid deploy target even if its engine knobs —
        # continuous, page sizes, batcher limits — differ from the deploy
        # request's defaults); a different identity is a real error
        have = self.model_configs[cfg.name]
        if _model_identity(have) != _model_identity(cfg):
            raise ValueError(
                f"model {cfg.name!r} already loaded with a different config"
            )
        need, got = _engine_features(cfg), _engine_features(have)
        if not need <= got:
            raise ValueError(
                f"model {cfg.name!r} already loaded with features "
                f"{sorted(got)} but this deploy needs {sorted(need)} "
                "— unload it first"
            )
        return True

    def load_model(self, cfg: ModelConfig) -> None:
        if self._check_idempotent(cfg):
            logger.info("worker %s: model %s already loaded (idempotent)",
                        self.worker_id, cfg.name)
            self.model_manager.touch(cfg.name)
            return
        t0 = time.perf_counter()
        engine = self._build_engine(cfg)
        artifact_hit = getattr(engine, "artifact_manifest", None) is not None
        self._install_engine(cfg, engine)
        load_s = time.perf_counter() - t0
        self.model_load_stats.add(load_s)
        self._last_load_s[cfg.name] = load_s
        logger.info("worker %s loaded model %s (%s) in %.2fs%s",
                    self.worker_id, cfg.name, cfg.architecture, load_s,
                    " [artifact cold-start]" if artifact_hit else "")

    async def load_model_async(self, cfg: ModelConfig) -> None:
        """Load off the event loop, on the single engine thread — serializes
        with in-flight generates (one program on the chip at a time) and two
        concurrent loads of the same name can't race the already-loaded
        check. Used by both the RPC handler and the CLI."""
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._executor, self.load_model, cfg)

    def unload_model(self, name: str) -> bool:
        engine = self.model_manager.remove(name)
        pump = self._pumps.pop(name, None)
        if pump is not None:
            pump.shutdown_nowait()
        if engine is None:
            return False
        logger.info("worker %s unloaded model %s", self.worker_id, name)
        return True

    # -- background staging + hot swap (cluster/model_manager.py) -----------

    def _serving_steps(self) -> int:
        """Total pump steps across every resident continuous engine — the
        step-timeline clock staging overlap is accounted against."""
        return sum(int(p.get_stats().get("steps", 0))
                   for p in self._pumps.values())

    def stage_model(self, cfg: ModelConfig):
        """Begin staging ``cfg`` in the background (side thread; the
        serving pumps keep dispatching). Idempotent while in flight; a
        no-op returning None when the model is already resident."""
        if cfg.name in self.engines and self._check_idempotent(cfg):
            return None
        return self.model_manager.stage(cfg,
                                        serving_steps=self._serving_steps)

    def swap_model(self, name: str,
                   probe_expected: Optional[List[int]] = None,
                   timeout: Optional[float] = None) -> Dict[str, Any]:
        """Activate a staged model: wait for its build, golden-gate it,
        admit it (budget-evicting idle LRU models), give it a pump.
        Synchronous — call off the event loop."""
        receipt = self.model_manager.swap(name, probe_expected=probe_expected,
                                          timeout=timeout)
        if not receipt.get("already_resident"):
            engine = self.engines[name]
            cfg = self.model_configs[name]
            if hasattr(engine, "submit") and hasattr(engine, "step"):
                from ..serving.pump import EnginePump

                self._pumps[name] = EnginePump(
                    engine,
                    mixed_step_tokens=(
                        int(cfg.metadata.get("mixed_step_tokens", 0)) or None),
                    event_log=self.events, model=name)
        return receipt

    # -- connection handling (loop + envelope in FramedServerMixin) -----------

    @property
    def max_frame_bytes(self) -> int:
        return self.config.max_frame_bytes

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        self._active_connections += 1
        try:
            await super()._handle_connection(reader, writer)
        finally:
            self._active_connections -= 1
            logger.debug("worker %s connection from %s closed",
                         self.worker_id, peer)

    async def _run_handler(self, method: str, handler, msg) -> Any:
        # generate/load_model legitimately run for minutes (first-call XLA
        # compile, checkpoint load) — their deadline belongs to the caller.
        # The server-side timeout only guards the cheap control methods.
        # drain carries its own timeout_s in the message.
        if method in ("generate", "load_model", "swap_model", "prefill",
                      "generate_prefilled", "prefill_generate", "drain"):
            return await handler(msg)
        return await asyncio.wait_for(
            handler(msg), timeout=self.config.request_timeout
        )

    def _envelope_extra(self) -> Dict[str, Any]:
        return {"worker_id": self.worker_id}

    def _timeout_error(self, method: str) -> str:
        # only control methods are wait_for-wrapped, so a timeout is probe
        # trouble, not a generate failure — it stays out of _error_count
        return f"request timed out after {self.config.request_timeout}s"

    def _on_handler_error(self, method: str, exc: Exception) -> None:
        if method in ("generate", "generate_stream"):
            # load sheds are the engine WORKING as configured, not a fault:
            # counting them would let sustained overload trip the same
            # error-rate signals a sick worker trips
            kind = getattr(exc, "rpc_error_kind", "")
            if kind == "overloaded":
                self._overloaded_count += 1
                return
            if kind == "deadline":
                # caller-imposed budget expired in OUR queue — policy, not
                # a fault; it has its own counter so dashboards can see it
                self._deadline_expired_count += 1
                return
            self._error_count += 1

    def _after_dispatch(self, method: str, req_id: str,
                        duration_s: float, response: Dict[str, Any]) -> None:
        if method in ("generate", "generate_stream"):
            self.latency.add(duration_s)
            logger.info("worker %s: %s id=%s %.1fms ok=%s",
                        self.worker_id, method, req_id, duration_s * 1e3,
                        response["success"])

    # -- RPC methods ---------------------------------------------------------

    async def _rpc_ping(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self._ping_count += 1
        # "mono": this process's perf_counter — the coordinator's clock-sync
        # pairs it with its own send/recv stamps (obs/clocksync.py)
        return {"worker_id": self.worker_id, "time": time.time(),
                "mono": time.perf_counter(),
                "models": sorted(self.engines),
                "staged": self.model_manager.staged_names(),
                "draining": self._draining}

    def _admit(self) -> None:
        """Admission gate for work-carrying verbs (generate/prefill family):
        a draining worker refuses new work with the typed draining shed."""
        if self._draining:
            raise WorkerDrainingError(
                f"worker {self.worker_id} is draining — retry on another "
                "replica")

    def _attach_worker_trace(self, result: GenerationResult,
                             t_recv: float) -> None:
        """Worker-side phase marks, riding the result's metadata back to
        the coordinator (cross-process tracing: ISSUE 4 leg 3). Offsets
        are seconds RELATIVE TO THIS WORKER'S RECEIVE TIME — the two
        processes share no clock, so the coordinator anchors them at its
        own ``dispatched`` mark (``RequestTrace.add_offsets``).
        ``first_token`` is the engine-measured TTFT (admission-relative,
        ≈ receive-relative; exact for pumped continuous engines, which
        stamp it from submit)."""
        result.metadata.setdefault("worker_trace", {
            "worker_id": self.worker_id,
            "offsets": {
                "received": 0.0,
                "first_token": float(result.ttft_s),
                "done": time.perf_counter() - t_recv,
            },
        })

    async def _rpc_generate(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        t_recv = time.perf_counter()
        self._admit()
        name, engine = self._engine_for(msg, "generate")
        reqs = [request_from_dict(d) for d in msg.get("requests", [])]
        if not reqs:
            raise ValueError("empty 'requests'")
        self._request_count += 1
        self._busy += 1
        try:
            pump = self._pumps.get(name)
            if pump is not None:
                # continuous engine: requests join the rolling decode batch —
                # concurrent connections share chunks instead of serializing
                # whole generations behind the executor
                results = await pump.generate(reqs)
            else:
                loop = asyncio.get_running_loop()
                results = await loop.run_in_executor(
                    self._executor, engine.generate, reqs
                )
        finally:
            self._busy -= 1
        # sheds are per-request RESULTS (finish_reason "overloaded"), so
        # they bypass _on_handler_error — count them here, still apart
        # from real errors
        self._overloaded_count += sum(
            1 for r in results if r.finish_reason == "overloaded")
        self._deadline_expired_count += sum(
            1 for r in results if r.finish_reason == "deadline")
        for r in results:
            self._attach_worker_trace(r, t_recv)
        return {"model": name, "results": [result_to_dict(r) for r in results]}

    # -- streaming (token chunks ahead of the final result) -----------------

    async def _rpc_generate_stream(self, msg: Dict[str, Any], send) -> Dict[str, Any]:
        """Stream one request's tokens as they decode: chunk frames
        ``{"tokens": [...]}`` ride the connection ahead of the final
        result envelope. Continuous engines only (the rolling batch emits
        per-chunk; a static engine runs to completion in one call — use
        ``generate`` there)."""
        self._admit()
        name, _engine = self._engine_for(msg, "generate")
        pump = self._pumps.get(name)
        if pump is None:
            raise ValueError(
                f"model {name!r} is not a continuous engine — streaming "
                "needs metadata.continuous=1")
        req = request_from_dict(msg.get("request") or {})
        t_recv = time.perf_counter()
        self._request_count += 1
        self._busy += 1
        try:
            queue: asyncio.Queue = asyncio.Queue()
            fut = asyncio.ensure_future(
                pump.generate_streaming(req, queue.put_nowait))
            result = await relay_stream(fut, queue, send)
        finally:
            self._busy -= 1
        self._attach_worker_trace(result, t_recv)
        return {"model": name, "result": result_to_dict(result)}

    # -- profiling (SURVEY.md §5 tracing plan: XLA/TPU timeline capture) ----

    async def _rpc_profile(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Start/stop a ``jax.profiler`` trace on this worker. The trace
        directory is loadable in TensorBoard/XProf for XLA timelines —
        the real-engine upgrade of the reference's wall-clock-only
        "tracing" (``src/worker.py:126-133``)."""
        import jax

        action = msg.get("action")
        if action == "start":
            if self._profiling_dir is not None:
                raise ValueError(
                    f"profiling already active -> {self._profiling_dir}")
            trace_dir = msg.get("trace_dir") or f"/tmp/{self.worker_id}-trace"
            jax.profiler.start_trace(trace_dir)
            self._profiling_dir = trace_dir
            # bracket the engine step timelines to the same window: the
            # jax trace shows the XLA/device side, the step timeline the
            # engine's dispatch-level view of the SAME interval
            for engine in self.engines.values():
                tl = getattr(engine, "timeline", None)
                if tl is not None:
                    tl.start_capture()
            return {"profiling": True, "trace_dir": trace_dir}
        if action == "stop":
            if self._profiling_dir is None:
                raise ValueError("profiling is not active")
            jax.profiler.stop_trace()
            out, self._profiling_dir = self._profiling_dir, None
            written: List[str] = []
            for name, engine in self.engines.items():
                tl = getattr(engine, "timeline", None)
                if tl is None:
                    continue
                try:
                    import os

                    os.makedirs(out, exist_ok=True)
                    path = os.path.join(out, f"step_timeline_{name}.json")
                    written.append(tl.dump(path, tl.stop_capture()))
                except Exception as e:  # timeline dump must not fail stop
                    logger.warning("worker %s: step-timeline dump for %s "
                                   "failed: %s", self.worker_id, name, e)
            return {"profiling": False, "trace_dir": out,
                    "step_timelines": written}
        raise ValueError(f"unknown profile action {action!r} "
                         "(use 'start' or 'stop')")

    # -- disaggregated prefill/decode (engine/disagg.py; SURVEY.md §2.3) ----

    def _engine_for(self, msg: Dict[str, Any], capability: str):
        name = msg.get("model")
        if not name:
            raise ValueError("missing 'model'")
        engine = self.engines.get(name)
        if engine is None:
            raise ValueError(f"model {name!r} not loaded "
                             f"(have: {sorted(self.engines)})")
        if not hasattr(engine, capability):
            raise ValueError(
                f"model {name!r} engine ({type(engine).__name__}) does not "
                f"support {capability!r} — wrong pool role?"
            )
        # every routed request refreshes the model's LRU position, so the
        # residency budget evicts genuinely idle models, not busy ones
        self.model_manager.touch(name)
        return name, engine

    async def _rpc_prefill(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill-pool op: run the prompt, return KV handoffs to the caller."""
        from ..engine.disagg import handoff_to_wire

        self._admit()
        name, engine = self._engine_for(msg, "prefill")
        reqs = [request_from_dict(d) for d in msg.get("requests", [])]
        if not reqs:
            raise ValueError("empty 'requests'")
        self._request_count += 1
        self._busy += 1
        try:
            loop = asyncio.get_running_loop()
            handoffs = await loop.run_in_executor(
                self._executor, engine.prefill, reqs
            )
        finally:
            self._busy -= 1
        return {"model": name,
                "handoffs": [handoff_to_wire(h) for h in handoffs]}

    async def _rpc_prefix_probe(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Decode-pool op: how many leading prompt tokens (page-aligned)
        does this engine's prefix cache already hold, per prompt? The
        disaggregated prefill worker uses the answer to ship delta
        handoffs (KV tail only). Advisory — admission re-checks and a
        shortfall surfaces as the typed ``stale_prefix`` result."""
        from ..engine.paged_kv import page_chain_hashes

        name, engine = self._engine_for(msg, "submit_prefilled")
        kv = getattr(engine, "kv", None)
        enabled = kv is not None and getattr(engine, "prefix_cache", False)
        # advertise this pool's page size so the sender can hash with it
        # on later probes even when its own config disagrees
        my_page = kv.page_size if enabled else 0
        out: List[int] = []
        if "hashes" in msg:
            # preferred form: 16-byte-per-page chain hashes (the
            # page_chain_hashes contract) — the sender never ships the
            # prompt twice. Hashes chain over page-sized token chunks, so
            # a page-size mismatch means no entry can match: answer 0s
            # (the sender re-hashes with the advertised size next probe).
            if not enabled or msg.get("page_size") != kv.page_size:
                out = [0] * len(msg["hashes"])
            else:
                out = [kv.probe_prefix([bytes(h) for h in hs])
                       * kv.page_size
                       for hs in msg["hashes"]]
            return {"model": name, "cached_tokens": out,
                    "page_size": my_page}
        for prompt in msg.get("prompts", []):    # legacy full-prompt probe
            if not enabled:
                out.append(0)
                continue
            matchable = (len(prompt) - 1) // kv.page_size
            hashes = page_chain_hashes(prompt, matchable, kv.page_size)
            out.append(kv.probe_prefix(hashes) * kv.page_size)
        return {"model": name, "cached_tokens": out, "page_size": my_page}

    # -- KV fabric (engine/kv_fabric.py) ------------------------------------

    async def _rpc_kv_export(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Fabric op: serialize the longest locally-resident full-page
        prefix of ``tokens`` as a checksummed wire dict (None when cold).
        Deliberately NOT gated by ``_admit()``: a DRAINING worker must
        keep exporting — the drain handoff pulls its hot prefixes out
        while in-flight work finishes."""
        from ..engine.kv_fabric import wire_nbytes

        name, engine = self._engine_for(msg, "kv_export")
        tokens = [int(t) for t in msg.get("tokens", [])]
        if not tokens:
            raise ValueError("missing 'tokens'")
        max_pages = int(msg.get("max_pages", 0))
        loop = asyncio.get_running_loop()
        wire = await loop.run_in_executor(
            self._executor, engine.kv_export, tokens, max_pages)
        if wire is not None:
            self._kv_fabric_exports += 1
            self._kv_fabric_export_bytes += wire_nbytes(wire)
            self.events.emit("fabric.export", model=name,
                             pages=len(wire.get("pages", ())) if isinstance(wire, dict) else 0)
        return {"model": name, "wire": wire}

    async def _rpc_kv_import(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Fabric op: validate + land an exported prefix in the local host
        tier and start its layer-wise restage. A rejected wire (checksum /
        geometry mismatch) stores NOTHING and reports ``rejected`` in the
        payload — the caller counts a fallback and the next admission pays
        normal prefill; wrong KV is never served. Not ``_admit()``-gated:
        pre-warm runs before the worker takes traffic (half-open)."""
        from ..engine.kv_fabric import FabricRejected, wire_nbytes

        name, engine = self._engine_for(msg, "kv_import")
        wire = msg.get("wire")
        if not wire:
            raise ValueError("missing 'wire'")
        loop = asyncio.get_running_loop()
        try:
            imported = await loop.run_in_executor(
                self._executor, engine.kv_import, wire)
        except FabricRejected as exc:
            self._kv_fabric_import_fallbacks += 1
            return {"model": name, "imported_pages": 0,
                    "rejected": str(exc)}
        self._kv_fabric_imports += 1
        self._kv_fabric_import_bytes += wire_nbytes(wire)
        self.events.emit("fabric.import", model=name, pages=int(imported))
        return {"model": name, "imported_pages": int(imported)}

    async def _rpc_generate_prefilled(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Decode-pool op: admit handed-off KV, decode to completion."""
        from ..engine.disagg import handoff_from_wire

        self._admit()
        name, _engine = self._engine_for(msg, "submit_prefilled")
        pump = self._pumps.get(name)
        if pump is None:
            raise ValueError(
                f"model {name!r} is not a continuous engine — the decode "
                "pool needs metadata.continuous=1"
            )
        reqs = [request_from_dict(d) for d in msg.get("requests", [])]
        handoffs = [handoff_from_wire(d) for d in msg.get("handoffs", [])]
        if len(reqs) != len(handoffs) or not reqs:
            raise ValueError("requests and handoffs must align and be non-empty")
        self._request_count += 1
        self._busy += 1
        try:
            results = await pump.generate_prefilled(list(zip(reqs, handoffs)))
        finally:
            self._busy -= 1
        return {"model": name, "results": [result_to_dict(r) for r in results]}

    async def _rpc_prefill_generate(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Prefill-pool op: prefill locally, hand the KV to the decode peer
        at (decode_host, decode_port), relay its finished results.

        One KV hop (prefill → decode over DCN) — the coordinator only
        carries requests and token results.

        ``pipeline_groups`` (default 1 = off) overlaps the KV transfer
        with prefill AND decode: the request batch splits into contiguous
        groups, and because all prefill compute serializes on the single
        engine-executor thread, group g+1's prefill runs while group g's
        KV is in flight to the peer and its decode slots are already
        admitted into the rolling batch. The first group's TTFT stops
        paying for the whole batch's prefill + one monolithic transfer
        (VERDICT r2 item 3's overlap). Worth it when per-request prefill +
        transfer is substantial (long prompts at scale); for short cheap
        prompts the early groups decode at low occupancy and the overlap
        buys nothing — measured per-config in examples/disagg_bench.py.
        """
        from ..engine.disagg import handoff_to_wire

        self._admit()
        name, engine = self._engine_for(msg, "prefill")
        host, port = msg.get("decode_host"), msg.get("decode_port")
        if not host or not port:
            raise ValueError("missing 'decode_host'/'decode_port'")
        reqs_wire = msg.get("requests", [])
        reqs = [request_from_dict(d) for d in reqs_wire]
        if not reqs:
            raise ValueError("empty 'requests'")
        self._request_count += 1
        loop = asyncio.get_running_loop()
        peer = self._peer_clients.get((host, int(port)))
        if peer is None:
            peer = WorkerClient(host, int(port),
                                max_frame=self.config.max_frame_bytes)
            self._peer_clients[(host, int(port))] = peer

        # envelope headroom of 1 MiB, but never below half the frame for
        # small configured limits (budget must stay usable, not negative)
        budget = max(self.config.max_frame_bytes - 1_048_576,
                     self.config.max_frame_bytes // 2)
        # peer_timeout travels IN the message (the client-side ``timeout``
        # kwarg only bounds the caller's own read and is never serialized)
        peer_timeout = float(msg.get("peer_timeout", 300.0))
        decode_model = msg.get("decode_model", name)
        n_groups = max(1, min(int(msg.get("pipeline_groups", 1)),
                              len(reqs)))
        gsize = -(-len(reqs) // n_groups)
        groups = [list(range(a, min(a + gsize, len(reqs))))
                  for a in range(0, len(reqs), gsize)]

        # oversize-handoff config errors must fire BEFORE any group ships:
        # a mid-pipeline raise would orphan earlier groups' decodes on the
        # peer (r3 review finding). Handoff size is deterministic from the
        # prompt length — 2·L·Hkv·Dh·itemsize bytes/token — so no prefill
        # is needed to validate every request up front.
        spec = engine.spec
        tok_bytes = (2 * spec.n_layers * spec.n_kv_heads * spec.head_dim
                     * engine.kv_dtype.itemsize)
        for r in reqs:
            # the engine tail-truncates overlong prompts, so cap the
            # estimate the same way
            s = min(len(r.prompt), engine.max_seq_len - 1) * tok_bytes + 4096
            if s > budget:
                raise ValueError(
                    f"handoff for request {r.request_id!r} would be ~{s} "
                    f"bytes — exceeds the {self.config.max_frame_bytes}"
                    "-byte frame limit; raise ServerConfig.max_frame_bytes "
                    "on both pools"
                )

        async def run_group(g_idxs: List[int]) -> List[Any]:
            # prefill THIS group (serializes with other groups on the
            # engine thread — that serialization is the pipeline)
            handoffs = await loop.run_in_executor(
                self._executor, engine.prefill, [reqs[i] for i in g_idxs]
            )
            # prefix-aware delta handoff: probe which page-aligned prompt
            # heads the decode pool's prefix cache already holds and ship
            # only the KV tails. The probe ships 16-byte-per-page chain
            # hashes (page_chain_hashes — the prompt itself is shipped
            # exactly once, inside generate_prefilled). Advisory — a
            # reclaimed page surfaces as a typed per-request stale_prefix
            # result below, answered by re-shipping that request's full KV.
            from ..engine.disagg import trim_handoff
            from ..engine.paged_kv import page_chain_hashes

            full_handoffs = handoffs             # kept for stale re-sends
            # hash with the DECODE pool's page size: its prefix index is
            # what the chain hashes must match. Learned from the peer's
            # probe responses (cached on the peer client); until the first
            # response, fall back to this pool's configured page_size —
            # the pools share EngineConfig on a standard disagg deploy.
            # PrefillEngine has no kv, so the config is the only local
            # source (r4 review finding).
            page_size = (getattr(peer, "probe_page_size", 0)
                         or getattr(getattr(engine, "kv", None),
                                    "page_size", 0)
                         or getattr(engine.config, "page_size", 0))
            cached: List[int] = []
            if page_size > 0:
                try:
                    probe = await peer.call(
                        "prefix_probe", model=decode_model,
                        page_size=page_size,
                        hashes=[page_chain_hashes(
                                    reqs[i].prompt[-h.prompt_len:],
                                    (h.prompt_len - 1) // page_size,
                                    page_size)
                                for i, h in zip(g_idxs, handoffs)],
                        timeout=peer_timeout,
                    )
                    cached = probe.get("cached_tokens", [])
                    if int(probe.get("page_size", 0)) > 0:
                        peer.probe_page_size = int(probe["page_size"])
                except RPCError:
                    cached = []                  # peer predates the probe op
            cached = cached + [0] * (len(handoffs) - len(cached))
            # probe counts are page-aligned and capped below prompt_len by
            # construction ((len-1)//P pages) — the guard is belt/braces
            handoffs = [trim_handoff(h, c) if 0 < c < h.prompt_len else h
                        for h, c in zip(handoffs, cached)]
            # KV handoffs are big (≈2·L·Hkv·Dh·itemsize bytes/token) —
            # pack into as many generate_prefilled frames as the limit
            # needs. An oversize SINGLE handoff is a config error (raise
            # as one), never a DecodePeerError: misclassifying it would
            # dent the healthy decode worker's health on every long prompt
            wires = [handoff_to_wire(h) for h in handoffs]
            sizes = [len(w["k"]) + len(w["v"]) + 4096 for w in wires]
            self._handoff_bytes_shipped += sum(
                len(w["k"]) + len(w["v"]) for w in wires)
            # the up-front prompt-length estimate already bounds every
            # wire (trimming only shrinks them) — a violation would be an
            # accounting bug, but it must stay a REAL check (not an
            # assert, which -O strips): an oversized frame would otherwise
            # surface as a raw framing error mid-pipeline, orphaning
            # already-shipped groups. Nothing from THIS group has shipped
            # yet, so raising here is safe.
            if any(s > budget for s in sizes):
                raise ValueError(
                    "handoff wire exceeded the up-front size bound "
                    f"({max(sizes)} > {budget} bytes) — the per-token "
                    "estimate in generate_remote_decode has drifted from "
                    "handoff_to_wire; fix the estimate"
                )
            frames: List[List[int]] = []
            cur: List[int] = []
            cur_bytes = 0
            for j, s in enumerate(sizes):
                if cur and cur_bytes + s > budget:
                    frames.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(j)
                cur_bytes += s
            if cur:
                frames.append(cur)

            async def _send(js: List[int]) -> Any:
                return await peer.call(
                    "generate_prefilled", model=decode_model,
                    requests=[reqs_wire[g_idxs[j]] for j in js],
                    handoffs=[wires[j] for j in js],
                    timeout=peer_timeout,
                )

            parts = await asyncio.gather(
                *(asyncio.ensure_future(_send(js)) for js in frames))
            out: List[Any] = [None] * len(g_idxs)
            for js, part in zip(frames, parts):
                for j, r in zip(js, part["results"]):
                    out[j] = r
            # a delta handoff can lose its race (prefix pages reclaimed
            # between probe and admission): re-ship those requests' FULL
            # KV, one call each — the rare path buys simplicity
            stale = [j for j, r in enumerate(out)
                     if isinstance(r, dict)
                     and r.get("finish_reason") == "stale_prefix"]
            for j in stale:
                full_wire = handoff_to_wire(full_handoffs[j])
                self._handoff_bytes_shipped += (len(full_wire["k"])
                                                + len(full_wire["v"]))
                retry = await peer.call(
                    "generate_prefilled", model=decode_model,
                    requests=[reqs_wire[g_idxs[j]]],
                    handoffs=[full_wire],
                    timeout=peer_timeout,
                )
                out[j] = retry["results"][0]
            return out

        self._busy += 1
        tasks = [asyncio.ensure_future(run_group(g)) for g in groups]
        try:
            group_outs = await asyncio.gather(*tasks)
        except BaseException as e:
            # one group failing must CANCEL the siblings — the caller
            # will re-dispatch the whole batch elsewhere, and an orphaned
            # group would keep burning decode slots for discarded output
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if isinstance(e, (OSError, ConnectionError, asyncio.TimeoutError,
                              asyncio.IncompleteReadError, EOFError,
                              FrameError)):
                raise DecodePeerError(
                    f"decode peer {host}:{port} unreachable: "
                    f"{type(e).__name__}: {e}"
                ) from e
            raise
        finally:
            self._busy -= 1
        results: List[Any] = [None] * len(reqs_wire)
        for g_idxs, outs in zip(groups, group_outs):
            for i, r in zip(g_idxs, outs):
                results[i] = r
        return {"model": name, "results": results,
                "decode_worker": f"{host}:{port}"}

    async def _rpc_load_model(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        cfg = ModelConfig.from_dict(msg["config"])
        await self.load_model_async(cfg)
        return {"loaded": cfg.name,
                # measured engine-construction wall time (idempotent
                # re-loads report the original) — demo/supervisor receipts
                "load_s": self._last_load_s.get(cfg.name, 0.0)}

    async def _rpc_stage_model(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Begin background staging; returns immediately (the build runs on
        a side thread — dispatch is never displaced). ``swap_model`` later
        waits for it, probes it, and installs it."""
        cfg = ModelConfig.from_dict(msg["config"])
        rec = self.stage_model(cfg)
        if rec is not None:
            self.events.emit("model.stage", model=cfg.name)
        return {"staging": cfg.name,
                "already_resident": rec is None}

    async def _rpc_swap_model(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Activate a staged model (probe-gated). Runs on the engine
        executor: the wait for the staging thread happens off the event
        loop, and installation serializes with in-flight loads."""
        name = msg.get("model")
        if not name:
            raise ValueError("missing 'model'")
        probe = msg.get("probe")
        timeout = msg.get("timeout_s")
        loop = asyncio.get_running_loop()
        try:
            receipt = await loop.run_in_executor(
                self._executor,
                lambda: self.swap_model(
                    name,
                    probe_expected=([int(t) for t in probe]
                                    if probe else None),
                    timeout=float(timeout) if timeout else None))
            if not receipt.get("already_resident"):
                self.events.emit("model.swap", model=name)
            return receipt
        except (ModelProbeError, ModelStageError) as e:
            # typed application errors — the RPC envelope carries them as
            # failures without denting transport-level health
            raise ValueError(str(e)) from e

    async def _rpc_resident_models(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"worker_id": self.worker_id,
                "resident": sorted(self.engines),
                "staged": self.model_manager.staged_names(),
                "resident_bytes": self.model_manager.resident_bytes_used(),
                "max_resident_models": self.config.max_resident_models,
                "resident_bytes_budget": self.config.resident_bytes}

    async def _rpc_unload_model(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"unloaded": self.unload_model(msg["model"])}

    async def _rpc_list_models(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"models": {n: c.to_dict() for n, c in self.model_configs.items()}}

    async def _rpc_metrics(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return self.get_metrics()

    def _obs_collect(self) -> None:
        obs_collectors.clear_worker_labelled(self.obs_registry)
        obs_collectors.apply_worker(self.obs_registry, self.get_metrics())
        obs_collectors.apply_event_log(self.obs_registry,
                                       self.events.get_stats(),
                                       proc=self.worker_id)

    def metrics_text(self) -> str:
        """This worker's metrics as OpenMetrics exposition text. The
        render is self-timed (obs_scrape_seconds / obs_scrape_ok) — the
        sample lands on the NEXT exposition, it can't time itself into
        its own output."""
        t0 = time.perf_counter()
        try:
            text = self.obs_registry.render()
        except Exception:
            obs_collectors.record_scrape(self.obs_registry, self.worker_id,
                                         time.perf_counter() - t0, ok=False)
            raise
        obs_collectors.record_scrape(self.obs_registry, self.worker_id,
                                     time.perf_counter() - t0, ok=True)
        return text

    async def _rpc_metrics_text(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"content_type": OPENMETRICS_CONTENT_TYPE,
                "text": self.metrics_text()}

    async def _http_get(self, path: str) -> Optional[Tuple[str, bytes]]:
        if path == "/metrics":
            return (OPENMETRICS_CONTENT_TYPE,
                    self.metrics_text().encode("utf-8"))
        return None

    async def _rpc_drain(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Graceful drain: stop admitting (new work gets the typed
        ``draining`` shed, probes see ``draining`` in ping), wait for
        in-flight work — pumps' inboxes/futures and the ``_busy`` admission
        counter — to empty, then report a per-model summary so the caller
        can account for what this worker was holding (KV/prefix/token
        counters) before removing it. Idempotent; ``timeout_s`` rides in
        the message (this verb is exempt from the server-side timeout)."""
        timeout_s = float(msg.get("timeout_s", 30.0))
        if not self._draining:
            self._draining = True
            self._drain_count += 1
            self.events.emit("drain.begin")
            logger.info("worker %s draining (timeout %.1fs)",
                        self.worker_id, timeout_s)
        deadline = time.monotonic() + timeout_s
        drained = True
        for pump in self._pumps.values():
            remaining = max(0.0, deadline - time.monotonic())
            if not await pump.drain(remaining):
                drained = False
        while self._busy > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        if self._busy > 0:
            drained = False
        summary: Dict[str, Any] = {}
        for name, engine in self.engines.items():
            m = engine.get_metrics()
            summary[name] = {
                k: v for k, v in m.items()
                if isinstance(v, (int, float)) and any(
                    t in k for t in ("prefix", "kv", "page", "token",
                                     "request", "waiting", "live"))
            }
        self.events.emit("drain.done", drained=drained,
                         in_flight=self._busy)
        return {"worker_id": self.worker_id, "drained": drained,
                "in_flight": self._busy, "models": summary}

    async def _rpc_events(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Flight-recorder collection verb: this worker's event ring plus
        every resident continuous engine's step timeline (perf_counter
        axis), with a fresh ``mono`` stamp so the caller can re-anchor."""
        timelines: Dict[str, List[Dict[str, Any]]] = {}
        for name, engine in self.engines.items():
            tl = getattr(engine, "timeline", None)
            if tl is not None:
                timelines[name] = tl.events()
        return {"worker_id": self.worker_id,
                "mono": time.perf_counter(),
                "wall": time.time(),
                "ring": self.events.snapshot(),
                "timelines": timelines}

    async def _rpc_shutdown(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self._shutdown_event.set()
        return {"shutting_down": True}

    # -- metrics (reference src/worker.py:186-209) ----------------------------

    def get_metrics(self) -> Dict[str, Any]:
        process: Dict[str, Any] = {}
        try:
            import psutil

            p = psutil.Process()
            process = {
                "rss_bytes": p.memory_info().rss,
                "cpu_percent": p.cpu_percent(interval=None),
                "num_threads": p.num_threads(),
            }
        # graftlint: ok[swallowed-transport-error] psutil is optional (undeclared reference dep); process introspection, no peer involved
        except Exception:
            pass
        return {
            "worker_id": self.worker_id,
            "uptime_s": time.time() - self._started_at if self._started_at else 0.0,
            "request_count": self._request_count,
            "error_count": self._error_count,
            "overloaded_count": self._overloaded_count,
            "deadline_expired_count": self._deadline_expired_count,
            "draining": 1 if self._draining else 0,
            "drain_count": self._drain_count,
            "injected_faults": (
                self.fault_plan.injected_count(self._fault_scope())
                if self.fault_plan is not None else 0),
            "handoff_bytes_shipped": self._handoff_bytes_shipped,
            "kv_fabric_exports": self._kv_fabric_exports,
            "kv_fabric_imports": self._kv_fabric_imports,
            "kv_fabric_export_bytes": self._kv_fabric_export_bytes,
            "kv_fabric_import_bytes": self._kv_fabric_import_bytes,
            "kv_fabric_import_fallbacks": self._kv_fabric_import_fallbacks,
            "ping_count": self._ping_count,          # probes counted apart
            "active_connections": self._active_connections,
            "latency": self.latency.snapshot(),
            "model_load": self.model_load_stats.snapshot(),
            "artifact_hits": self._artifact_hits,
            "artifact_misses": self._artifact_misses,
            # multi-model residency (cluster/model_manager.py): resident/
            # staged gauges, stage/swap latency histograms, eviction and
            # probe-reject counters, measured staging↔dispatch overlap
            **self.model_manager.get_stats(),
            "models": {name: eng.get_metrics()
                       for name, eng in self.engines.items()},
            # pump stats without the engine sub-dict ("models" above
            # already carries every engine's metrics once)
            "pumps": {name: {k: v for k, v in pump.get_stats().items()
                             if k != "engine"}
                      for name, pump in self._pumps.items()},
            "process": process,
        }


# --------------------------------------------------------------------------
# client

class WorkerClient(FramedRPCClient):
    """Persistent framed-RPC client for one worker.

    The reference has no client class at all — callers hand-roll
    ``asyncio.open_connection`` (only the health probes do,
    ``src/router.py:287-292``). One connection is reused across calls and
    transparently re-established after a drop (``utils/rpc.py``).
    """

    # convenience wrappers -----------------------------------------------

    async def ping(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        return await self.call("ping", timeout=timeout)

    async def events(self, timeout: Optional[float] = None) -> Dict[str, Any]:
        """Flight-recorder collection: event ring + step timelines."""
        return await self.call("events", timeout=timeout)

    async def generate(
        self, model: str, requests: List[GenerationRequest],
        timeout: Optional[float] = None,
    ) -> List[GenerationResult]:
        result = await self.call(
            "generate", model=model,
            requests=[request_to_dict(r) for r in requests],
            timeout=timeout,
        )
        return [result_from_dict(d) for d in result["results"]]

    async def generate_stream(
        self, model: str, request: GenerationRequest, on_tokens,
        timeout: Optional[float] = None,
    ) -> GenerationResult:
        """Stream one request: ``on_tokens(tokens)`` fires per decoded
        chunk; returns the final (authoritative) result. ``timeout``
        bounds the gap between frames, not the whole generation."""
        result = await self.call_stream(
            "generate_stream",
            lambda frame: on_tokens(list(frame.get("tokens", []))),
            model=model, request=request_to_dict(request),
            timeout=timeout,
        )
        return result_from_dict(result["result"])

    async def prefill(self, model: str, requests: List[GenerationRequest],
                      timeout: Optional[float] = None) -> List[Any]:
        """Prefill-pool call: returns ``PrefillHandoff`` objects."""
        from ..engine.disagg import handoff_from_wire

        result = await self.call(
            "prefill", model=model,
            requests=[request_to_dict(r) for r in requests],
            timeout=timeout,
        )
        return [handoff_from_wire(d) for d in result["handoffs"]]

    async def generate_prefilled(
        self, model: str, requests: List[GenerationRequest],
        handoffs: List[Any], timeout: Optional[float] = None,
    ) -> List[GenerationResult]:
        """Decode-pool call: requests + KV handoffs → finished results."""
        from ..engine.disagg import handoff_to_wire

        result = await self.call(
            "generate_prefilled", model=model,
            requests=[request_to_dict(r) for r in requests],
            handoffs=[handoff_to_wire(h) for h in handoffs],
            timeout=timeout,
        )
        return [result_from_dict(d) for d in result["results"]]

    async def prefill_generate(
        self, model: str, requests: List[GenerationRequest],
        decode_host: str, decode_port: int,
        decode_model: Optional[str] = None,
        timeout: Optional[float] = None,
        pipeline_groups: int = 1,
    ) -> List[GenerationResult]:
        """Disaggregated end-to-end: prefill here, decode at the peer.

        ``timeout`` is the decode budget (serialized as ``peer_timeout``
        for the prefill worker's wait on its peer); this call itself waits
        2× that, leaving headroom for prefill + KV transfer — otherwise a
        decode that finishes inside its allowance could still time out
        here and falsely dent the healthy prefill worker.
        ``pipeline_groups`` > 1 overlaps prefill with KV transfer + decode
        admission (see ``WorkerServer._rpc_prefill_generate``)."""
        budget = timeout if timeout is not None else self.timeout
        result = await self.call(
            "prefill_generate", model=model,
            requests=[request_to_dict(r) for r in requests],
            decode_host=decode_host, decode_port=decode_port,
            decode_model=decode_model or model,
            peer_timeout=budget, pipeline_groups=pipeline_groups,
            timeout=2.0 * budget,
        )
        return [result_from_dict(d) for d in result["results"]]

    async def load_model(self, cfg: ModelConfig,
                         timeout: Optional[float] = None) -> Dict[str, Any]:
        """Load ``cfg`` on the worker; returns the measured-load receipt
        ({loaded, load_s}) — the cold-start half of the staged-swap
        latency comparison."""
        return await self.call("load_model", config=cfg.to_dict(),
                               timeout=timeout if timeout is not None
                               else 300.0)

    async def unload_model(self, name: str) -> bool:
        result = await self.call("unload_model", model=name)
        return bool(result["unloaded"])

    async def stage_model(self, cfg: ModelConfig,
                          timeout: Optional[float] = None) -> Dict[str, Any]:
        """Begin background staging on the worker; returns immediately."""
        return await self.call("stage_model", config=cfg.to_dict(),
                               timeout=timeout)

    async def swap_model(self, name: str,
                         probe: Optional[List[int]] = None,
                         timeout: Optional[float] = None) -> Dict[str, Any]:
        """Activate a staged model; ``probe`` is the expected golden-probe
        token list for engines without an artifact manifest. Returns the
        worker's swap receipt ({swapped, stage_s, swap_s, evicted})."""
        budget = timeout if timeout is not None else 300.0
        return await self.call(
            "swap_model", model=name,
            probe=[int(t) for t in probe] if probe else None,
            timeout_s=budget, timeout=budget + 10.0)

    async def resident_models(self) -> Dict[str, Any]:
        """The worker's resident + staged model sets and byte budget."""
        return await self.call("resident_models")

    async def kv_export(self, model: str, tokens: List[int],
                        max_pages: int = 0,
                        timeout: Optional[float] = None
                        ) -> Optional[Dict[str, Any]]:
        """Fabric pull: the worker's wire dict for ``tokens``' longest
        resident full-page prefix, or None when it holds nothing."""
        result = await self.call(
            "kv_export", model=model, tokens=[int(t) for t in tokens],
            max_pages=int(max_pages), timeout=timeout)
        return result.get("wire")

    async def kv_import(self, model: str, wire: Dict[str, Any],
                        timeout: Optional[float] = None) -> Dict[str, Any]:
        """Fabric push: land an exported wire in the worker's host tier.
        Returns ``{imported_pages, rejected?}`` — a checksum/geometry
        reject comes back typed in the payload, not as a transport error."""
        return await self.call("kv_import", model=model, wire=wire,
                               timeout=timeout)

    async def drain(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Gracefully drain the worker: stop admission, wait for in-flight
        work, return its per-model summary. The RPC read allowance adds
        headroom over the worker-side wait."""
        return await self.call("drain", timeout_s=timeout_s,
                               timeout=timeout_s + 10.0)

    async def metrics(self) -> Dict[str, Any]:
        return await self.call("metrics")

    async def metrics_text(self) -> str:
        """The worker's OpenMetrics exposition text (``/metrics`` body)."""
        result = await self.call("metrics_text")
        return str(result["text"])

    async def shutdown(self) -> None:
        await self.call("shutdown")


# worker-reported request failure (distinct from transport failure)
WorkerRPCError = RPCError
