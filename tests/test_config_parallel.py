"""Config-driven parallel serving: ``ModelConfig.metadata`` tp/sp/dp builds
the mesh + shardings inside ``engine_from_config``, so tensor- and
sequence-parallel placement deploys through the same CLI / coordinator /
config-file path as everything else (the reference's registry records
placement but its engine can't act on it — SURVEY.md §2.3)."""

import jax
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import ModelConfig
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import engine_from_config


def _cfg(**meta):
    return ModelConfig(name="m", architecture="llama-tiny", dtype="float32",
                       max_batch_size=2, max_seq_len=128, metadata=meta)


def test_tp_metadata_builds_sharded_continuous_engine():
    eng = engine_from_config(_cfg(continuous=1, page_size=16, tp=4))
    wq = eng.params["blocks"]["wq"]
    assert "tp" in str(wq.sharding.spec)
    # page pools sharded too (per-chip KV HBM drops with tp)
    assert "tp" in str(eng.kv.k_pages.sharding.spec)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3, 4],
                                          max_new_tokens=6)])[0]
    assert len(out.tokens) == 6
    # parity with an unsharded engine on the same params is covered by
    # tests/test_parallel.py; here the contract is the CONFIG path works


def test_sp_metadata_builds_sp_prefill_static_engine():
    plain = engine_from_config(_cfg(prefill_buckets=[64]))
    sp = engine_from_config(_cfg(sp=4, dp=2, prefill_buckets=[64]))
    # same seed => same random init => token-identical greedy output
    req = lambda: GenerationRequest(prompt=list(range(1, 50)),
                                    max_new_tokens=8)
    assert plain.generate([req()])[0].tokens == sp.generate([req()])[0].tokens


def test_sp_prefill_pool_from_config():
    eng = engine_from_config(_cfg(role="prefill", sp=4,
                                  prefill_buckets=[64]))
    h = eng.prefill([GenerationRequest(prompt=list(range(1, 40)),
                                       max_new_tokens=4,
                                       request_id="r1")])[0]
    assert h.prompt_len == 39 and h.k.shape[1] == 39


def test_continuous_plus_sp_rejected():
    with pytest.raises(ValueError, match="prefill-phase"):
        engine_from_config(_cfg(continuous=1, sp=4))


def test_quantized_plus_mesh_rejected():
    cfg = _cfg(tp=4)
    cfg.quantized = True
    with pytest.raises(ValueError, match="quantized"):
        engine_from_config(cfg)


def test_speculative_plus_mesh_rejected():
    with pytest.raises(ValueError, match="unsharded"):
        engine_from_config(_cfg(tp=4, speculative=2,
                                draft_size="llama-tiny"))


def test_too_many_devices_requested():
    with pytest.raises(ValueError, match="devices"):
        engine_from_config(_cfg(tp=64))


def test_dp_without_sp_rejected():
    """dp shards nothing in the tp-only serving path — accepting it would
    silently waste half the slice."""
    with pytest.raises(ValueError, match="load balancer"):
        engine_from_config(_cfg(continuous=1, dp=2, tp=4))
