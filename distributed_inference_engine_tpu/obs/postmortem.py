"""Crash post-mortem bundles (ISSUE 19 leg 4).

When a worker dies the evidence used to die with it: its event ring,
its place in the fleet timeline, the fault that killed it. A bundle is
one directory capturing everything the coordinator can still reach at
the moment of a supervisor-detected death, crash-loop open, upgrade
rollback, or chaos-leg failure:

    <dir>/<bundle-name>/
        manifest.json     reason, dead workers, file inventory, counts
        trace.json        merged fleet Perfetto trace (clocksync)
        metrics.prom      OpenMetrics registry snapshot at dump time
        rings.json        survivors' event rings (fresh collection)
        dead_rings.json   dead workers' LAST-KNOWN rings from the
                          coordinator's collection cache
        faults.json       the chaos fault ledger (plan.sequence())

Every JSON file goes through ``utils.files.atomic_write_json`` and the
``.prom`` snapshot through ``atomic_write`` — a crash mid-dump never
leaves a half-parseable bundle. Writing is best-effort by contract:
callers fire it from supervision paths and must never let a dump
failure take down the control loop, so ``write_bundle`` itself only
raises for an unusable destination directory.

No jax imports (package discipline — see ``obs/__init__``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

from ..utils.files import atomic_write, atomic_write_json

BUNDLE_SCHEMA = 1


def _bundle_name(dir_path: str, reason: str) -> str:
    """Collision-free bundle directory name: wall-clock stamp + reason,
    suffixed with a counter when two dumps land in the same second."""
    stamp = time.strftime("%Y%m%d-%H%M%S")
    safe = "".join(c if (c.isalnum() or c in "-_") else "-"
                   for c in reason) or "unknown"
    base = f"postmortem-{stamp}-{safe}"
    name, n = base, 1
    while os.path.exists(os.path.join(dir_path, name)):
        name = f"{base}-{n}"
        n += 1
    return name


def write_bundle(
    dir_path: str,
    reason: str,
    *,
    trace: Optional[Dict[str, Any]] = None,
    metrics_text: str = "",
    event_rings: Optional[Dict[str, Dict[str, Any]]] = None,
    dead_rings: Optional[Dict[str, Dict[str, Any]]] = None,
    fault_ledger: Optional[Sequence] = None,
    dead_workers: Sequence[str] = (),
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Dump one post-mortem bundle under ``dir_path``; returns the
    bundle directory path.

    ``event_rings`` / ``dead_rings`` map process name → ring
    ``snapshot()`` dicts; ``trace`` is a merged Chrome trace object
    (``clocksync.merge_fleet_trace``); ``fault_ledger`` is the
    order-independent ``FaultPlan.sequence()`` (or an equivalent list).
    Only the files whose payload was provided are written — the
    manifest records which, so bundle readers need no sniffing."""
    os.makedirs(dir_path, exist_ok=True)
    bundle = os.path.join(dir_path, _bundle_name(dir_path, reason))
    os.makedirs(bundle, exist_ok=True)

    files: List[str] = []
    if trace is not None:
        atomic_write_json(os.path.join(bundle, "trace.json"), trace,
                          indent=0)
        files.append("trace.json")
    if metrics_text:
        atomic_write(os.path.join(bundle, "metrics.prom"),
                     lambda f: f.write(metrics_text))
        files.append("metrics.prom")
    if event_rings is not None:
        atomic_write_json(os.path.join(bundle, "rings.json"), event_rings)
        files.append("rings.json")
    if dead_rings is not None:
        atomic_write_json(os.path.join(bundle, "dead_rings.json"),
                          dead_rings)
        files.append("dead_rings.json")
    if fault_ledger is not None:
        atomic_write_json(os.path.join(bundle, "faults.json"),
                          [list(e) if isinstance(e, tuple) else e
                           for e in fault_ledger])
        files.append("faults.json")

    manifest = {
        "schema": BUNDLE_SCHEMA,
        "reason": reason,
        "wall_time": time.time(),
        "dead_workers": sorted(str(w) for w in dead_workers),
        "files": sorted(files),
        "counts": {
            "trace_events": len((trace or {}).get("traceEvents", ())),
            "rings": len(event_rings or {}),
            "dead_rings": len(dead_rings or {}),
            "faults": len(fault_ledger or ()),
        },
    }
    if extra:
        manifest["extra"] = extra
    atomic_write_json(os.path.join(bundle, "manifest.json"), manifest)
    return bundle


def read_bundle(bundle: str) -> Dict[str, Any]:
    """Load a bundle back (receipt printers, tests). Returns the
    manifest plus each present payload under its file stem."""
    import json

    out: Dict[str, Any] = {}
    with open(os.path.join(bundle, "manifest.json")) as f:
        out["manifest"] = json.load(f)
    for fname in out["manifest"].get("files", ()):
        p = os.path.join(bundle, fname)
        stem = os.path.splitext(fname)[0]
        if fname.endswith(".json"):
            with open(p) as f:
                out[stem] = json.load(f)
        else:
            with open(p) as f:
                out[stem] = f.read()
    return out


def list_bundles(dir_path: str) -> List[str]:
    """Bundle directories under ``dir_path``, oldest first (name order —
    names embed the wall-clock stamp)."""
    if not os.path.isdir(dir_path):
        return []
    return sorted(
        os.path.join(dir_path, n) for n in os.listdir(dir_path)
        if n.startswith("postmortem-")
        and os.path.isfile(os.path.join(dir_path, n, "manifest.json")))
