"""Satellite rule: the declared dependency surface must match reality.

The seed repo shipped an EMPTY requirements.txt while the worker metrics
path quietly imported ``psutil`` — the classic undeclared-dependency
drift. ``undeclared-import`` walks every Import/ImportFrom in the
analyzed set (function-local lazy imports included), classifies the
top-level module (stdlib / local / third-party), and requires every
third-party module to appear in requirements.txt. The reverse direction
is checked too: a requirement nothing imports is flagged as stale.

requirements.txt itself is generated from a ``--format=json`` pass of
this rule (see docs/static_analysis.md for the refresh workflow).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, Project, Rule, register

REQUIREMENTS = "requirements.txt"

# import name -> PyPI distribution name, where they differ
DIST_NAMES = {
    "yaml": "pyyaml",
    "orbax": "orbax-checkpoint",
}
# distributions whose import name differs (normalized, reverse direction)
_IMPORT_OF_DIST = {v: k for k, v in DIST_NAMES.items()}

_STDLIB: Set[str] = set(getattr(sys, "stdlib_module_names", ())) | {
    "__future__",
    "tomllib",   # stdlib from 3.11; config.py falls back to tomli below
}
_REQ_LINE = re.compile(r"^([A-Za-z0-9_.\-]+)")


def _norm(name: str) -> str:
    return name.lower().replace("-", "_").replace(".", "_")


def _top_level_imports(tree: ast.Module) -> Dict[str, int]:
    """top-level module name -> first line it's imported on."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                top = alias.name.split(".")[0]
                out.setdefault(top, node.lineno)
        elif isinstance(node, ast.ImportFrom):
            if node.level:            # relative import — local by definition
                continue
            if node.module:
                out.setdefault(node.module.split(".")[0], node.lineno)
    return out


def _local_packages(root: str) -> Set[str]:
    """Importable names the repo itself provides (dirs with __init__.py or
    top-level .py files)."""
    out: Set[str] = set()
    try:
        entries = os.listdir(root)
    except OSError:
        return out
    for e in entries:
        p = os.path.join(root, e)
        if os.path.isdir(p) and os.path.exists(
                os.path.join(p, "__init__.py")):
            out.add(e)
        elif os.path.isdir(p):
            out.add(e)                 # namespace package (scripts/)
        elif e.endswith(".py"):
            out.add(e[:-3])
    return out


def declared_requirements(root: str) -> Optional[Set[str]]:
    """Normalized import-level names declared in requirements.txt, or None
    when the file doesn't exist."""
    path = os.path.join(root, REQUIREMENTS)
    if not os.path.exists(path):
        return None
    out: Set[str] = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = _REQ_LINE.match(line)
            if not m:
                continue
            dist = m.group(1)
            out.add(_norm(dist))
            imp = _IMPORT_OF_DIST.get(dist.lower())
            if imp:
                out.add(_norm(imp))
    return out


def third_party_imports(project: Project) -> Dict[str, Tuple[str, int]]:
    """third-party top-level module -> (first relpath, line)."""
    local = _local_packages(project.root)
    out: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules:
        if mod.tree is None:
            continue
        for top, line in sorted(_top_level_imports(mod.tree).items()):
            if top in _STDLIB or top in local:
                continue
            if top not in out:
                out[top] = (mod.relpath, line)
    return out


@register
class UndeclaredImport(Rule):
    id = "undeclared-import"
    family = "drift"
    severity = "error"
    doc = ("every third-party import (lazy ones included) must be declared "
           "in requirements.txt; every requirement must be imported "
           "somewhere — the seed repo's undeclared-psutil failure mode")

    def check_project(self, project: Project) -> Iterable[Finding]:
        third = third_party_imports(project)
        if not third:
            return ()
        declared = declared_requirements(project.root)
        out: List[Finding] = []
        if declared is None:
            out.append(Finding(
                rule=self.id, path=REQUIREMENTS, line=1,
                message=f"{REQUIREMENTS} missing but the tree imports "
                        f"{len(third)} third-party module(s): "
                        f"{', '.join(sorted(third))}",
                key="missing-requirements"))
            return out
        for top, (rel, line) in sorted(third.items()):
            dist = DIST_NAMES.get(top, top)
            if _norm(top) not in declared and _norm(dist) not in declared:
                out.append(Finding(
                    rule=self.id, path=rel, line=line,
                    message=f"import {top} ({dist} on PyPI) is not "
                            f"declared in {REQUIREMENTS}",
                    key=f"undeclared:{top}"))
        # reverse: stale requirement nothing imports. jaxlib is the one
        # legitimate import-less dist (jax's binary backend).
        imported = {_norm(t) for t in third} | \
            {_norm(DIST_NAMES.get(t, t)) for t in third}
        for dist in sorted(declared - imported - {"jaxlib"}):
            if dist in {_norm(i) for i in _IMPORT_OF_DIST.values()}:
                continue              # counted under its import name
            out.append(Finding(
                rule=self.id, path=REQUIREMENTS, line=1,
                message=f"requirement {dist} is declared but never "
                        f"imported by the analyzed tree — stale "
                        f"dependency", key=f"stale:{dist}"))
        return out
