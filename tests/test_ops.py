"""Numeric tests for the TPU compute ops (run on CPU via conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.ops.norms import layer_norm, rms_norm
from distributed_inference_engine_tpu.ops.rope import apply_rope
from distributed_inference_engine_tpu.ops.attention import causal_attention, cached_attention
from distributed_inference_engine_tpu.ops.sampling import SamplingParams, sample_tokens


def test_layer_norm_matches_numpy():
    x = np.random.RandomState(0).randn(2, 3, 8).astype(np.float32)
    scale = np.random.RandomState(1).rand(8).astype(np.float32)
    bias = np.random.RandomState(2).rand(8).astype(np.float32)
    got = layer_norm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias))
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mu) / np.sqrt(var + 1e-5) * scale + bias
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_rms_norm_matches_numpy():
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    scale = np.ones(8, dtype=np.float32) * 2
    got = rms_norm(jnp.asarray(x), jnp.asarray(scale))
    want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * 2
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_rope_identity_at_position_zero():
    x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 2, 8).astype(np.float32))
    pos = jnp.zeros((1, 1), dtype=jnp.int32)
    np.testing.assert_allclose(np.asarray(apply_rope(x, pos)), np.asarray(x), atol=1e-6)


def test_rope_preserves_norm_and_relative_positions():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(1, 4, 1, 16).astype(np.float32))
    pos = jnp.arange(4)[None, :]
    r = apply_rope(x, pos)
    # rotation preserves vector norm
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # dot(q_i, k_j) depends only on i - j: shift both positions by a constant
    q = jnp.asarray(rs.randn(1, 1, 1, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 1, 1, 16).astype(np.float32))
    def dot_at(pq, pk):
        qq = apply_rope(q, jnp.array([[pq]]))
        kk = apply_rope(k, jnp.array([[pk]]))
        return float(jnp.sum(qq * kk))
    assert dot_at(5, 3) == pytest.approx(dot_at(9, 7), rel=1e-4)


def test_causal_attention_masks_future_and_padding():
    rs = np.random.RandomState(0)
    b, t, h, dh = 1, 4, 2, 8
    q = jnp.asarray(rs.randn(b, t, h, dh).astype(np.float32))
    k = jnp.asarray(rs.randn(b, t, h, dh).astype(np.float32))
    v = jnp.asarray(rs.randn(b, t, h, dh).astype(np.float32))
    out_full = causal_attention(q, k, v, jnp.array([3]))
    # position 0 attends only to key 0 => its output is v[0]
    np.testing.assert_allclose(
        np.asarray(out_full[0, 0]), np.asarray(v[0, 0]), rtol=1e-4, atol=1e-5
    )
    # changing the padded key (index 3) must not change any output at pos < 3
    k2 = k.at[0, 3].set(99.0)
    v2 = v.at[0, 3].set(99.0)
    out2 = causal_attention(q, k2, v2, jnp.array([3]))
    np.testing.assert_allclose(
        np.asarray(out_full[0, :3]), np.asarray(out2[0, :3]), rtol=1e-5
    )


def test_cached_attention_respects_lengths():
    rs = np.random.RandomState(1)
    b, s, h, dh = 2, 8, 2, 4
    q = jnp.asarray(rs.randn(b, 1, h, dh).astype(np.float32))
    ck = jnp.asarray(rs.randn(b, s, h, dh).astype(np.float32))
    cv = jnp.asarray(rs.randn(b, s, h, dh).astype(np.float32))
    lengths = jnp.array([3, 5])
    out = cached_attention(q, ck, cv, lengths)
    # poisoning cache beyond the live prefix must not change outputs
    ck2 = ck.at[:, 6:].set(1e4)
    cv2 = cv.at[:, 6:].set(1e4)
    out2 = cached_attention(q, ck2, cv2, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-5)


def test_gqa_grouping_matches_repeated_heads():
    """GQA with Hkv=1 must equal MHA where the single KV head is broadcast."""
    rs = np.random.RandomState(2)
    b, t, h, dh = 1, 3, 4, 8
    q = jnp.asarray(rs.randn(b, t, h, dh).astype(np.float32))
    k1 = jnp.asarray(rs.randn(b, t, 1, dh).astype(np.float32))
    v1 = jnp.asarray(rs.randn(b, t, 1, dh).astype(np.float32))
    out_gqa = causal_attention(q, k1, v1, jnp.array([t]))
    out_mha = causal_attention(
        q, jnp.tile(k1, (1, 1, h, 1)), jnp.tile(v1, (1, 1, h, 1)), jnp.array([t])
    )
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha), rtol=1e-4, atol=1e-6)


# ------------------------------------------------------------------ sampling


def test_greedy_is_argmax():
    logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 0.5]])
    p = SamplingParams.make(2, temperature=0.0)
    toks = sample_tokens(logits, p, jax.random.key(0))
    assert toks.tolist() == [1, 0]


def test_top_k_one_is_argmax_even_with_temperature():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    p = SamplingParams.make(4, temperature=5.0, top_k=1)
    for seed in range(3):
        toks = sample_tokens(logits, p, jax.random.key(seed))
        assert toks.tolist() == np.argmax(np.asarray(logits), -1).tolist()


def test_top_p_excludes_tail():
    # one dominant token (p=0.9+); top_p=0.5 must always pick it
    logits = jnp.asarray([[10.0, 0.0, 0.0, 0.0]])
    p = SamplingParams.make(1, temperature=1.0, top_p=0.5)
    for seed in range(5):
        assert sample_tokens(logits, p, jax.random.key(seed)).tolist() == [0]


def test_sampling_is_deterministic_per_key():
    logits = jnp.asarray(np.random.RandomState(0).randn(2, 32).astype(np.float32))
    p = SamplingParams.make(2, temperature=1.0, top_k=8, top_p=0.9)
    a = sample_tokens(logits, p, jax.random.key(7))
    b = sample_tokens(logits, p, jax.random.key(7))
    assert a.tolist() == b.tolist()


def test_temperature_spreads_choices():
    logits = jnp.asarray(np.zeros((1, 8), dtype=np.float32))
    p = SamplingParams.make(1, temperature=1.0)
    seen = {sample_tokens(logits, p, jax.random.key(s)).tolist()[0] for s in range(20)}
    assert len(seen) > 1          # uniform logits at temp 1 should vary


def test_top_p_nucleus_widens_with_temperature():
    """Code-review regression: nucleus membership is judged on the TEMPERED
    distribution (HF semantics) — high temperature must widen the nucleus."""
    logits = jnp.asarray([[6.0, 2.0, 0.0, -10.0]])
    # raw distribution: token 0 has ~0.98 mass => untempered nucleus@0.9 = {0}
    cold = SamplingParams.make(1, temperature=0.05, top_p=0.9)
    seen_cold = {int(sample_tokens(logits, cold, jax.random.key(s))[0]) for s in range(30)}
    assert seen_cold == {0}
    hot = SamplingParams.make(1, temperature=3.0, top_p=0.9)
    seen_hot = {int(sample_tokens(logits, hot, jax.random.key(s))[0]) for s in range(30)}
    assert len(seen_hot) > 1        # tempered softmax spreads mass; nucleus grows
    assert 3 not in seen_hot        # the -10 tail stays excluded
