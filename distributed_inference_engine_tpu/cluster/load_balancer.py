"""Load balancer: strategy-based worker selection over the healthy set.

Capability heir of the reference's ``src/load_balancer.py``: four selection
strategies — round-robin (``:231-244``), least-connections (``:246-261``),
random (``:263-274``), least-latency (``:276-291``) — applied over workers
whose consecutive-failure count is under the threshold (``:150-153``), with
runtime register/unregister (``:97-126``), per-worker request/latency/error
stats (``:166-226``), and a periodic health loop (``:293-348``).

Reference pitfall fixed (SURVEY.md §5 failure-detection row): the reference's
health probes write their own timings into the same ``request_count``/
``total_latency`` fields the LEAST_LATENCY strategy reads
(``src/load_balancer.py:334-339``), so an idle worker's latency profile is
probe noise. Here probe outcomes only touch health fields; request stats come
only from ``update_stats`` calls on real traffic. Probes are also a real
``ping`` RPC rather than a bare TCP connect.

Role split vs the router (reference ``docs/router_vs_load_balancer.md``): the
router answers "which shard *must* serve this key" (placement/affinity); the
LB answers "which of the equivalent replicas *should* take the next request"
(spreading). In TPU terms: the router picks the mesh partition, the LB picks
among data-parallel replicas of it.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import logging
import random
import time
from collections import Counter, OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from ..config import HealthConfig
from .worker import WorkerClient

logger = logging.getLogger(__name__)


class LoadBalancerStrategy(str, enum.Enum):
    """Reference ``src/load_balancer.py:18-23``."""

    ROUND_ROBIN = "round_robin"
    LEAST_CONNECTIONS = "least_connections"
    RANDOM = "random"
    LEAST_LATENCY = "least_latency"
    # KV-locality-aware spreading (PRESERVE-style): requests carrying the
    # same prefix-chain hash stick to the worker whose prefix cache is warm;
    # cold prefixes fall back to least-connections
    PREFIX_AFFINITY = "prefix_affinity"


# per-worker circuit breaker states (docs/design.md "Failure model"):
# CLOSED = normal traffic; OPEN = excluded from selection, cooling down;
# HALF_OPEN = cooldown over, exactly one trial probe outstanding.
BREAKER_CLOSED = "closed"
BREAKER_HALF_OPEN = "half_open"
BREAKER_OPEN = "open"
_BREAKER_CODE = {BREAKER_CLOSED: 0, BREAKER_HALF_OPEN: 1, BREAKER_OPEN: 2}


@dataclass
class WorkerStats:
    """Reference ``src/load_balancer.py:25-37`` — with probe stats separated."""

    worker_id: str
    host: str
    port: int
    active_connections: int = 0
    request_count: int = 0
    error_count: int = 0
    total_latency_s: float = 0.0
    consecutive_failures: int = 0
    last_probe: float = 0.0
    probe_count: int = 0
    probe_failures: int = 0
    breaker_state: str = BREAKER_CLOSED
    breaker_opened_at: float = 0.0
    breaker_opens: int = 0
    metadata: Dict[str, Any] = field(default_factory=dict)

    @property
    def avg_latency_s(self) -> float:
        """Reference ``src/load_balancer.py:34-37`` — real traffic only."""
        return self.total_latency_s / self.request_count if self.request_count else 0.0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class NoHealthyWorkerError(RuntimeError):
    pass


class LoadBalancer:
    """Reference ``src/load_balancer.py:39-348``."""

    def __init__(
        self,
        strategy: LoadBalancerStrategy = LoadBalancerStrategy.ROUND_ROBIN,
        health: Optional[HealthConfig] = None,
        seed: Optional[int] = None,
        affinity_capacity: int = 4096,
    ) -> None:
        self.strategy = LoadBalancerStrategy(strategy)
        self.health_config = health or HealthConfig()
        self.workers: Dict[str, WorkerStats] = {}
        self._rr = itertools.count()
        self._rand = random.Random(seed)
        self._clients: Dict[str, WorkerClient] = {}
        self._health_task: Optional[asyncio.Task] = None
        # asyncio keeps only weak refs to tasks: retain close() tasks here
        # or they can be garbage-collected before the socket is closed
        self._bg_tasks: set = set()
        self._running = False
        self._pick_count = 0
        # prefix-affinity binding table: prefix key -> worker_id, LRU-bounded
        # so a long-tail of one-shot prefixes can't grow it without bound
        self._affinity: "OrderedDict[Hashable, str]" = OrderedDict()
        self._affinity_capacity = affinity_capacity
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._affinity_rebinds = 0
        self._affinity_handoffs = 0   # bindings MOVED (KV fabric), not dropped
        # model+prefix placement (multi-model fleets): composite keys are
        # "<model>:<prefix-hash>", so hits/misses split per model, and the
        # cold-prefix placement prefers workers that already hold (or are
        # staging) the key's model — learned from ping payloads and
        # coordinator deploy/stage notifications
        self._model_affinity: Dict[str, Dict[str, int]] = {}
        self._resident_models: Dict[str, set] = {}   # worker -> resident
        self._staged_models: Dict[str, set] = {}     # worker -> staging
        # breaker-transition observer (flight recorder): called as
        # on_transition(worker_id, new_state) for every CLOSED/HALF_OPEN/
        # OPEN flip; must be cheap and must not raise (guarded anyway)
        self.on_transition: Optional[Callable[[str, str], None]] = None
        self._strategies = {
            LoadBalancerStrategy.ROUND_ROBIN: self._round_robin,
            LoadBalancerStrategy.LEAST_CONNECTIONS: self._least_connections,
            LoadBalancerStrategy.RANDOM: self._random,
            LoadBalancerStrategy.LEAST_LATENCY: self._least_latency,
            # keyless requests under prefix_affinity spread like
            # least-connections; keyed picks short-circuit in get_worker
            LoadBalancerStrategy.PREFIX_AFFINITY: self._least_connections,
        }

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._health_task = asyncio.create_task(self._health_loop())

    async def stop(self) -> None:
        self._running = False
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        for client in self._clients.values():
            await client.close()
        self._clients.clear()

    # -- membership (reference src/load_balancer.py:97-126) -------------------

    def register_worker(self, worker_id: str, host: str, port: int,
                        **metadata: Any) -> WorkerStats:
        stats = WorkerStats(worker_id=worker_id, host=host, port=port,
                            metadata=metadata)
        self.workers[worker_id] = stats
        logger.info("lb: registered worker %s at %s", worker_id, stats.address)
        return stats

    def unregister_worker(self, worker_id: str) -> bool:
        stats = self.workers.pop(worker_id, None)
        self._resident_models.pop(worker_id, None)
        self._staged_models.pop(worker_id, None)
        if stats is not None:
            self.invalidate_affinity(worker_id)
        client = self._clients.pop(worker_id, None)
        if client is not None:
            # tear in-flight calls NOW: their pending reads fail fast as
            # transport errors and the coordinator's retry budget requeues
            # the work, instead of queued dispatches timing out against a
            # deregistered target
            client.abort_inflight()
            try:
                task = asyncio.get_running_loop().create_task(client.close())
                self._bg_tasks.add(task)
                task.add_done_callback(self._bg_tasks.discard)
            except RuntimeError:
                pass
        return stats is not None

    def client_for(self, worker_id: str) -> WorkerClient:
        stats = self.workers.get(worker_id)
        if stats is None:
            raise NoHealthyWorkerError(f"unknown worker {worker_id!r}")
        client = self._clients.get(worker_id)
        if client is None:
            client = WorkerClient(stats.host, stats.port)
            self._clients[worker_id] = client
        return client

    # -- selection (reference src/load_balancer.py:128-164) -------------------

    def _is_healthy(self, s: WorkerStats) -> bool:
        return (s.breaker_state == BREAKER_CLOSED
                and s.consecutive_failures
                < self.health_config.max_consecutive_failures)

    # -- circuit breaker ------------------------------------------------------

    def _record_failure(self, s: WorkerStats) -> None:
        s.consecutive_failures += 1
        if s.breaker_state == BREAKER_HALF_OPEN:
            # the one trial probe failed: re-open and restart the cooldown
            self._open_breaker(s)
        elif (s.breaker_state == BREAKER_CLOSED
              and s.consecutive_failures
              >= self.health_config.max_consecutive_failures):
            self._open_breaker(s)

    def _notify_transition(self, worker_id: str, state: str) -> None:
        cb = self.on_transition
        if cb is None:
            return
        try:
            cb(worker_id, state)
        # graftlint: ok[swallowed-transport-error] observer hook — telemetry must never break breaker bookkeeping
        except Exception:
            logger.exception("lb: on_transition observer failed")

    def _record_success(self, s: WorkerStats) -> None:
        s.consecutive_failures = 0
        if s.breaker_state != BREAKER_CLOSED:
            logger.info("lb: circuit for %s closed", s.worker_id)
            s.breaker_state = BREAKER_CLOSED
            self._notify_transition(s.worker_id, BREAKER_CLOSED)

    def _open_breaker(self, s: WorkerStats) -> None:
        was = s.breaker_state
        s.breaker_state = BREAKER_OPEN
        s.breaker_opened_at = time.monotonic()
        s.breaker_opens += 1
        logger.info("lb: circuit for %s opened (%d consecutive failures)",
                    s.worker_id, s.consecutive_failures)
        if was != BREAKER_OPEN:
            self._notify_transition(s.worker_id, BREAKER_OPEN)

    def quarantine(self, worker_id: str) -> bool:
        """Administratively open a worker's circuit (the drain/remove path):
        it drops out of selection immediately; a successful half-open probe
        or real-traffic success re-admits it."""
        s = self.workers.get(worker_id)
        if s is None:
            return False
        self._open_breaker(s)
        self.invalidate_affinity(worker_id)
        return True

    def enter_half_open(self, worker_id: str) -> bool:
        """Put a worker straight into HALF_OPEN (the supervisor's rejoin
        path after a respawn): the next selection or health probe is its
        one trial — success closes the circuit, failure re-opens it. Skips
        the usual OPEN→cooldown wait because the respawn itself is the
        evidence the process is fresh."""
        s = self.workers.get(worker_id)
        if s is None:
            return False
        s.consecutive_failures = 0
        if s.breaker_state != BREAKER_HALF_OPEN:
            self._notify_transition(worker_id, BREAKER_HALF_OPEN)
        s.breaker_state = BREAKER_HALF_OPEN
        s.breaker_opened_at = time.monotonic()
        return True

    def healthy_workers(self) -> List[WorkerStats]:
        return [s for s in self.workers.values() if self._is_healthy(s)]

    def get_worker(self, pinned: Optional[str] = None,
                   affinity: Optional[Hashable] = None) -> WorkerStats:
        """Pick a worker; ``pinned`` forces a specific healthy worker
        (reference pinned-worker path, ``src/load_balancer.py:144-147``).

        Under ``PREFIX_AFFINITY``, ``affinity`` is the request's prefix-chain
        hash: a live binding to a healthy worker is a *hit* (same-prefix
        traffic lands on the warm cache), a cold key is a *miss* (bound to
        the least-loaded worker), and a binding whose worker has died,
        drained, or tripped its breaker is *rebound* to a healthy one —
        requests are never dropped for affinity's sake."""
        self._pick_count += 1
        if pinned is not None:
            s = self.workers.get(pinned)
            if s is None or not self._is_healthy(s):
                raise NoHealthyWorkerError(f"pinned worker {pinned!r} unavailable")
            return s
        healthy = self.healthy_workers()
        if not healthy:
            raise NoHealthyWorkerError("no healthy workers registered")
        healthy.sort(key=lambda s: s.worker_id)   # deterministic strategy input
        if (self.strategy == LoadBalancerStrategy.PREFIX_AFFINITY
                and affinity is not None):
            return self._affine_pick(affinity, healthy)
        return self._strategies[self.strategy](healthy)

    # -- model residency (multi-model fleets) --------------------------------

    @staticmethod
    def model_of_key(key: Hashable) -> Optional[str]:
        """The model id a composite ``"<model>:<prefix-hash>"`` affinity
        key names; None for legacy bare-hash keys."""
        if isinstance(key, str) and ":" in key:
            return key.split(":", 1)[0]
        return None

    def note_models(self, worker_id: str, resident=None, staged=None) -> None:
        """Record which models a worker holds (and is staging) — fed by the
        health loop's ping payloads and by the coordinator after deploys/
        stage requests, and read by the cold-key placement preference."""
        if worker_id not in self.workers:
            return
        if resident is not None:
            self._resident_models[worker_id] = set(resident)
        if staged is not None:
            self._staged_models[worker_id] = set(staged)

    def add_resident_model(self, worker_id: str, model: str) -> None:
        """Merge one model into a worker's known-resident set (deploy-time
        hint; the health loop's ping payloads overwrite with ground truth).
        A model that just became resident is no longer merely staged."""
        if worker_id not in self.workers:
            return
        self._resident_models.setdefault(worker_id, set()).add(model)
        self._staged_models.get(worker_id, set()).discard(model)

    def add_staged_model(self, worker_id: str, model: str) -> None:
        """Merge one model into a worker's staging set — cold keys for that
        model prefer a worker already staging it over a fully cold one."""
        if worker_id not in self.workers:
            return
        self._staged_models.setdefault(worker_id, set()).add(model)

    def workers_with_model(self, model: str) -> set:
        return {wid for wid, models in self._resident_models.items()
                if model in models}

    def _model_count(self, model: Optional[str], field: str) -> None:
        if model is None:
            return
        rec = self._model_affinity.setdefault(
            model, {"hits": 0, "misses": 0, "rebinds": 0})
        rec[field] += 1

    def _affine_pick(self, key: Hashable,
                     healthy: List[WorkerStats]) -> WorkerStats:
        model = self.model_of_key(key)
        bound = self._affinity.get(key)
        if bound is not None:
            s = self.workers.get(bound)
            if s is not None and self._is_healthy(s):
                self._affinity_hits += 1
                self._model_count(model, "hits")
                self._affinity.move_to_end(key)
                return s
            # bound worker is gone/unhealthy: rebind, don't drop the request
            self._affinity_rebinds += 1
            self._model_count(model, "rebinds")
        else:
            self._affinity_misses += 1
            self._model_count(model, "misses")
        # cold-key placement: prefer workers where the key's MODEL is
        # already resident (swap is free) over ones merely staging it
        # (swap is cheap and imminent) over the rest (placement triggers a
        # cold load) — a cold-model request should not displace a resident
        # model elsewhere when a warm replica has capacity. Within a tier:
        # least-connections, tie-broken by how many bindings each worker
        # already holds — bare active_connections ties to the first worker
        # on an idle fleet, piling every cold prefix onto one replica
        candidates = healthy
        if model is not None:
            resident = [w for w in healthy
                        if model in self._resident_models.get(w.worker_id, ())]
            staging = [w for w in healthy
                       if model in self._staged_models.get(w.worker_id, ())]
            candidates = resident or staging or healthy
        held = Counter(self._affinity.values())
        s = min(candidates, key=lambda w: (w.active_connections,
                                           held.get(w.worker_id, 0),
                                           w.request_count))
        self._bind_affinity(key, s.worker_id)
        return s

    def _bind_affinity(self, key: Hashable, worker_id: str) -> None:
        self._affinity[key] = worker_id
        self._affinity.move_to_end(key)
        while len(self._affinity) > self._affinity_capacity:
            self._affinity.popitem(last=False)

    def invalidate_affinity(self, worker_id: Optional[str] = None) -> int:
        """Drop bindings to ``worker_id`` (or all when None); subsequent
        same-prefix picks rebind fresh. Called automatically on unregister/
        quarantine, and explicitly by the coordinator when a streaming
        failover replays a prefix onto an alternate (the old binding is
        known-stale even though the breaker may not have tripped yet).
        Each dropped binding counts as a rebind."""
        stale = [k for k, w in self._affinity.items()
                 if worker_id is None or w == worker_id]
        for k in stale:
            del self._affinity[k]
        self._affinity_rebinds += len(stale)
        return len(stale)

    def bindings_for(self, worker_id: str) -> List[Hashable]:
        """One worker's bound prefix keys, most-recently-used first — the
        drain handoff's export list."""
        return [k for k in reversed(self._affinity)
                if self._affinity[k] == worker_id]

    def top_bindings(self, k: int = 0) -> List[Tuple[Hashable, str]]:
        """The hottest (MRU-first) affinity bindings fleet-wide as
        ``(key, worker_id)`` pairs; all of them when ``k <= 0``. The
        coordinator's pre-warm source set."""
        out = [(key, self._affinity[key]) for key in reversed(self._affinity)]
        return out[:k] if k > 0 else out

    def bind_affinity(self, key: Hashable, worker_id: str) -> bool:
        """Explicitly (re)bind one key — the stream-failover handoff after
        the alternate imported the prefix KV. False when the worker is not
        registered. Counts as a handoff, not a rebind: the KV moved with
        the binding."""
        if worker_id not in self.workers:
            return False
        self._bind_affinity(key, worker_id)
        self._affinity_handoffs += 1
        return True

    def rebind_affinity(self, from_worker: str, to_worker: str) -> int:
        """HAND OFF every binding from one worker to another (the drain
        path, after the target imported the prefixes' KV) instead of
        dropping them cold. Recency is preserved — the moved bindings keep
        their LRU positions. No-op when the target is unregistered."""
        if to_worker not in self.workers:
            return 0
        moved = 0
        for key, bound in self._affinity.items():
            if bound == from_worker:
                self._affinity[key] = to_worker
                moved += 1
        self._affinity_handoffs += moved
        return moved

    def _round_robin(self, healthy: List[WorkerStats]) -> WorkerStats:
        return healthy[next(self._rr) % len(healthy)]

    def _least_connections(self, healthy: List[WorkerStats]) -> WorkerStats:
        return min(healthy, key=lambda s: s.active_connections)

    def _random(self, healthy: List[WorkerStats]) -> WorkerStats:
        return self._rand.choice(healthy)

    def _least_latency(self, healthy: List[WorkerStats]) -> WorkerStats:
        # cold workers (no real traffic yet) sort first so they get sampled
        return min(healthy, key=lambda s: s.avg_latency_s)

    # -- traffic accounting (reference src/load_balancer.py:166-191) ----------

    def acquire(self, worker_id: str) -> None:
        s = self.workers.get(worker_id)
        if s is not None:
            s.active_connections += 1

    def release(self, worker_id: str) -> None:
        s = self.workers.get(worker_id)
        if s is not None and s.active_connections > 0:
            s.active_connections -= 1

    def update_stats(self, worker_id: str, success: bool,
                     latency_s: float) -> None:
        s = self.workers.get(worker_id)
        if s is None:
            return
        s.request_count += 1
        s.total_latency_s += latency_s
        if success:
            self._record_success(s)        # reference :187-191
        else:
            s.error_count += 1
            self._record_failure(s)

    # -- health loop (reference src/load_balancer.py:293-348) -----------------

    async def _health_loop(self) -> None:
        while self._running:
            try:
                await self.check_all_workers()
            # graftlint: ok[swallowed-transport-error] per-worker failures are marked inside check_worker; this guards the sweep loop itself from dying
            except Exception:
                logger.exception("lb: health sweep failed")
            await asyncio.sleep(self.health_config.check_interval)

    async def check_all_workers(self) -> None:
        if self.workers:
            await asyncio.gather(*(self.check_worker(w)
                                   for w in list(self.workers)))

    async def check_worker(self, worker_id: str) -> bool:
        """Ping probe. Touches only health/probe fields — never the request
        stats the LEAST_LATENCY strategy reads (fixed reference pitfall).

        Breaker-aware: an OPEN circuit is probed only after its cooldown
        (half-open, one trial) — no hammering a host that just failed N
        times in a row. A ping that reports ``draining: true`` counts as a
        failed probe: the worker is alive but refusing admission, so it
        must stay out of rotation until the drain finishes."""
        s = self.workers.get(worker_id)
        if s is None:
            return False
        s.last_probe = time.monotonic()
        if s.breaker_state == BREAKER_OPEN:
            cooled = (time.monotonic() - s.breaker_opened_at
                      >= self.health_config.breaker_cooldown_s)
            if not cooled:
                return False
            s.breaker_state = BREAKER_HALF_OPEN
            self._notify_transition(worker_id, BREAKER_HALF_OPEN)
        s.probe_count += 1
        try:
            pong = await self.client_for(worker_id).ping(
                timeout=self.health_config.check_timeout
            )
        except Exception as e:
            logger.debug("lb: probe of %s failed: %s", worker_id, e)
            s.probe_failures += 1
            self._record_failure(s)
            return False
        if isinstance(pong, dict):
            # pings advertise the worker's resident + staging model sets —
            # the model-aware cold-key placement's knowledge source
            self.note_models(worker_id, resident=pong.get("models"),
                             staged=pong.get("staged"))
        if isinstance(pong, dict) and pong.get("draining"):
            logger.debug("lb: %s is draining — held out of rotation",
                         worker_id)
            s.probe_failures += 1
            self._record_failure(s)
            return False
        self._record_success(s)
        return True

    # -- introspection (reference src/load_balancer.py:193-226) ---------------

    def get_worker_stats(self, worker_id: str) -> Optional[Dict[str, Any]]:
        s = self.workers.get(worker_id)
        if s is None:
            return None
        return {
            "worker_id": s.worker_id,
            "address": s.address,
            "healthy": self._is_healthy(s),
            "active_connections": s.active_connections,
            "request_count": s.request_count,
            "error_count": s.error_count,
            "avg_latency_s": s.avg_latency_s,
            "consecutive_failures": s.consecutive_failures,
            "probe_count": s.probe_count,
            "probe_failures": s.probe_failures,
            "breaker_state": s.breaker_state,
            "breaker_state_code": _BREAKER_CODE[s.breaker_state],
            "breaker_opens": s.breaker_opens,
        }

    def get_all_stats(self) -> Dict[str, Any]:
        return {
            "strategy": self.strategy.value,
            "pick_count": self._pick_count,
            "workers": {wid: self.get_worker_stats(wid) for wid in self.workers},
            "healthy_count": len(self.healthy_workers()),
            "affinity_hits": self._affinity_hits,
            "affinity_misses": self._affinity_misses,
            "affinity_rebinds": self._affinity_rebinds,
            "affinity_handoffs": self._affinity_handoffs,
            "affinity_bindings": len(self._affinity),
            # per-model split of the composite-key hits/misses/rebinds
            # (multi-model fleets; legacy bare-hash keys are unlabelled)
            "affinity_models": {m: dict(rec) for m, rec
                                in self._model_affinity.items()},
        }
