"""Request tracing: real request IDs propagated end-to-end with per-phase
timestamps.

The reference README promises "request tracing" (``README.md:18``) but only
``FakeModel`` fabricates a request_id that never leaves the mock
(``src/mock_models/fake_model.py:56``); the worker logs per-connection
durations (``src/worker.py:126-133``) with no correlation id. Here a
``RequestTrace`` travels with each request and records queue/prefill/decode
phase boundaries — the timestamps that produce TTFT and tok/s, the
BASELINE.json metrics.
"""

from __future__ import annotations

import bisect
import contextlib
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


# fixed histogram bucket bounds (seconds) shared with the metrics registry
# (obs/registry.py imports these as its default): LatencyStats snapshots
# carry cumulative counts over EXACTLY these bounds, so they export as
# OpenMetrics histograms without translation
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)


def format_bucket_bound(bound: float) -> str:
    """Canonical ``le`` label for a bucket bound (shortest float form)."""
    f = float(bound)
    if f == float("inf"):
        return "+Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


@dataclass
class RequestTrace:
    """Monotonic per-phase marks for one request's lifetime.

    Canonical phases: received, queued, batched, prefill_start, prefill_end,
    first_token, decode_end, responded.
    """

    request_id: str = field(default_factory=new_request_id)
    marks: Dict[str, float] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if "received" not in self.marks:
            self.mark("received")

    def mark(self, phase: str) -> float:
        t = time.monotonic()
        self.marks.setdefault(phase, t)   # first mark wins (first_token semantics)
        return t

    def span(self, start: str, end: str) -> Optional[float]:
        if start in self.marks and end in self.marks:
            return self.marks[end] - self.marks[start]
        return None

    @property
    def ttft(self) -> Optional[float]:
        """Time to first token: received → first_token."""
        return self.span("received", "first_token")

    @property
    def total(self) -> Optional[float]:
        return self.span("received", "responded")

    def add_offsets(self, prefix: str, offsets: Dict[str, float],
                    anchor: Optional[float] = None) -> None:
        """Merge REMOTE phase marks recorded as offsets on another clock.

        A worker cannot share this trace's ``time.monotonic`` epoch, so it
        reports phases as offsets from its own receive time; anchoring
        them at this trace's ``dispatched`` mark (network transit folds
        into the remote ``received``≈0 offset) lands them on the local
        timeline. ``mark()``'s first-wins semantics are preserved via
        ``setdefault``. ``anchor`` is an absolute local monotonic stamp;
        defaults to the ``dispatched`` (else ``received``) mark."""
        if anchor is None:
            anchor = self.marks.get("dispatched",
                                    self.marks.get("received", 0.0))
        for phase, off in offsets.items():
            if isinstance(off, (int, float)):
                self.marks.setdefault(f"{prefix}{phase}",
                                      anchor + float(off))

    def to_dict(self) -> Dict[str, float]:
        base = self.marks.get("received", 0.0)
        d = {k: v - base for k, v in self.marks.items()}
        d["request_id"] = self.request_id  # type: ignore[assignment]
        return d


@contextlib.contextmanager
def trace_span(trace: Optional[RequestTrace], start: str, end: str) -> Iterator[None]:
    if trace is not None:
        trace.mark(start)
    try:
        yield
    finally:
        if trace is not None:
            trace.mark(end)


class LatencyStats:
    """Streaming latency accumulator with percentile snapshots.

    Keeps a bounded reservoir so long-running workers don't grow
    unboundedly. Fixed-bucket counts (over ``LATENCY_BUCKETS``) accumulate
    over EVERY observation — unlike the percentiles, they never decimate —
    so ``snapshot()`` exports as a proper OpenMetrics histogram
    (cumulative buckets + sum + count).
    """

    def __init__(self, reservoir: int = 4096,
                 buckets: tuple = LATENCY_BUCKETS) -> None:
        self._samples: list[float] = []
        self._reservoir = reservoir
        self._buckets = tuple(sorted(float(b) for b in buckets))
        self._bucket_counts = [0] * (len(self._buckets) + 1)  # +Inf tail
        self.count = 0
        self.total = 0.0

    def add(self, latency_s: float) -> None:
        self.count += 1
        self.total += latency_s
        self._bucket_counts[
            bisect.bisect_left(self._buckets, latency_s)] += 1
        if len(self._samples) < self._reservoir:
            self._samples.append(latency_s)
        else:
            # deterministic decimation: overwrite round-robin
            self._samples[self.count % self._reservoir] = latency_s

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, int(round(p / 100.0 * (len(s) - 1))))
        return s[idx]

    def bucket_counts(self) -> Dict[str, int]:
        """CUMULATIVE counts keyed by their ``le`` label (+Inf last)."""
        out: Dict[str, int] = {}
        cum = 0
        for bound, n in zip(self._buckets, self._bucket_counts):
            cum += n
            out[format_bucket_bound(bound)] = cum
        out["+Inf"] = self.count
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean_s": self.mean,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
            "sum_s": self.total,
            "buckets": self.bucket_counts(),
        }
