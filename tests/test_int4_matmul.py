"""Mosaic int4-unpack matmul kernel (ops/int4_matmul.py) — interpret-mode
correctness on CPU; the perf claim lives in README/BENCH (measured on the
real chip, where this kernel is the default int4 path on single-device
processes).

The kernel math must match quantize->dequantize->einsum exactly in
structure (same contraction, fp32 accumulation): tolerance covers only
dot-order noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.ops import quant
from distributed_inference_engine_tpu.ops.int4_matmul import (
    _int4_matmul_2d,
    kernel_wants,
    set_kernel_mode,
)


@pytest.fixture
def kernel_on():
    set_kernel_mode("on")
    yield
    set_kernel_mode("auto")


def _q4(rs, k, n):
    w = jnp.asarray(rs.randn(k, n).astype("float32") * 0.05)
    return w, quant.quantize_weight(w, (0,), bits=4)


@pytest.mark.parametrize("m,k,n", [(5, 256, 256), (64, 512, 384),
                                   (16, 256, 128)])
def test_kernel_matches_dequantized_reference(m, k, n):
    rs = np.random.RandomState(m + k + n)
    w, qt = _q4(rs, k, n)
    x = jnp.asarray(rs.randn(m, k).astype("float32"))
    ref = jnp.einsum("md,df->mf", x, qt.dequantize(jnp.float32))
    got = _int4_matmul_2d(x, qt.q, qt.s.astype(jnp.float32), interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_bf16_activations_exact_vs_fp32_dot():
    """int4 values and bf16 activations are both exact in the fp32-
    accumulated dot — the kernel must agree with the fp32 reference run
    on the SAME bf16 inputs, bit-for-bit after the output cast."""
    rs = np.random.RandomState(0)
    w, qt = _q4(rs, 256, 256)
    x = jnp.asarray(rs.randn(32, 256).astype("float32")).astype(jnp.bfloat16)
    ref = (jnp.einsum("md,df->mf", x.astype(jnp.float32),
                      qt.dequantize(jnp.float32))).astype(jnp.bfloat16)
    got = _int4_matmul_2d(x, qt.q, qt.s.astype(jnp.float32), interpret=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, dtype="float32"), np.asarray(ref, dtype="float32"),
        rtol=1e-2, atol=1e-2)


def test_matmul_any_dispatches_to_kernel(kernel_on):
    """With mode "on", matmul_any routes tileable int4 einsums through the
    kernel (interpreted off-TPU) and matches the XLA fallback path."""
    rs = np.random.RandomState(1)
    w, qt = _q4(rs, 256, 256)
    x3 = jnp.asarray(rs.randn(2, 3, 256).astype("float32"))
    assert kernel_wants("btd,df->btf", x3, qt)
    got = quant.matmul_any("btd,df->btf", x3, qt)
    set_kernel_mode("off")
    ref = quant.matmul_any("btd,df->btf", x3, qt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_kernel_wants_rejects_unsupported(kernel_on):
    rs = np.random.RandomState(2)
    _, qt = _q4(rs, 256, 256)
    x = jnp.asarray(rs.randn(4, 256).astype("float32"))
    assert kernel_wants("bd,df->bf", x, qt)
    # untileable N
    _, qt_small = _q4(rs, 256, 96)
    assert not kernel_wants("bd,df->bf", x, qt_small)
    # stacked [L, K/2, N] payload (inside scan slicing it becomes 2-D)
    wL = jnp.asarray(rs.randn(2, 256, 256).astype("float32") * 0.05)
    qtL = quant.quantize_weight(wL, (1,), bits=4)
    assert not kernel_wants("bd,ldf->lbf", x, qtL)
    # contraction not on x's last axis
    assert not kernel_wants("db,df->bf", x, qt)
    set_kernel_mode("off")
    assert not kernel_wants("bd,df->bf", x, qt)


def test_int4_engine_tokens_unchanged_by_kernel_path(kernel_on):
    """A tileable-width spec decodes the same greedy tokens through the
    kernel path (interpret) and the XLA path — guards the engine-level
    wiring, not just the op."""
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.engine import Engine
    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )
    from distributed_inference_engine_tpu.models.llama import llama_spec
    from distributed_inference_engine_tpu.ops.quant import (
        random_quantized_params,
    )

    spec = llama_spec("llama-tiny", max_seq_len=64).replace(
        d_model=256, d_ff=256, n_heads=4, n_kv_heads=4, dtype="float32")
    params = random_quantized_params(spec, jax.random.key(0), bits=4)
    cfg = EngineConfig(max_slots=2, max_seq_len=64, prefill_buckets=[16],
                       decode_steps_per_call=4)
    reqs = lambda: [GenerationRequest(prompt=[1, 2, 3, 4], max_new_tokens=6,
                                      temperature=0.0, request_id="k")]
    t_kernel = Engine(spec, params=params, config=cfg).generate(reqs())[0]
    set_kernel_mode("off")
    t_xla = Engine(spec, params=params, config=cfg).generate(reqs())[0]
    assert t_kernel.tokens == t_xla.tokens


def test_stacked_kernel_layer_indexed_matches_sliced(kernel_on):
    """The scalar-prefetch stacked kernel (layer picked by the grid's
    index_map, no materialized slice) must match the per-layer 2-D
    kernel for every layer, and matmul_any must route IndexedQuant to
    it."""
    from distributed_inference_engine_tpu.ops.int4_matmul import (
        int4_einsum_kernel_stacked,
        stacked_kernel_wants,
    )

    rs = np.random.RandomState(7)
    L, K, N = 3, 256, 384
    w = jnp.asarray(rs.randn(L, K, N).astype("float32") * 0.05)
    qt = quant.quantize_weight(w, (1,), bits=4)
    assert stacked_kernel_wants(qt)
    x = jnp.asarray(rs.randn(4, K).astype("float32"))
    for l in range(L):
        per_layer = quant.QuantizedTensor(q=qt.q[l], s=qt.s[l],
                                          bits=4, pack_axis=qt.pack_axis)
        ref = quant.matmul_any("bd,df->bf", x, per_layer)
        got = int4_einsum_kernel_stacked("bd,df->bf", x, qt, jnp.int32(l))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        via_any = quant.matmul_any("bd,df->bf", x,
                                   quant.IndexedQuant(qt, jnp.int32(l)))
        np.testing.assert_array_equal(np.asarray(via_any), np.asarray(got))


def test_split_indexed_blocks_identity_when_off():
    """With the kernel disabled the split is an identity — the XLA paths
    keep their scanned-slice fusion."""
    from distributed_inference_engine_tpu.ops.quant import (
        split_indexed_blocks,
    )

    set_kernel_mode("off")
    try:
        rs = np.random.RandomState(3)
        w = jnp.asarray(rs.randn(2, 64, 64).astype("float32"))
        blocks = {"wq": quant.quantize_weight(w, (1,), bits=4),
                  "ln1_scale": jnp.ones((2, 64))}
        xs, rebuild = split_indexed_blocks(blocks)
        assert set(xs) == {"wq", "ln1_scale"}
        blk = rebuild({k: jax.tree.map(lambda a: a[0], v)
                       for k, v in xs.items()}, 0)
        assert not isinstance(blk["wq"], quant.IndexedQuant)
    finally:
        set_kernel_mode("auto")
