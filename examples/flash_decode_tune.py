"""pages_per_block tuning sweep for the fused flash-decode kernel on
hardware (ops/flash_decode.py). The knob trades DMA batching (more pages
in flight per issue, deeper latency hiding) against VMEM scratch
(2 x bp x P x fused x dtype per K and V) and tail waste on short rows.

Measurement discipline follows examples/int4_kernel_tune.py: host-side
timing of single dispatches is untrustworthy over the tunnelled chip, so
each config is timed as a DEVICE-side ``lax.scan`` over L layers x P
passes inside ONE jit returning one scalar, at two pass counts; the
difference cancels the dispatch + round-trip constant:

    per-layer-us = (t(2P) - t(P)) / (P * L)

Prints one JSON row per (ctx, pages_per_block) with the achieved KV-read
GB/s. Feed the winners into ``_TUNED_PAGES_PER_BLOCK`` in
``ops/flash_decode.py`` (keyed by (page_size, fused)).

    python examples/flash_decode_tune.py                  # 8B serving shape
    BENCH_BATCH=64 BENCH_CTX=512 python examples/flash_decode_tune.py
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import jax
import jax.numpy as jnp

from distributed_inference_engine_tpu.ops.flash_decode import (
    flash_decode_attention_pallas,
)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# 8B flagship decode shape: 32 q heads / 8 kv heads x 128 -> fused = 1024,
# page_size = 128 (bench.py), bs 128, fp8 KV pools + bf16 activations.
B = int(os.environ.get("BENCH_BATCH", "128"))
H = int(os.environ.get("BENCH_HEADS", "32"))
HKV = int(os.environ.get("BENCH_KV_HEADS", "8"))
DH = int(os.environ.get("BENCH_HEAD_DIM", "128"))
PAGE = int(os.environ.get("BENCH_PAGE", "128"))
W = int(os.environ.get("BENCH_WINDOW", "16"))        # decode_steps_per_call
L = int(os.environ.get("BENCH_LAYERS", "32"))
CTXS = [int(c) for c in os.environ.get("BENCH_CTX", "512,1024,2048").split(",")]
KV_DTYPE = jnp.dtype(os.environ.get("BENCH_KV_DTYPE", "float8_e4m3fn"))
PASSES = int(os.environ.get("BENCH_PASSES", "16"))
BPS = [int(x) for x in os.environ.get("BENCH_BP", "1,2,4,8").split(",")]
PEAK_GBPS = 819.0                                    # v5e HBM


@functools.partial(jax.jit, static_argnames=("bp", "passes", "n_pages"))
def _loop(q, kp, vp, pt, plen, sk, sv, n_side, *, bp, passes, n_pages):
    """passes x L sequential kernel calls on-device; scalar out."""

    def body(acc, l):
        y = flash_decode_attention_pallas(
            q, kp, vp, pt, plen, sk, sv, n_side, n_kv_heads=HKV,
            layer=l, n_pages_per_layer=n_pages, pages_per_block=bp)
        # fold a few output elements into the carry: the scan carry is the
        # data dependency that keeps XLA from reordering/eliding calls
        return acc + y[0, 0, :8].astype(jnp.float32).sum(), None

    acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                          jnp.tile(jnp.arange(L, dtype=jnp.int32), passes))
    return acc


def _timed(args, bp, n_pages, passes):
    t0 = time.perf_counter()
    v = _loop(*args, bp=bp, passes=passes, n_pages=n_pages)
    float(v)                       # scalar fetch = the only sync point
    return time.perf_counter() - t0


def main():
    fused = HKV * DH
    log(f"devices: {jax.devices()}  B={B} H={H}/{HKV} Dh={DH} "
        f"page={PAGE} kv={KV_DTYPE.name} passes={PASSES}")
    key = jax.random.key(0)
    best = {}
    for ctx in CTXS:
        mp = -(-ctx // PAGE)
        n_pages = B * mp + 8
        ks = jax.random.split(jax.random.fold_in(key, ctx), 6)
        q = jax.random.normal(ks[0], (B, H, DH), jnp.bfloat16)
        kp = jax.random.normal(ks[1], (L * n_pages, PAGE, fused),
                               jnp.float32).astype(KV_DTYPE)
        vp = jax.random.normal(ks[2], (L * n_pages, PAGE, fused),
                               jnp.float32).astype(KV_DTYPE)
        pt = jax.random.randint(ks[3], (B, mp), 0, n_pages, jnp.int32)
        plen = jnp.full((B,), ctx, jnp.int32)
        sk = jax.random.normal(ks[4], (B, W, HKV, DH), jnp.bfloat16)
        sv = jax.random.normal(ks[5], (B, W, HKV, DH), jnp.bfloat16)
        n_side = jnp.full((B,), W // 2, jnp.int32)
        args = (q, kp, vp, pt, plen, sk, sv, n_side)
        # bytes the kernel must stream per call: every live page of K and V
        kv_bytes = 2 * B * mp * PAGE * fused * KV_DTYPE.itemsize
        for bp in BPS:
            try:
                _timed(args, bp, n_pages, PASSES)     # compile
                _timed(args, bp, n_pages, 2 * PASSES)
                t1 = _timed(args, bp, n_pages, PASSES)
                t2 = _timed(args, bp, n_pages, 2 * PASSES)
            except Exception as e:   # VMEM overflow etc: record, move on
                log(f"ctx={ctx} bp={bp}: FAIL {type(e).__name__}: "
                    f"{str(e)[:120]}")
                continue
            dt = max(t2 - t1, 1e-9) / (PASSES * L)    # overhead cancels
            gbps = kv_bytes / dt / 1e9
            row = {"ctx": ctx, "pages_per_block": bp, "B": B,
                   "page_size": PAGE, "fused": fused,
                   "us_per_layer": round(dt * 1e6, 1),
                   "kv_gbps": round(gbps, 1),
                   "pct_peak": round(gbps / PEAK_GBPS, 3)}
            print(json.dumps(row), flush=True)
            cur = best.get(ctx)
            if cur is None or gbps > cur[1]:
                best[ctx] = (bp, gbps)
    log("--- best per ctx ---")
    for ctx, (bp, gbps) in best.items():
        log(f"ctx={ctx}: pages_per_block={bp} {gbps:.0f} GB/s "
            f"({gbps / PEAK_GBPS:.0%} of peak)")


if __name__ == "__main__":
    main()
