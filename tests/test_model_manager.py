"""Multi-model worker tests (-m multimodel): resident-budget LRU
eviction, background staging that never displaces dispatch, the
golden-probe swap gate, model-qualified affinity routing + KV isolation,
and supervisor respawn reloading the full resident catalog.

Unit tests drive ``ModelManager`` directly with fake engines (the
manager is jax-free at import); the integration tests run real
WorkerServers over framed RPC through the coordinator, with per-model
token-exactness checked against the crc32 chain — two models with
different vocabs have DIFFERENT chains, so any cross-model mixing in
routing, KV, or swap shows up as a token divergence.
"""

import asyncio
import time

import pytest

from distributed_inference_engine_tpu.api.coordinator import (
    Coordinator,
    CoordinatorConfig,
)
from distributed_inference_engine_tpu.cluster.model_manager import (
    ModelManager,
    ModelProbeError,
    ModelStageError,
)
from distributed_inference_engine_tpu.cluster.worker import WorkerServer
from distributed_inference_engine_tpu.config import (
    HealthConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.engine.artifact import GOLDEN_PROMPT
from distributed_inference_engine_tpu.models import engine_from_config
from distributed_inference_engine_tpu.models.fake import _chain

pytestmark = pytest.mark.multimodel

VOCAB_A = 997
VOCAB_B = 1009


def expected_tokens(prompt, n, vocab=VOCAB_A):
    st = 0
    for t in prompt:
        st = _chain(st, t)
    out = []
    for _ in range(n):
        nxt = st % vocab
        st = _chain(st, nxt)
        out.append(nxt)
    return out


def fake_cfg(name="m", **meta):
    md = {"continuous": 1, "max_slots": 4}
    md.update(meta)
    return ModelConfig(name=name, architecture="fake", metadata=md)


def golden_probe(vocab):
    """What a healthy engine of ``vocab`` must emit over GOLDEN_PROMPT."""
    return expected_tokens(list(GOLDEN_PROMPT), 8, vocab=vocab)


# --------------------------------------------------- ModelManager (unit)

def test_lru_eviction_under_count_budget():
    """Over the count budget the LEAST-RECENTLY-USED idle model goes;
    ``touch`` refreshes recency, so the routed-to model survives."""
    gone = []
    mm = ModelManager(engine_from_config, max_resident_models=2,
                      on_evict=lambda name, eng: gone.append(name))
    for name in ("a", "b", "c"):
        cfg = fake_cfg(name=name)
        mm.admit(cfg, engine_from_config(cfg))
    assert gone == ["a"]
    assert set(mm.engines) == {"b", "c"}
    assert mm.get_stats()["model_evictions"] == 1
    mm.touch("b")                      # b just served a request
    cfg = fake_cfg(name="d")
    evicted = mm.admit(cfg, engine_from_config(cfg))
    assert evicted == ["c"] and gone == ["a", "c"]
    assert set(mm.engines) == {"b", "d"}


def test_byte_budget_eviction():
    """The byte budget uses the deploy-declared ``size_bytes`` and evicts
    LRU-first until the resident set fits."""
    mm = ModelManager(engine_from_config, resident_bytes=250)
    for name in ("a", "b", "c"):
        cfg = fake_cfg(name=name, size_bytes=100)
        mm.admit(cfg, engine_from_config(cfg))
    assert set(mm.engines) == {"b", "c"}
    assert mm.resident_bytes_used() == 200
    st = mm.get_stats()
    assert st["resident_models"] == 2 and st["resident_bytes"] == 200


def test_busy_model_is_never_evicted():
    """In-flight work pins residency: when every candidate is busy the
    manager stays over budget rather than evicting a serving model."""
    busy = {"a"}
    mm = ModelManager(engine_from_config, max_resident_models=1,
                      busy_fn=lambda name: name in busy)
    for name in ("a", "b"):
        cfg = fake_cfg(name=name)
        mm.admit(cfg, engine_from_config(cfg))
    # a is LRU but busy; b is the new admit (protected) — nobody goes
    assert set(mm.engines) == {"a", "b"}
    assert mm.get_stats()["model_evictions"] == 0
    busy.clear()                       # a drains; next admit collects it
    cfg = fake_cfg(name="c")
    assert mm.admit(cfg, engine_from_config(cfg)) == ["a", "b"]
    assert set(mm.engines) == {"c"}


def test_stage_failure_surfaces_typed_error():
    """A factory crash rides the stage record and surfaces as
    ``ModelStageError`` at swap time; never-staged names fail fast."""
    def boom(cfg):
        raise RuntimeError("corrupt artifact payload")

    mm = ModelManager(boom)
    mm.stage(fake_cfg(name="x"))
    with pytest.raises(ModelStageError, match="corrupt artifact"):
        mm.stage_wait("x", timeout=5.0)
    st = mm.get_stats()
    assert st["stage_started"] == 1 and st["stage_failed"] == 1
    with pytest.raises(ModelStageError, match="not staged"):
        mm.stage_wait("never-staged", timeout=0.1)


def test_probe_gated_swap_rejects_wrong_numerics():
    """A staged engine whose golden-probe tokens diverge (vocab 991 ≠ the
    expected 997 chain) is DISCARDED: swap raises, the resident set and
    the reject counter both show it, and a correct engine still swaps."""
    mm = ModelManager(engine_from_config)
    good = fake_cfg(name="good")
    mm.admit(good, engine_from_config(good))
    mm.stage(fake_cfg(name="bad", vocab_size=991))
    with pytest.raises(ModelProbeError, match="probe FAILED"):
        mm.swap("bad", probe_expected=golden_probe(VOCAB_A))
    assert set(mm.engines) == {"good"}
    assert mm.get_stats()["swap_probe_rejects"] == 1
    # the probe consumes the staged record — the gate cannot be retried
    # into admitting the same rejected build
    assert mm.staged_names() == []
    mm.stage(fake_cfg(name="ok", vocab_size=VOCAB_B))
    receipt = mm.swap("ok", probe_expected=golden_probe(VOCAB_B))
    assert receipt["swapped"] == "ok" and not receipt["already_resident"]
    assert set(mm.engines) == {"good", "ok"}


def test_worker_budget_evicts_idle_on_swap():
    """Worker-level wiring of the ``ServerConfig`` budget knobs: with
    ``max_resident_models=1`` a swap-in evicts the idle previous model
    and tears down its pump."""
    w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                  worker_id="wb", max_resident_models=1))
    try:
        w.load_model(fake_cfg(name="ma"))
        assert w.stage_model(fake_cfg(name="mb", vocab_size=VOCAB_B))
        receipt = w.swap_model("mb", probe_expected=golden_probe(VOCAB_B),
                               timeout=10.0)
        assert receipt["evicted"] == ["ma"]
        assert set(w.engines) == {"mb"}
        assert set(w._pumps) == {"mb"}
    finally:
        for name in list(w.engines):
            w.unload_model(name)


# ------------------------------------------------ fleet (over framed RPC)

async def start_fleet(n_workers, **coord_overrides):
    kw = dict(lb_strategy="prefix_affinity", affinity_page_size=4,
              affinity_pages=2, retry_seed=7, retry_backoff_base_s=0.01)
    kw.update(coord_overrides)
    coord = Coordinator(CoordinatorConfig(**kw))
    await coord.start()
    workers = {}
    for i in range(n_workers):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=f"w{i}"))
        host, port = await w.start()
        workers[f"w{i}"] = w
        coord.add_worker(f"w{i}", host, port)
    return coord, workers


async def stop_fleet(coord, workers):
    await coord.stop()
    for w in workers.values():
        try:
            await w.stop()
        except Exception:
            pass


async def test_background_stage_never_blocks_dispatch():
    """While a 0.6 s stage is in flight, requests keep completing at
    serving latency — a stage that displaced dispatch (ran on the engine
    executor or inside a pump step) would stall one request by the full
    stage cost. The overlap is then read off the swap receipt."""
    coord, workers = await start_fleet(1)
    try:
        await coord.deploy_model(
            fake_cfg(name="ma", step_latency_s=0.005),
            register_shards=False)
        staged = await coord.stage_model(
            fake_cfg(name="mb", vocab_size=VOCAB_B, load_sleep_s=0.6))
        assert staged == 1
        lat = []
        deadline = time.perf_counter() + 0.6
        i = 0
        while time.perf_counter() < deadline:
            p = [3, 1, 4, 100 + i]
            t0 = time.perf_counter()
            r = await coord.submit("ma", prompt=p, max_new_tokens=6,
                                   no_cache=True)
            lat.append(time.perf_counter() - t0)
            assert r["tokens"] == expected_tokens(p, 6)
            i += 1
        assert len(lat) >= 5, "dispatch starved during the stage window"
        assert max(lat) < 0.3, \
            f"a request stalled {max(lat):.3f}s while staging (the stage " \
            f"displaced dispatch)"
        swaps = await coord.swap_model("mb", probe=golden_probe(VOCAB_B))
        assert swaps[0]["overlap_steps"] > 0, \
            "stage overlapped zero serving steps"
        m = await coord.router.client_for("w0").metrics()
        assert m["stage_overlap_steps"] > 0
        assert set(m["models"]) == {"ma", "mb"}
    finally:
        await stop_fleet(coord, workers)


async def test_swap_probe_reject_over_rpc_keeps_serving():
    """A bad staged artifact (vocab 991: the probe's greedy tokens
    diverge) must be rejected at swap over RPC; the resident model keeps
    serving token-exact and the reject is counted."""
    coord, workers = await start_fleet(1)
    try:
        await coord.deploy_model(fake_cfg(name="ma"),
                                 register_shards=False)
        await coord.stage_model(fake_cfg(name="mb", vocab_size=991))
        with pytest.raises(Exception, match="probe FAILED"):
            await coord.swap_model("mb", probe=golden_probe(VOCAB_B))
        m = await coord.router.client_for("w0").metrics()
        assert m["swap_probe_rejects"] == 1
        assert set(m["models"]) == {"ma"}
        p = [9, 8, 7]
        r = await coord.submit("ma", prompt=p, max_new_tokens=6,
                               no_cache=True)
        assert r["tokens"] == expected_tokens(p, 6)
    finally:
        await stop_fleet(coord, workers)


async def test_model_qualified_affinity_and_isolation():
    """Two models on one fleet: affinity keys are model-qualified (the
    same prompt under ma and mb binds under DIFFERENT keys), per-model
    LB counters account every pick, and each model's tokens follow its
    own vocab chain — any cross-model KV or routing mix-up diverges."""
    coord, workers = await start_fleet(2)
    try:
        await coord.deploy_model(fake_cfg(name="ma"),
                                 register_shards=False)
        await coord.deploy_model(fake_cfg(name="mb", vocab_size=VOCAB_B),
                                 register_shards=False)
        prefix = [5, 5, 5, 5]          # one full affinity page
        for i in range(8):
            p = prefix + [50 + i]
            ra = await coord.submit("ma", prompt=p, max_new_tokens=6,
                                    no_cache=True)
            rb = await coord.submit("mb", prompt=p, max_new_tokens=6,
                                    no_cache=True)
            assert ra["tokens"] == expected_tokens(p, 6, vocab=VOCAB_A)
            assert rb["tokens"] == expected_tokens(p, 6, vocab=VOCAB_B)
            assert ra["tokens"] != rb["tokens"]
        models_of_keys = {k.split(":", 1)[0]
                          for k in coord.lb._affinity}
        assert models_of_keys == {"ma", "mb"}, \
            f"affinity keys not model-qualified: {models_of_keys}"
        per_model = coord.lb.get_all_stats()["affinity_models"]
        for name in ("ma", "mb"):
            rec = per_model[name]
            assert rec["hits"] == 7 and rec["misses"] == 1, rec
    finally:
        await stop_fleet(coord, workers)


async def test_respawn_reloads_full_resident_set():
    """Supervisor respawn of a multi-model worker must reload EVERY
    catalog model, not just one — the replacement rejoins able to serve
    both chains token-exact."""
    coord, workers = await start_fleet(
        2,
        health=HealthConfig(check_interval=0.05, check_timeout=0.5,
                            max_consecutive_failures=2),
        supervisor_interval_s=0.05, supervisor_backoff_base_s=0.02,
        supervisor_backoff_max_s=0.1)
    spawned = []

    async def restart_hook(worker_id, info):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=worker_id))
        host, port = await w.start()
        spawned.append(w)
        return host, port

    coord.start_supervisor(restart_hook)
    try:
        await coord.deploy_model(fake_cfg(name="ma"),
                                 register_shards=False)
        await coord.deploy_model(fake_cfg(name="mb", vocab_size=VOCAB_B),
                                 register_shards=False)
        await workers.pop("w1").stop()
        for _ in range(100):
            if coord.get_stats()["supervisor_respawns"] >= 1:
                break
            await asyncio.sleep(0.05)
        assert coord.get_stats()["supervisor_respawns"] >= 1
        res = await coord.router.client_for("w1").resident_models()
        assert set(res["resident"]) == {"ma", "mb"}, \
            f"respawn reloaded {res['resident']}, catalog is [ma, mb]"
        assert "w1" in coord.lb.workers_with_model("mb")
        p = [2, 4, 6]
        for name, vocab in (("ma", VOCAB_A), ("mb", VOCAB_B)):
            r = await coord.submit(name, prompt=p, max_new_tokens=6,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens(p, 6, vocab=vocab)
    finally:
        await stop_fleet(coord, workers)
        for w in spawned:
            try:
                await w.stop()
            except Exception:
                pass
