"""Paged decode attention: Pallas TPU kernel + XLA reference implementation.

This is the TPU-native answer to SURVEY.md §7 hard-part #2 (paged KV cache in
HBM) and the north-star reinterpretation of the reference's ``src/kvstore.py``
cache: attention state lives in a pool of fixed-size HBM pages instead of one
contiguous row per sequence, so long and short sequences share HBM without
fragmentation and page recycling replaces whole-row eviction.

Layout (per layer):

- ``k_pages`` / ``v_pages``: ``[num_pages, page_size, n_kv * head_dim]`` —
  the trailing dim is fused so every VMEM block is lane-aligned (the kernel
  requires ``n_kv * head_dim`` to be a multiple of 128, the TPU lane count).
- ``page_table``: ``[batch, max_pages_per_seq]`` int32 — logical page ``p`` of
  slot ``b`` lives in physical page ``page_table[b, p]``. Unused entries must
  hold a valid page id (0): the kernel still DMAs them (static grid) and masks
  the scores, so the id only has to be safe to read.
- ``lengths``: ``[batch]`` int32 — live tokens per slot, *including* the
  token at the current decode position.

Kernel design (flash-style online softmax over pages):

- Grid ``(batch, max_pages_per_seq)``; the page table and lengths ride
  ``PrefetchScalarGridSpec`` so the index map can translate logical→physical
  page ids before the block DMA is issued — the gather lives in the DMA
  engine, not in compute.
- Per grid step one K page and one V page are DMA'd to VMEM (double-buffered
  by the Pallas pipeline across the sequential page axis), scores are computed
  on the MXU in fp32, and VMEM scratch carries the running (max, sum, acc)
  across pages of the same row.
- GQA without materialization: Q is reshaped ``[n_kv, group, head_dim]`` and
  contracted per kv-head, so grouped queries share one K/V load.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# ----------------------------------------------------------------- XLA path


def paged_attention_xla(
    q: jnp.ndarray,            # [B, H, Dh]
    k_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    v_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    page_table: jnp.ndarray,   # [B, MP] int32
    lengths: jnp.ndarray,      # [B] int32
    *,
    n_kv_heads: int,
) -> jnp.ndarray:
    """Reference implementation via gather; correct everywhere (CPU tests,
    interpret-mode cross-check), but reads the whole gathered cache through
    XLA's generic scatter/gather path. Returns [B, H, Dh] in q.dtype."""
    b, h, dh = q.shape
    n, p, fused = k_pages.shape
    mp = page_table.shape[1]
    g = h // n_kv_heads

    k = k_pages[page_table]                       # [B, MP, P, Hkv*Dh]
    v = v_pages[page_table]
    k = k.reshape(b, mp * p, n_kv_heads, dh)      # [B, S, Hkv, Dh]
    v = v.reshape(b, mp * p, n_kv_heads, dh)

    qg = q.reshape(b, n_kv_heads, g, dh)
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k).astype(jnp.float32) * scale
    valid = jnp.arange(mp * p)[None, :] < lengths[:, None]        # [B, S]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(v.dtype), v)
    return out.reshape(b, h, dh).astype(q.dtype)


# -------------------------------------------------------------- Pallas path


def _paged_attn_kernel(
    # scalar prefetch
    page_table_ref,            # [B, MP] SMEM
    lengths_ref,               # [B] SMEM
    # blocks
    q_ref,                     # [1, H * Dh] VMEM
    k_ref,                     # [1, P, Hkv * Dh] VMEM (one physical page)
    v_ref,                     # [1, P, Hkv * Dh] VMEM
    out_ref,                   # [1, H * Dh] VMEM
    # scratch
    m_scr,                     # [H, 128] f32
    l_scr,                     # [H, 128] f32
    acc_scr,                   # [H, Dh] f32
    *,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    length = lengths_ref[b]
    dh = head_dim

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages past the live prefix contribute nothing; skip their FLOPs
    live = p * page_size < length

    @pl.when(live)
    def _page():
        h_total = q_ref.shape[1] // dh
        g = h_total // n_kv_heads
        q = q_ref[0, :].reshape(n_kv_heads, g, dh)            # [Hkv, G, Dh]
        k = k_ref[0].reshape(page_size, n_kv_heads, dh)       # [P, Hkv, Dh]
        v = v_ref[0].reshape(page_size, n_kv_heads, dh)

        # scores [Hkv, G, P]: contract Dh, batch over Hkv (MXU, fp32 accum)
        scores = lax.dot_general(
            q, k,
            dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * (1.0 / (dh ** 0.5))

        tok = p * page_size + lax.broadcasted_iota(
            jnp.int32, (n_kv_heads, g, page_size), 2
        )
        scores = jnp.where(tok < length, scores, NEG_INF)
        scores = scores.reshape(h_total, page_size)           # [H, P]

        m_prev = m_scr[:, 0][:, None]                         # [H, 1]
        l_prev = l_scr[:, 0][:, None]
        m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                       # [H, 1]
        probs = jnp.exp(scores - m_new)                       # [H, P]
        l_new = l_prev * alpha + probs.sum(axis=-1, keepdims=True)

        # pv [Hkv, G, Dh]: contract P, batch over Hkv
        pv = lax.dot_general(
            probs.reshape(n_kv_heads, g, page_size),
            v.astype(jnp.float32),
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ).reshape(h_total, dh)

        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p == n_pages - 1)
    def _finish():
        h_total = q_ref.shape[1] // dh
        l = jnp.maximum(l_scr[:, 0][:, None], 1e-30)          # [H, 1]
        out = (acc_scr[:] / l).reshape(1, h_total * dh)
        out_ref[:] = out.astype(out_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,            # [B, H, Dh]
    k_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    v_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    page_table: jnp.ndarray,   # [B, MP] int32
    lengths: jnp.ndarray,      # [B] int32
    *,
    n_kv_heads: int,
    interpret: bool = False,
) -> jnp.ndarray:
    b, h, dh = q.shape
    n, page_size, fused = k_pages.shape
    mp = page_table.shape[1]
    if fused != n_kv_heads * dh:
        raise ValueError(f"fused dim {fused} != n_kv_heads*head_dim {n_kv_heads * dh}")
    if fused % 128:
        raise ValueError(
            f"n_kv_heads*head_dim = {fused} must be a multiple of 128 (TPU lanes)"
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mp),
        in_specs=[
            pl.BlockSpec((1, h * dh), lambda i, p, pt, ln: (i, 0)),
            pl.BlockSpec((1, page_size, fused), lambda i, p, pt, ln: (pt[i, p], 0, 0)),
            pl.BlockSpec((1, page_size, fused), lambda i, p, pt, ln: (pt[i, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h * dh), lambda i, p, pt, ln: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_attn_kernel,
        n_kv_heads=n_kv_heads,
        head_dim=dh,
        page_size=page_size,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h * dh), q.dtype),
        interpret=interpret,
    )(page_table, lengths, q.reshape(b, h * dh), k_pages, v_pages)
    return out.reshape(b, h, dh)


# ------------------------------------------------------------- dispatcher


def paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    n_kv_heads: int,
    impl: str = "auto",
) -> jnp.ndarray:
    """impl: "auto" (pallas on TPU, xla elsewhere) | "xla" | "pallas" |
    "pallas_interpret" (kernel correctness tests on CPU)."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return paged_attention_xla(
            q, k_pages, v_pages, page_table, lengths, n_kv_heads=n_kv_heads
        )
    if impl in ("pallas", "pallas_interpret"):
        return paged_attention_pallas(
            q, k_pages, v_pages, page_table, lengths,
            n_kv_heads=n_kv_heads, interpret=impl == "pallas_interpret",
        )
    raise ValueError(f"unknown paged-attention impl {impl!r}")
