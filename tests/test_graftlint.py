"""graftlint: rule fixtures (each family: fires on bad, silent on good),
pragma + baseline mechanics, CLI, and the zero-findings gate on the real
tree. Pure stdlib — no jax import anywhere on this path."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from scripts.graftlint import lint_source, lint_paths, all_rules  # noqa: E402
from scripts.graftlint.core import (  # noqa: E402
    Baseline, Finding, build_project, run_rules, suppress, unsuppressed,
)
from scripts.graftlint.drift_rules import (  # noqa: E402
    check_events_drift, check_knob_drift, check_metrics_drift,
)

pytestmark = pytest.mark.lint


def rules_fired(src, **kw):
    return {f.rule for f in lint_source(textwrap.dedent(src), **kw)
            if f.suppressed_by is None}


# --------------------------------------------------------- host-sync-hot-path

HOT_SYNC_BAD = """
    import numpy as np
    from utils.hotpath import hot_path

    @hot_path
    def step(self):
        helper(self)

    def helper(self):
        x = np.asarray(self.device_buf)     # device read in the hot graph
        return x
"""

HOT_SYNC_GOOD = """
    import numpy as np
    from utils.hotpath import hot_path

    @hot_path
    def step(self):
        rows = [1, 2, 3]
        a = np.asarray(rows)                # host list -> host array
        lengths_np = self.mirror
        b = np.asarray(lengths_np[:2])      # *_np naming convention
        return a, b

    def cold(self):
        return np.asarray(self.device_buf)  # not reachable from a seed
"""


def test_host_sync_fires_through_call_graph():
    assert "host-sync-hot-path" in rules_fired(HOT_SYNC_BAD)


def test_host_sync_silent_on_host_data_and_cold_code():
    assert "host-sync-hot-path" not in rules_fired(HOT_SYNC_GOOD)


def test_host_sync_flags_item_and_device_get():
    src = """
        import jax
        from utils.hotpath import hot_path

        @hot_path
        def step(self):
            n = self.counter_dev.item()
            y = jax.device_get(self.buf)
            self.buf.block_until_ready()
            return n, y
    """
    fired = [f for f in lint_source(textwrap.dedent(src))
             if f.rule == "host-sync-hot-path"]
    assert len(fired) == 3


# ----------------------------------------------------------------- jit rules

def test_jit_static_argnames_typo_fires():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n_stepz",))
        def f(x, n_steps):
            return x
    """
    assert "jit-static-argnames" in rules_fired(src)


def test_jit_static_argnames_valid_silent():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0,))
        def f(x, n_steps):
            return x
    """
    assert "jit-static-argnames" not in rules_fired(src)


def test_jit_donate_argnums_out_of_range_fires():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(5,))
        def f(x, y):
            return x
    """
    assert "jit-static-argnames" in rules_fired(src)


def test_jit_in_loop_fires():
    src = """
        import jax

        def build(fns):
            out = []
            for fn in fns:
                out.append(jax.jit(fn))     # fresh cache every iteration
            return out
    """
    assert "jit-in-loop" in rules_fired(src)


def test_jit_in_hot_function_fires_but_init_exempt():
    bad = """
        import jax
        from utils.hotpath import hot_path

        @hot_path
        def step(self):
            f = jax.jit(self.kernel)        # per-request rewrap
            return f()
    """
    good = """
        import jax
        from utils.hotpath import hot_path

        class Engine:
            def __init__(self):
                self._f = jax.jit(kernel)   # once at init — fine

            @hot_path
            def step(self):
                self.__init__()             # makes __init__ hot-reachable
                return self._f()
    """
    assert "jit-in-loop" in rules_fired(bad)
    assert "jit-in-loop" not in rules_fired(good)


def test_jit_unbucketed_shape_fires_and_bucketed_silent():
    bad = """
        import numpy as np
        from utils.hotpath import hot_path

        @hot_path
        def step(self, rows):
            n = len(rows)
            pad = np.zeros((n,), np.int32)   # one compile per size
            return pad
    """
    good = """
        import numpy as np
        from utils.hotpath import hot_path

        @hot_path
        def step(self, rows):
            b = _next_bucket(len(rows), self.buckets)
            bb = 1 << (len(rows) - 1).bit_length()   # inline pow2 idiom
            return np.zeros((b,), np.int32), np.zeros((bb,), np.int32)

        def _next_bucket(n, buckets):
            return max(n, 1)
    """
    assert "jit-unbucketed-shape" in rules_fired(bad)
    assert "jit-unbucketed-shape" not in rules_fired(good)


# --------------------------------------------------------------- async rules

def test_async_blocking_call_fires():
    src = """
        import time

        async def handler(self):
            time.sleep(1.0)
    """
    assert "async-blocking-call" in rules_fired(src)


def test_async_sleep_ok_and_serving_plane_sync_sleep():
    good = """
        import asyncio

        async def handler(self):
            await asyncio.sleep(1.0)
    """
    assert "async-blocking-call" not in rules_fired(good)
    sync_sleep = """
        import time

        def pump(self):
            time.sleep(0.1)
    """
    # same code: flagged inside cluster/, silent elsewhere
    assert "async-blocking-call" in rules_fired(
        sync_sleep, relpath="pkg/cluster/pump.py")
    assert "async-blocking-call" not in rules_fired(
        sync_sleep, relpath="pkg/models/pump.py")


def test_async_unawaited_coroutine_fires_and_awaited_silent():
    bad = """
        async def work(self):
            pass

        async def caller(self):
            work(self)                      # coroutine never scheduled
    """
    good = """
        async def work(self):
            pass

        async def caller(self):
            await work(self)
    """
    assert "async-unawaited-coroutine" in rules_fired(bad)
    assert "async-unawaited-coroutine" not in rules_fired(good)


def test_async_orphan_task_fires_and_retained_silent():
    bad = """
        import asyncio

        def kick(loop, coro):
            loop.create_task(coro)          # Task dropped on the floor
    """
    good = """
        import asyncio

        def kick(self, loop, coro):
            task = loop.create_task(coro)
            self._bg.add(task)
            task.add_done_callback(self._bg.discard)
    """
    assert "async-orphan-task" in rules_fired(bad)
    assert "async-orphan-task" not in rules_fired(good)


# ------------------------------------------- swallowed-transport-error

SWALLOW_BAD = """
    async def probe(self, wid):
        try:
            await self.client.ping()
        except ConnectionError:
            pass
"""

SWALLOW_BARE = """
    def close(self):
        try:
            self.sock.close()
        except:
            pass
"""


def test_swallowed_transport_error_fires_in_serving_plane():
    assert "swallowed-transport-error" in rules_fired(
        SWALLOW_BAD, relpath="pkg/api/x.py")
    assert "swallowed-transport-error" in rules_fired(
        SWALLOW_BARE, relpath="pkg/cluster/x.py")
    broad = """
        async def sweep(self):
            try:
                await self.check_all()
            except Exception:
                self.log.exception("sweep failed")
    """
    assert "swallowed-transport-error" in rules_fired(
        broad, relpath="pkg/serving/x.py")


def test_swallowed_transport_error_silent_outside_serving_plane():
    assert "swallowed-transport-error" not in rules_fired(
        SWALLOW_BAD, relpath="pkg/models/x.py")


def test_swallowed_transport_error_silent_when_acknowledged():
    marks = """
        async def probe(self, wid):
            try:
                await self.client.ping()
            except (OSError, ConnectionError):
                self.mark_worker_failure(wid)
    """
    reraises = """
        async def fetch(self):
            try:
                return await self.client.call("metrics")
            except ConnectionResetError:
                raise RuntimeError("worker gone")
    """
    reads_bound = """
        async def fetch(self):
            try:
                return await self.client.call("metrics")
            except TimeoutError as e:
                self.log.warning("slow worker: %s", e)
                return None
    """
    moves_field = """
        async def probe(self, wid):
            try:
                await self.client.ping()
            except BrokenPipeError:
                self._consecutive_failures += 1
    """
    app_error = """
        async def fetch(self):
            try:
                return await self.client.call("metrics")
            except KeyError:
                return None
    """
    for src in (marks, reraises, reads_bound, app_error):
        assert "swallowed-transport-error" not in rules_fired(
            src, relpath="pkg/api/x.py"), src
    # AugAssign to a health-ish attribute counts as acknowledgement
    fired = {f.rule for f in lint_source(
        textwrap.dedent(moves_field), relpath="pkg/api/x.py")
        if f.suppressed_by is None}
    assert "swallowed-transport-error" not in fired


def test_swallowed_transport_error_pragma_suppresses():
    src = """
        async def close(self):
            try:
                await self.writer.wait_closed()
            # graftlint: ok[swallowed-transport-error] teardown of a dead socket
            except (ConnectionResetError, BrokenPipeError):
                pass
    """
    findings = lint_source(textwrap.dedent(src), relpath="pkg/api/x.py")
    mine = [f for f in findings if f.rule == "swallowed-transport-error"]
    assert mine and all(f.suppressed_by == "pragma" for f in mine)


# ------------------------------------------- non-atomic-serving-write

ATOMIC_BAD_OPEN = """
    import json

    def dump(self, path, obj):
        with open(path, "w") as f:
            json.dump(obj, f)
"""

ATOMIC_BAD_WRITE_TEXT = """
    import json, pathlib

    def save(self, path, obj):
        pathlib.Path(path).write_text(json.dumps(obj))
"""


def test_non_atomic_serving_write_fires_in_persistence_plane():
    # the serving plane, obs/, and the two named artifact/checkpoint
    # modules are all "persistence plane"
    for rel in ("pkg/api/x.py", "pkg/obs/x.py",
                "pkg/utils/checkpoint.py", "pkg/engine/artifact.py"):
        assert "non-atomic-serving-write" in rules_fired(
            ATOMIC_BAD_OPEN, relpath=rel), rel
    assert "non-atomic-serving-write" in rules_fired(
        ATOMIC_BAD_WRITE_TEXT, relpath="pkg/cluster/x.py")
    # mode= keyword and append mode count too
    kw_mode = """
        def log(self, path, line):
            with open(path, mode="a") as f:
                f.write(line)
    """
    assert "non-atomic-serving-write" in rules_fired(
        kw_mode, relpath="pkg/obs/x.py")


def test_non_atomic_serving_write_silent_outside_plane_and_on_reads():
    assert "non-atomic-serving-write" not in rules_fired(
        ATOMIC_BAD_OPEN, relpath="pkg/models/x.py")
    reads = """
        import json

        def load(self, path):
            with open(path) as f:
                return json.load(f)

        def load_b(self, path):
            with open(path, "rb") as f:
                return f.read()
    """
    assert "non-atomic-serving-write" not in rules_fired(
        reads, relpath="pkg/api/x.py")
    # the atomic helper's own implementation is exempt
    assert "non-atomic-serving-write" not in rules_fired(
        ATOMIC_BAD_OPEN, relpath="pkg/utils/files.py")


def test_non_atomic_serving_write_pragma_suppresses():
    src = """
        def append_line(self, path, line):
            # graftlint: ok[non-atomic-serving-write] append-only log, readers tolerate truncation
            with open(path, "a") as f:
                f.write(line)
    """
    findings = lint_source(textwrap.dedent(src), relpath="pkg/api/x.py")
    mine = [f for f in findings if f.rule == "non-atomic-serving-write"]
    assert mine and all(f.suppressed_by == "pragma" for f in mine)


# ------------------------------------------------------------------- pragmas

def test_pragma_suppresses_same_line_and_line_above():
    same = """
        import time

        async def f(self):
            time.sleep(1)  # graftlint: ok[async-blocking-call] test fixture
    """
    above = """
        import time

        async def f(self):
            # graftlint: ok[async-blocking-call] test fixture
            time.sleep(1)
    """
    for src in (same, above):
        fs = lint_source(textwrap.dedent(src))
        hit = [f for f in fs if f.rule == "async-blocking-call"]
        assert hit and all(f.suppressed_by == "pragma" for f in hit)


def test_pragma_wrong_rule_does_not_suppress():
    src = """
        import time

        async def f(self):
            time.sleep(1)  # graftlint: ok[jit-in-loop] wrong rule id
    """
    assert "async-blocking-call" in rules_fired(src)


def test_reasonless_pragma_is_itself_a_finding():
    src = """
        import time

        async def f(self):
            time.sleep(1)  # graftlint: ok[async-blocking-call]
    """
    fired = rules_fired(src)
    assert "pragma-missing-reason" in fired
    assert "async-blocking-call" not in fired   # pragma still suppresses


# ------------------------------------------------------------------ baseline

BASELINE_SRC = textwrap.dedent("""
    import time

    async def f(self):
        time.sleep(1)
""")


def _project_with(tmp_path, source):
    p = tmp_path / "mod.py"
    p.write_text(source)
    return build_project([str(p)], str(tmp_path))


def test_baseline_suppresses_and_line_shift_survives(tmp_path):
    project = _project_with(tmp_path, BASELINE_SRC)
    findings = run_rules(project, rules=["async-blocking-call"])
    bl_path = tmp_path / "baseline.json"
    Baseline.write(str(bl_path), findings)

    # shifted two lines down: same stripped line content -> still covered
    shifted = "# pad\n# pad\n" + BASELINE_SRC
    project2 = _project_with(tmp_path, shifted)
    findings2 = run_rules(project2, rules=["async-blocking-call"])
    suppress(project2, findings2, Baseline.load(str(bl_path)))
    assert findings2 and all(
        f.suppressed_by == "baseline" for f in findings2)


def test_baseline_does_not_cover_new_findings(tmp_path):
    project = _project_with(tmp_path, BASELINE_SRC)
    findings = run_rules(project, rules=["async-blocking-call"])
    bl_path = tmp_path / "baseline.json"
    Baseline.write(str(bl_path), findings)

    # editing the flagged line invalidates its baseline key
    edited = BASELINE_SRC.replace("time.sleep(1)", "time.sleep(2)")
    project2 = _project_with(tmp_path, edited)
    findings2 = run_rules(project2, rules=["async-blocking-call"])
    suppress(project2, findings2, Baseline.load(str(bl_path)))
    assert unsuppressed(findings2)


def test_baseline_multiset_counts(tmp_path):
    two = BASELINE_SRC + "\n\nasync def g(self):\n    time.sleep(1)\n"
    project = _project_with(tmp_path, two)
    findings = run_rules(project, rules=["async-blocking-call"])
    assert len(findings) == 2
    bl = Baseline([{"rule": "async-blocking-call", "path": "mod.py",
                    "key": "time.sleep(1)"}])     # accepts ONE, not both
    suppress(project, findings, bl)
    assert len(unsuppressed(findings)) == 1


# --------------------------------------------------------------- drift rules

def _mini_repo(tmp_path, catalog_body, doc_table):
    pkg = tmp_path / "distributed_inference_engine_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg.parent / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "collectors.py").write_text(catalog_body)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(doc_table)
    return str(tmp_path)


CATALOG_BODY = 'CATALOG = {"reqs_total": ("counter", (), "h")}\n'


def test_metrics_drift_detects_all_three_directions(tmp_path):
    root = _mini_repo(
        tmp_path, CATALOG_BODY,
        "| `reqs_total` | gauge |  |  |\n| `ghost_total` | counter |  |  |\n")
    rules = {f.key for f in check_metrics_drift(root)}
    assert rules == {"reqs_total", "ghost_total"}   # kind drift + stale row


def test_metrics_drift_clean(tmp_path):
    root = _mini_repo(tmp_path, CATALOG_BODY,
                      "| `reqs_total` | counter |  |  |\n")
    # load_catalog imports under a per-root alias, so this works even
    # with the real repo's package already imported by earlier tests
    assert check_metrics_drift(root) == []


EVENTS_BODY = 'EVENTS = {"drain.begin": "h", "drain.done": "h"}\n'

EVENT_TABLE = ("| event | emitter | meaning |\n"
               "| --- | --- | --- |\n"
               "| `drain.begin` | worker |  |\n"
               "| `drain.done` | worker |  |\n")


def _mini_events_repo(tmp_path, events_body, doc_text):
    pkg = tmp_path / "distributed_inference_engine_tpu" / "obs"
    pkg.mkdir(parents=True)
    (pkg.parent / "__init__.py").write_text("")
    (pkg / "__init__.py").write_text("")
    (pkg / "events.py").write_text(events_body)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(doc_text)
    return str(tmp_path)


def test_events_drift_clean(tmp_path):
    root = _mini_events_repo(tmp_path, EVENTS_BODY, EVENT_TABLE)
    assert check_events_drift(root) == []


def test_events_drift_detects_both_directions(tmp_path):
    doc = ("| event | emitter | meaning |\n| --- | --- | --- |\n"
           "| `drain.begin` | worker |  |\n"
           "| `ghost.event` | nobody |  |\n")
    root = _mini_events_repo(tmp_path, EVENTS_BODY, doc)
    keys = {f.key for f in check_events_drift(root)}
    assert keys == {"drain.done",      # in catalog, undocumented
                    "ghost.event"}     # documented, emit would raise


def test_events_drift_ignores_rows_outside_event_table(tmp_path):
    # dotted code spans in OTHER tables (e.g. the trace-phase glossary)
    # must not be mistaken for event-catalog rows
    doc = ("| phase | meaning |\n| --- | --- |\n"
           "| `worker.received` | glossary row, not an event |\n\n"
           + EVENT_TABLE)
    root = _mini_events_repo(tmp_path, EVENTS_BODY, doc)
    assert check_events_drift(root) == []


def test_knob_drift_stale_field_and_phantom_bench_var(tmp_path):
    (tmp_path / "distributed_inference_engine_tpu").mkdir()
    (tmp_path / "distributed_inference_engine_tpu" / "config.py").write_text(
        "class EngineConfig:\n    max_slots: int = 8\n")
    (tmp_path / "README.md").write_text(
        "Set `EngineConfig.max_slotz` and BENCH_GHOST.\n")
    (tmp_path / "bench.py").write_text(
        '"""knobs: BENCH_REAL documented."""\n'
        'import os\nV = os.environ.get("BENCH_REAL", "1")\n'
        'W = os.environ.get("BENCH_SECRET", "1")\n')
    keys = {f.key for f in check_knob_drift(str(tmp_path))}
    assert keys == {"EngineConfig.max_slotz",   # stale field ref
                    "BENCH_GHOST",              # documented, never read
                    "BENCH_SECRET"}             # read, never documented


def test_knob_drift_clean(tmp_path):
    (tmp_path / "distributed_inference_engine_tpu").mkdir()
    (tmp_path / "distributed_inference_engine_tpu" / "config.py").write_text(
        "class EngineConfig:\n    max_slots: int = 8\n")
    (tmp_path / "README.md").write_text("Set `EngineConfig.max_slots`.\n")
    (tmp_path / "bench.py").write_text(
        '"""knobs: BENCH_REAL."""\n'
        'import os\nV = os.environ.get("BENCH_REAL", "1")\n')
    assert check_knob_drift(str(tmp_path)) == []


# ------------------------------------------------------------------- imports

def test_undeclared_import_fires_without_requirements(tmp_path):
    (tmp_path / "m.py").write_text("import totallyfakepkg\n")
    findings = lint_paths([str(tmp_path)], root=str(tmp_path),
                          rules=["undeclared-import"])
    assert any(f.rule == "undeclared-import" for f in unsuppressed(findings))


def test_undeclared_import_clean_when_declared(tmp_path):
    (tmp_path / "m.py").write_text("import os, json\nimport totallyfakepkg\n")
    (tmp_path / "requirements.txt").write_text("totallyfakepkg>=1.0\n")
    findings = lint_paths([str(tmp_path)], root=str(tmp_path),
                          rules=["undeclared-import"])
    assert unsuppressed(findings) == []


def test_stale_requirement_fires(tmp_path):
    (tmp_path / "m.py").write_text("import totallyfakepkg\n")
    (tmp_path / "requirements.txt").write_text(
        "totallyfakepkg\nunusedpkg\n")
    findings = lint_paths([str(tmp_path)], root=str(tmp_path),
                          rules=["undeclared-import"])
    live = unsuppressed(findings)
    assert len(live) == 1 and "unusedpkg" in live[0].message


# ------------------------------------------------------------------ CLI/gate

def _cli(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", *args],
        capture_output=True, text=True, cwd=cwd)


def test_cli_zero_findings_on_real_tree():
    """The acceptance gate: the shipped tree is graftlint-clean."""
    out = _cli("distributed_inference_engine_tpu", "bench.py")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 finding(s)" in out.stdout


def test_cli_json_format_and_exit_code(tmp_path):
    (tmp_path / "m.py").write_text(
        "import time\n\nasync def f(self):\n    time.sleep(1)\n")
    out = _cli(str(tmp_path / "m.py"), "--format", "json",
               "--baseline", "none", "--rules", "async-blocking-call")
    assert out.returncode == 1
    data = json.loads(out.stdout)
    assert data and data[0]["rule"] == "async-blocking-call"
    assert data[0]["line"] == 4


def test_cli_update_baseline_roundtrip(tmp_path):
    src = tmp_path / "m.py"
    src.write_text("import time\n\nasync def f(self):\n    time.sleep(1)\n")
    bl = tmp_path / "bl.json"
    out = _cli(str(src), "--baseline", str(bl), "--update-baseline",
               "--rules", "async-blocking-call")
    assert out.returncode == 0 and "BASELINE UPDATED" in out.stdout
    out2 = _cli(str(src), "--baseline", str(bl),
                "--rules", "async-blocking-call")
    assert out2.returncode == 0, out2.stdout
    assert "1 baseline-suppressed" in out2.stdout


def test_every_rule_family_registered():
    fams = {r.family for r in all_rules().values()}
    assert {"hot-path", "jit", "async", "drift"} <= fams


def test_every_pragma_in_tree_has_reason():
    """Repo invariant: no reasonless ok[...] anywhere (the rule enforces
    it per-run; this pins it for the whole package explicitly)."""
    findings = lint_paths(
        [os.path.join(ROOT, "distributed_inference_engine_tpu")], root=ROOT)
    assert not [f for f in findings if f.rule == "pragma-missing-reason"]
