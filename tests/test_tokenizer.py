"""Tokenizer layer: byte fallback, BPE correctness, native C++ core vs the
pure-Python mirror (same ranked-merge algorithm, identical outputs)."""

import json

import pytest

from distributed_inference_engine_tpu.utils.tokenizer import (
    BPETokenizer,
    ByteTokenizer,
    _bytes_to_unicode,
    _py_bpe_encode,
    build_tokenizer,
)


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    s = "hello, TPU! ünïcödé"
    assert t.decode(t.encode(s)) == s
    ids = t.encode("ab", add_bos=True, add_eos=True)
    assert ids[0] == t.BOS and ids[-1] == t.EOS


def _toy_bpe(**kw):
    """Tiny hand-built vocab: bytes for 'abcd ' + merged units."""
    b2u = _bytes_to_unicode()
    base = [b2u[ord(c)] for c in "abcd "]
    vocab = {u: i for i, u in enumerate(base)}
    a, b, c, d = (b2u[ord(x)] for x in "abcd")
    for unit in (a + b, c + d, a + b + c + d):
        vocab[unit] = len(vocab)
    merges = [(a, b), (c, d), (a + b, c + d)]
    return BPETokenizer(vocab, merges, **kw)


def test_bpe_merges_applied_in_rank_order():
    t = _toy_bpe(use_native=False)
    # "abcd" -> ab, cd -> abcd (one token)
    assert len(t.encode("abcd")) == 1
    assert t.encode("ab cd") != t.encode("abcd")
    assert t.decode(t.encode("abcd ab")) == "abcd ab"


def test_native_matches_python():
    t_native = _toy_bpe(use_native=True)
    t_py = _toy_bpe(use_native=False)
    if not t_native.native_enabled:
        pytest.skip("no native toolchain")
    for text in ["", "a", "abcd", "ab cd abcd", "dcba", "abcabcd abcd d",
                 "aaaa bbbb abab"]:
        assert t_native.encode(text) == t_py.encode(text), text


def test_native_matches_python_fuzz():
    import random

    t_native = _toy_bpe(use_native=True)
    t_py = _toy_bpe(use_native=False)
    if not t_native.native_enabled:
        pytest.skip("no native toolchain")
    rng = random.Random(0)
    for _ in range(50):
        s = "".join(rng.choice("abcd ") for _ in range(rng.randrange(1, 60)))
        assert t_native.encode(s) == t_py.encode(s), s


def test_bpe_from_pretrained_dir(tmp_path):
    b2u = _bytes_to_unicode()
    a, b = b2u[ord("a")], b2u[ord("b")]
    vocab = {a: 0, b: 1, a + b: 2}
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(f"#version\n{a} {b}\n")
    t = BPETokenizer.from_pretrained_dir(str(tmp_path))
    assert t.encode("ab") == [2]
    assert t.decode([2, 0]) == "aba"
    assert isinstance(build_tokenizer(str(tmp_path)), BPETokenizer)
    assert isinstance(build_tokenizer(""), ByteTokenizer)


def test_py_core_tie_break_is_leftmost():
    # two applications of the same rank: leftmost merges first
    ranks = {(0, 1): (0, 9)}
    assert _py_bpe_encode([0, 1, 0, 1], ranks) == [9, 9]
