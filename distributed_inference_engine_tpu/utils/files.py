"""Filesystem helpers shared by every snapshot writer.

One definition of the atomic-write dance (tempfile in the target dir →
write → ``os.replace``) so the coordinator state snapshot, the response
cache snapshot, and future writers cannot drift on crash semantics: a
failure mid-write must leave any previous file intact, and a crash must
not litter half-written temp files that later reads could mistake for
snapshots.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Callable, IO


def atomic_write(path: str, write_fn: Callable[[IO], None],
                 binary: bool = False) -> str:
    """Write ``path`` atomically: ``write_fn(f)`` fills a temp file in the
    same directory, then ``os.replace`` swaps it in. On any failure the
    temp file is removed and the previous ``path`` (if any) is untouched.
    Returns ``path``."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix="." + os.path.basename(path)
                               + "-")
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def atomic_write_json(path: str, obj: Any, indent: int = 2) -> str:
    """JSON convenience over ``atomic_write``: readers either see the
    previous document or the complete new one, never a truncated parse."""
    return atomic_write(
        path, lambda f: json.dump(obj, f, indent=indent, sort_keys=True))
