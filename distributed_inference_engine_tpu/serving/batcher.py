"""Request batcher: coalesce per-(model, version), flush at size OR latency.

Capability heir of the reference's ``src/batcher.py:37-269``: requests are
grouped per ``model:version``; a batch flushes when it reaches
``max_batch_size`` (``src/batcher.py:140-147``) or when ``max_latency_ms``
elapses since the batch opened (``src/batcher.py:151-166``); each request gets
an ``asyncio.Future`` resolved from the batch result (``src/batcher.py:202-240``).

Concurrency invariants carried over from the reference (SURVEY.md §3.2):
batch state is mutated only under the lock, the backend callback runs
*outside* the lock, and futures are guarded with ``done()`` checks so a
result and a timeout can't double-resolve.

TPU-first addition: optional bucket padding. XLA compiles one program per
input shape (SURVEY.md §7 hard-part #1), so the batcher can pad every flushed
batch up to the next bucket size — the backend then sees only
``len(bucket_sizes)`` distinct batch shapes instead of an unbounded set.
Fixed reference bugs: no duplicate ``pending_batches`` stats key
(``src/batcher.py:263,268``), and exact result-count mismatches fan an error
to every future rather than hanging some of them.

Mixed-step budget (Sarathi): the continuous-engine path does NOT coalesce
here — admission throttling for ragged mixed batches lives in
``config.BatcherConfig.mixed_step_tokens``, handed down by the worker into
``serving.pump.EnginePump(mixed_step_tokens=...)`` which writes it into the
engine config; ``ContinuousEngine._step_mixed`` enforces it per dispatch.
This module's size/latency flush knobs only govern the static-``Engine``
backend path.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.tracing import LatencyStats, RequestTrace

logger = logging.getLogger(__name__)

# An inference backend: async (model, version, inputs) -> list of outputs,
# one per input (reference ``src/batcher.py:42`` contract).
BatchCallback = Callable[[str, str, List[Any]], Awaitable[List[Any]]]

PAD_INPUT = {"__pad__": True}


@dataclass
class BatchedRequest:
    """Reference ``src/batcher.py:17-24``."""

    request_id: str
    inputs: Any
    future: "asyncio.Future[Any]"
    enqueued_at: float = field(default_factory=time.monotonic)
    trace: Optional[RequestTrace] = None


@dataclass
class Batch:
    """Reference ``src/batcher.py:27-35``."""

    model: str
    version: str
    requests: List[BatchedRequest] = field(default_factory=list)
    created_at: float = field(default_factory=time.monotonic)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.model, self.version)


class Batcher:
    def __init__(
        self,
        batch_callback: BatchCallback,
        max_batch_size: int = 8,
        max_latency_ms: float = 50.0,
        bucket_sizes: Optional[Sequence[int]] = None,
    ) -> None:
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_latency_ms < 0:
            raise ValueError("max_latency_ms must be >= 0")
        self.batch_callback = batch_callback
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        if bucket_sizes:
            bucket_sizes = sorted(set(bucket_sizes))
            if bucket_sizes[-1] < max_batch_size:
                raise ValueError("largest bucket must cover max_batch_size")
        self.bucket_sizes = list(bucket_sizes) if bucket_sizes else None

        self._pending: Dict[Tuple[str, str], Batch] = {}
        self._timers: Dict[Tuple[str, str], asyncio.Task] = {}
        self._inflight: set[asyncio.Task] = set()
        self._lock = asyncio.Lock()
        self._running = False
        # stats
        self._total_requests = 0
        self._total_batches = 0
        self._total_batched_requests = 0
        self._total_errors = 0
        self._batch_size_sum = 0
        self._queue_wait = LatencyStats()

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        self._running = True
        logger.info(
            "batcher started (max_batch=%d, max_latency=%.1fms)",
            self.max_batch_size,
            self.max_latency_ms,
        )

    async def stop(self) -> None:
        """Stop accepting requests and drain: pending batches are flushed and
        in-flight callbacks awaited (reference ``src/batcher.py:70-100``)."""
        self._running = False
        async with self._lock:
            keys = list(self._pending.keys())
        for key in keys:
            await self._flush(key, reason="drain")
        while self._inflight:
            tasks = list(self._inflight)
            await asyncio.gather(*tasks, return_exceptions=True)
            # gather on already-done tasks may not yield to the loop, so the
            # done-callbacks that discard them can starve — drop them here
            self._inflight.difference_update(t for t in tasks if t.done())

    # -------------------------------------------------------------- intake

    async def add_request(
        self,
        model: str,
        version: str,
        inputs: Any,
        request_id: Optional[str] = None,
        trace: Optional[RequestTrace] = None,
    ) -> "asyncio.Future[Any]":
        """Enqueue one request; returns a Future resolved with its output
        (reference ``src/batcher.py:102-149``)."""
        if not self._running:
            raise RuntimeError("batcher is not running")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        key = (model, version)
        full_batch: Optional[Batch] = None
        timer: Optional[asyncio.Task] = None
        async with self._lock:
            # id minted under the lock so concurrent adds can't collide
            self._total_requests += 1
            req = BatchedRequest(
                request_id=request_id or f"req-{self._total_requests}",
                inputs=inputs,
                future=fut,
                trace=trace,
            )
            if trace is not None:
                trace.mark("queued")
            batch = self._pending.get(key)
            if batch is None:
                batch = Batch(model=model, version=version)
                self._pending[key] = batch
                self._timers[key] = asyncio.ensure_future(self._latency_timer(key))
            batch.requests.append(req)
            if len(batch.requests) >= self.max_batch_size:
                # detach the full batch HERE, not after re-acquiring the lock —
                # a lock-waiting add could otherwise grow it past max_batch_size
                full_batch = self._pending.pop(key)
                timer = self._timers.pop(key, None)
        if full_batch is not None:
            if timer is not None and not timer.done():
                timer.cancel()
            self._dispatch(full_batch, reason="size")
        return fut

    # ------------------------------------------------------------- flushing

    async def _latency_timer(self, key: Tuple[str, str]) -> None:
        """Latency trigger (reference ``src/batcher.py:151-166``)."""
        try:
            await asyncio.sleep(self.max_latency_ms / 1000.0)
            await self._flush(key, reason="latency")
        except asyncio.CancelledError:
            pass

    async def _flush(self, key: Tuple[str, str], reason: str) -> None:
        """Detach the pending batch under the lock, dispatch outside it
        (timer and drain paths; the size path detaches in add_request)."""
        async with self._lock:
            batch = self._pending.pop(key, None)
            timer = self._timers.pop(key, None)
        if timer is not None and not timer.done():
            timer.cancel()
        if batch is None or not batch.requests:
            return
        self._dispatch(batch, reason)

    def _dispatch(self, batch: Batch, reason: str) -> None:
        self._total_batches += 1
        self._total_batched_requests += len(batch.requests)
        self._batch_size_sum += len(batch.requests)
        logger.debug(
            "flush %s:%s n=%d reason=%s", batch.model, batch.version,
            len(batch.requests), reason,
        )
        task = asyncio.ensure_future(self._process(batch))
        self._inflight.add(task)
        task.add_done_callback(self._inflight.discard)

    def _padded_size(self, n: int) -> int:
        if not self.bucket_sizes:
            return n
        for b in self.bucket_sizes:
            if n <= b:
                return b
        return n

    async def _process(self, batch: Batch) -> None:
        """Invoke the backend and fan results out to futures (reference
        ``src/batcher.py:202-240``)."""
        reqs = batch.requests
        inputs = [r.inputs for r in reqs]
        n_real = len(inputs)
        n_padded = self._padded_size(n_real)
        inputs = inputs + [PAD_INPUT] * (n_padded - n_real)
        t_dispatch = time.monotonic()
        for r in reqs:
            self._queue_wait.add(t_dispatch - r.enqueued_at)
            if r.trace is not None:
                r.trace.mark("batched")
        try:
            results = await self.batch_callback(batch.model, batch.version, inputs)
            if results is None or len(results) < n_real:
                raise RuntimeError(
                    f"backend returned {0 if results is None else len(results)} "
                    f"results for {n_real} requests"
                )
            for req, result in zip(reqs, results):
                if req.future.done():
                    continue
                if isinstance(result, BaseException):
                    # backend may fail a subset (e.g. one worker group of a
                    # split batch) without discarding the others' results
                    req.future.set_exception(result)
                else:
                    req.future.set_result(result)
        except Exception as exc:  # fan the error out to every waiter
            self._total_errors += 1
            logger.warning("batch %s:%s failed: %s", batch.model, batch.version, exc)
            for req in reqs:
                if not req.future.done():
                    req.future.set_exception(exc)

    # ---------------------------------------------------------------- stats

    def get_stats(self) -> Dict[str, Any]:
        """Schema-stable stats (the reference's version shipped a duplicate
        key and its demo read a key that didn't exist — SURVEY.md §5)."""
        return {
            "running": self._running,
            "total_requests": self._total_requests,
            "total_batches": self._total_batches,
            "total_batched_requests": self._total_batched_requests,
            "total_errors": self._total_errors,
            "avg_batch_size": (
                self._batch_size_sum / self._total_batches if self._total_batches else 0.0
            ),
            "pending_batches": len(self._pending),
            "pending_requests": sum(len(b.requests) for b in self._pending.values()),
            "inflight_batches": len(self._inflight),
            "max_batch_size": self.max_batch_size,
            "max_latency_ms": self.max_latency_ms,
            "queue_wait": self._queue_wait.snapshot(),
        }
