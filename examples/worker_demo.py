"""Interactive worker CLI — heir of the reference's
``examples/worker_demo.py`` (an interactive worker + registry REPL).

Starts one worker in-process, then reads commands:

    load <name> <architecture> [size]   e.g. load tiny llama llama-tiny
    unload <name>
    models
    generate <name> <max_new> <tok> [tok ...]
    metrics
    quit

Non-interactive: --script "load tiny llama llama-tiny; generate tiny 4 1 2 3"

    JAX_PLATFORMS=cpu python examples/worker_demo.py --script "..."
"""

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_inference_engine_tpu.utils.platform import (  # noqa: E402
    pin_platform_from_env,
)

pin_platform_from_env()

from distributed_inference_engine_tpu.cluster.worker import (  # noqa: E402
    WorkerClient, WorkerServer,
)
from distributed_inference_engine_tpu.config import (  # noqa: E402
    ModelConfig, ServerConfig,
)
from distributed_inference_engine_tpu.engine.types import (  # noqa: E402
    GenerationRequest,
)


async def handle(client: WorkerClient, line: str) -> bool:
    parts = line.split()
    if not parts:
        return True
    cmd, args = parts[0], parts[1:]
    try:
        if cmd in ("quit", "exit"):
            return False
        elif cmd == "load":
            name, arch = args[0], args[1]
            meta = {"size": args[2]} if len(args) > 2 else {}
            cfg = ModelConfig(name=name, architecture=arch, max_seq_len=128,
                              dtype="float32", metadata=meta)
            print(await client.call("load_model", config=cfg.to_dict(),
                                    timeout=600))
        elif cmd == "unload":
            print(await client.call("unload_model", model=args[0]))
        elif cmd == "models":
            print(json.dumps(await client.call("list_models"), indent=2))
        elif cmd == "generate":
            name, max_new = args[0], int(args[1])
            prompt = [int(t) for t in args[2:]] or [1, 2, 3]
            out = await client.generate(name, [GenerationRequest(
                prompt=prompt, max_new_tokens=max_new, temperature=0.0)],
                timeout=600)
            r = out[0]
            print(f"tokens={r.tokens} finish={r.finish_reason} "
                  f"ttft={r.ttft_s * 1e3:.1f}ms")
        elif cmd == "metrics":
            print(json.dumps(await client.call("metrics"), indent=2,
                             default=str))
        elif cmd == "ping":
            print(await client.ping())
        else:
            print(f"unknown command {cmd!r} "
                  "(load/unload/models/generate/metrics/ping/quit)")
    except Exception as e:
        print(f"error: {type(e).__name__}: {e}")
    return True


async def amain(script: str) -> None:
    from _repl import run_repl

    w = WorkerServer(ServerConfig(worker_id="demo-worker", host="127.0.0.1",
                                  port=0))
    host, port = await w.start()
    print(f"worker on {host}:{port}")
    client = WorkerClient(host, port, timeout=600.0)
    try:
        await run_repl(lambda line: handle(client, line), "worker> ", script)
    finally:
        await client.close()
        await w.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--script", default="", help="semicolon-separated commands")
    args = ap.parse_args()
    asyncio.run(amain(args.script))


if __name__ == "__main__":
    main()
