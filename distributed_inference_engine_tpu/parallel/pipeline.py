"""Pipeline parallelism: GPipe-style microbatched stages over the ``pp``
mesh axis.

The last parallelism strategy SURVEY.md §2.3 reserves ("stage-sharded mesh
axis + microbatched decode"): the stacked ``[n_layers, ...]`` parameter
layout (models/base.py) splits naturally — stage ``s`` of ``S`` holds layers
``[s·L/S, (s+1)·L/S)`` as its local shard of every block tensor, placed with
``P("pp", ...)`` on the leading axis.

TPU-native execution model: one ``shard_map`` over the ``pp`` axis runs the
classic pipeline schedule as an SPMD program —

- each tick, every stage applies its local layer stack (``lax.scan``) to the
  activation it currently holds, then the activations rotate one stage
  forward with ``lax.ppermute`` over ICI;
- stage 0 injects microbatch ``t`` at tick ``t``; the last stage holds the
  finished microbatch ``t`` at tick ``t + S - 1``; a run of
  ``n_micro + S - 1`` ticks drains the pipeline (the S-1 bubble ticks are
  the standard GPipe cost, amortized by more microbatches);
- per-microbatch ``seq_lens`` travel WITH the activations through the
  rotation (each stage is processing a different microbatch at any tick, so
  the attention mask data must ride the pipe, not be indexed by tick);
- embedding runs before the pipe and the LM head after it (both replicated
  over ``pp``); the batch dim shards over ``dp`` as usual, so dp×pp compose.

Everything is differentiable (``ppermute`` has a transpose rule), so the
same schedule backs the pipeline training step.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map          # jax >= 0.7 public API
except ImportError:                                   # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; pass whichever this jax spells
import inspect as _inspect

_CHECK_KW = ("check_vma" if "check_vma"
             in _inspect.signature(_shard_map).parameters else "check_rep")

from ..models.base import (
    ModelSpec,
    Params,
    embed,
    init_params,
    next_token_xent,
    transformer_block,
    unembed,
)
from ..ops.attention import causal_attention


def pp_param_pspecs(spec: ModelSpec) -> Any:
    """PartitionSpec tree for pipeline placement: every block tensor's
    leading (layer) axis shards over ``pp``; embeddings, final norm, and LM
    head are replicated (they run outside the pipe)."""
    from .sharding import param_pspecs

    base = dict(param_pspecs(spec))
    # replace each block pspec's leading (layer) axis with pp; trailing tp
    # dims from param_pspecs compose untouched
    base["blocks"] = {k: P("pp", *tuple(v)[1:])
                      for k, v in base["blocks"].items()}
    return base


def _stage_body(spec: ModelSpec, blocks: Params, x: jnp.ndarray,
                seq_lens: jnp.ndarray) -> jnp.ndarray:
    """Apply this stage's local layer stack to activations ``x``
    ([mb, T, D]) — ``models.base.transformer_block`` with the dense causal
    attention, KV discarded (training/scoring path)."""
    b, t, _ = x.shape
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    def attn(q, k, v):
        return causal_attention(q, k, v, seq_lens,
                                window=spec.sliding_window)

    def body(x, blk):
        x, _, _, _ = transformer_block(spec, blk, x, positions, attn)
        return x, None

    x, _ = lax.scan(body, x, blocks)
    return x


def pipeline_hidden(
    spec: ModelSpec,
    params: Params,
    tokens: jnp.ndarray,     # [B, T] (B = n_micro * microbatch)
    seq_lens: jnp.ndarray,   # [B]
    mesh: Mesh,
    n_micro: int,
) -> jnp.ndarray:
    """Run the layer stack as a pp-staged pipeline; returns final hidden
    states [B, T, D] (pre final-norm), numerically identical to the dense
    forward."""
    n_stages = mesh.shape["pp"]
    b, t = tokens.shape
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by n_micro {n_micro}")
    if spec.n_layers % n_stages:
        raise ValueError(
            f"n_layers {spec.n_layers} not divisible by pp stages "
            f"{n_stages} — each stage needs an equal slice of the layer "
            f"stack")
    if spec.n_experts:
        # the stage body would silently use the drop-free inference MoE
        # path and discard the router load-balance aux loss — training an
        # MoE through the pipe without the penalty invites router collapse,
        # so refuse until aux plumbing rides the schedule
        raise ValueError(
            "pipeline parallelism does not yet support MoE specs "
            "(router aux loss is not plumbed through the pipe; use "
            "parallel.train.make_train_step with the ep axis)")
    mb = b // n_micro

    x = embed(spec, params, tokens,
              jnp.broadcast_to(jnp.arange(t)[None, :], (b, t)))
    xs = x.reshape(n_micro, mb, t, -1)
    lens = seq_lens.reshape(n_micro, mb)

    blocks_spec = jax.tree.map(lambda _: P("pp"), params["blocks"])

    @functools.partial(
        _shard_map, mesh=mesh,
        in_specs=(blocks_spec, P(None, "dp"), P(None, "dp")),
        out_specs=P(None, "dp"),
        **{_CHECK_KW: False},
    )
    def run(blocks, xs, lens):
        stage = lax.axis_index("pp")
        steps = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state = jnp.zeros_like(xs[0])
        state_lens = jnp.zeros_like(lens[0])
        out = jnp.zeros_like(xs)

        def tick(carry, ti):
            state, state_lens, out = carry
            # stage 0 ingests microbatch ti (a clipped gather; ticks past
            # the last microbatch feed the bubble and are never read back)
            inj = lax.dynamic_index_in_dim(
                xs, jnp.clip(ti, 0, n_micro - 1), axis=0, keepdims=False)
            inj_lens = lax.dynamic_index_in_dim(
                lens, jnp.clip(ti, 0, n_micro - 1), axis=0, keepdims=False)
            state = jnp.where(stage == 0, inj, state)
            state_lens = jnp.where(stage == 0, inj_lens, state_lens)

            state = _stage_body(spec, blocks, state, state_lens)

            # last stage completed microbatch ti-(S-1); write it home
            widx = ti - (n_stages - 1)
            write = (stage == n_stages - 1) & (widx >= 0)
            out = lax.dynamic_update_index_in_dim(
                out,
                jnp.where(write,
                          state,
                          lax.dynamic_index_in_dim(
                              out, jnp.clip(widx, 0, n_micro - 1),
                              axis=0, keepdims=False)),
                jnp.clip(widx, 0, n_micro - 1), axis=0)

            # rotate activations one stage forward over ICI
            state = lax.ppermute(state, "pp", perm)
            state_lens = lax.ppermute(state_lens, "pp", perm)
            return (state, state_lens, out), None

        (state, state_lens, out), _ = lax.scan(
            tick, (state, state_lens, out), jnp.arange(steps))
        # results live on the last stage only; broadcast over pp so the
        # out_spec (replicated over pp) is truthful
        out = lax.psum(jnp.where(stage == n_stages - 1, out,
                                 jnp.zeros_like(out)), "pp")
        return out

    hidden = run(params["blocks"], xs, lens)
    return hidden.reshape(b, t, -1)


def pipeline_forward_train(
    spec: ModelSpec,
    params: Params,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray,
    mesh: Mesh,
    n_micro: int,
) -> jnp.ndarray:
    """Full-sequence logits [B, T, V] fp32 through the pipeline."""
    hidden = pipeline_hidden(spec, params, tokens, seq_lens, mesh, n_micro)
    return unembed(spec, params, hidden)


def pipeline_lm_loss(
    spec: ModelSpec,
    params: Params,
    tokens: jnp.ndarray,
    seq_lens: jnp.ndarray,
    mesh: Mesh,
    n_micro: int,
) -> jnp.ndarray:
    logits = pipeline_forward_train(spec, params, tokens, seq_lens, mesh,
                                    n_micro)
    return next_token_xent(logits, tokens, seq_lens)


def make_pp_train_step(
    spec: ModelSpec,
    mesh: Mesh,
    n_micro: int,
    learning_rate: float = 1e-3,
):
    """(init_state, train_step) with parameters stage-sharded over ``pp``
    and the batch over ``dp`` — the pipeline twin of
    ``parallel.train.make_train_step``.

    ``ppermute`` differentiates, so one ``value_and_grad`` over the
    pipelined loss gives the full backward schedule; optimizer state
    inherits the parameters' stage sharding (adamw moments live with their
    stage's weights)."""
    import optax

    tx = optax.adamw(learning_rate)
    pspecs = pp_param_pspecs(spec)
    param_shardings = jax.tree.map(
        lambda p: NamedSharding(mesh, p), pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_sharding = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())

    def init_state(key: jax.Array):
        params = init_params(spec, key)
        params = jax.tree.map(jax.device_put, params, param_shardings)
        opt_state = tx.init(params)
        return params, opt_state

    def step(state, tokens, seq_lens):
        params, opt_state = state
        loss, grads = jax.value_and_grad(
            lambda p: pipeline_lm_loss(spec, p, tokens, seq_lens, mesh,
                                       n_micro)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    train_step = jax.jit(
        step,
        in_shardings=(None, batch_sharding, batch_sharding),
        out_shardings=(None, repl),
        donate_argnums=(0,),
    )
    return init_state, train_step
