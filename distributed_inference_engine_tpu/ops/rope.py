"""Rotary position embeddings (RoPE), as used by the Llama family.

Position indices arrive as an explicit array (shape [B] or [B, T]) rather than
being derived from the sequence axis: under continuous batching every slot sits
at a different absolute position, and under sequence parallelism each shard
owns a different slice of positions — both just change the index array, not
the op. Everything here is static-shape and jit/scan-safe.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_freqs(head_dim: int, theta: float = 10000.0) -> jnp.ndarray:
    """Inverse frequencies for each head-dim pair: [head_dim // 2], fp32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta ** exponents)


def apply_rope(
    x: jnp.ndarray,          # [B, T, H, Dh]
    positions: jnp.ndarray,  # [B, T] absolute token positions
    theta: float = 10000.0,
) -> jnp.ndarray:
    """Rotate query/key vectors by their absolute position.

    Uses the split-halves convention (first half / second half pairing), the
    same layout HF Llama checkpoints are trained with, so loaded weights work
    unmodified.
    """
    head_dim = x.shape[-1]
    freqs = rope_freqs(head_dim, theta)                      # [Dh/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, Dh/2]
    cos = jnp.cos(angles)[:, :, None, :]                     # [B, T, 1, Dh/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)
