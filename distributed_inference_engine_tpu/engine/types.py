"""jax-free generation request/result types.

Split out of ``engine.engine`` so control-plane hosts (coordinator, registry,
router — no TPU, no jax import cost) can marshal requests without pulling in
the device stack. ``engine.engine`` re-exports both names.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


# machine-readable error class for load shedding: the engine's waiting
# queue is full (hard backpressure at submit) or the request sat in queue
# past its deadline (shed at admission). The coordinator reacts by trying
# ONE alternate replica, then surfaces the typed error to the client —
# an overloaded worker is NOT an unhealthy worker (the reference's only
# notions of bounding: ``/root/reference/src/batcher.py:140-147`` batch
# cap, ``src/load_balancer.py:150-153`` healthy-set filter).
OVERLOADED = "overloaded"


class EngineOverloadedError(RuntimeError):
    """The engine shed this request instead of queueing it unboundedly."""

    rpc_error_kind = OVERLOADED

    def __init__(self, msg: str, reason: str = "queue_full",
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        # "queue_full" | "deadline" | "draining" | "fleet_overloaded"
        self.reason = reason
        # backoff hint for the caller: set by fleet-level admission
        # shedding (the coordinator at max fleet and still SLO-violating);
        # None for engine-local sheds, where "one alternate then error"
        # already encodes the policy
        self.retry_after_s = retry_after_s
        # rides the RPC error envelope as ``error_detail`` so remote
        # callers get the reason structurally, not by sniffing text
        self.rpc_error_detail = reason


# machine-readable error class for a request that aged out of its OWN
# per-request budget (``GenerationRequest.deadline_s``). Distinct from an
# OVERLOADED shed: a shed is the worker's problem (retriable elsewhere),
# a deadline expiry is the request's problem (never retried — the client
# already stopped caring, and replaying it only wastes another worker's
# engine steps).
DEADLINE = "deadline"


class DeadlineExceededError(RuntimeError):
    """The request's per-request deadline expired before completion."""

    rpc_error_kind = DEADLINE

    def __init__(self, msg: str, request_id: str = "") -> None:
        super().__init__(msg)
        self.request_id = request_id
        self.rpc_error_detail = request_id


@dataclass
class GenerationRequest:
    """One generation job (token-id space; tokenization is a host concern)."""

    prompt: List[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0                # keep tokens with p >= min_p * p_max
    request_id: str = ""
    eos_id: int = -1                  # -1: never stops early
    # additional stop conditions, checked host-side (eos_id stays the fast
    # device-side exit): any single id in stop_ids, or any exact token
    # subsequence in stop_sequences, ends generation. The matched stop
    # token/sequence is INCLUDED in the output (same contract as eos_id).
    stop_ids: List[int] = field(default_factory=list)
    stop_sequences: List[List[int]] = field(default_factory=list)
    # remaining per-request time budget in seconds, measured from engine
    # submit. None = no deadline. The coordinator decrements it by queue/
    # transit time before each dispatch hop, so the value a worker sees is
    # the budget it actually has left; engines shed the request unstarted
    # (finish_reason="deadline", zero decode steps) once it ages out.
    deadline_s: Optional[float] = None


def find_stop_cut(tokens: List[int], req: "GenerationRequest",
                  start: int = 0) -> int:
    """Earliest cut index (exclusive, stop INCLUDED) of any stop condition
    — ``eos_id``, ``stop_ids``, or ``stop_sequences`` — or -1 if none.

    ``start`` is a scan hint: the index of the first token not yet checked.
    The scan rewinds by the longest stop sequence minus one so a match
    spanning the boundary is still found — callers tracking a per-slot
    checked offset get O(total) stop detection instead of rescanning from
    zero after every decode chunk."""
    stops = set(req.stop_ids or ())
    if req.eos_id >= 0:
        stops.add(req.eos_id)
    seqs = [list(s) for s in (req.stop_sequences or ()) if s]
    if not stops and not seqs:
        return -1
    max_len = max((len(s) for s in seqs), default=1)
    begin = max(0, start - (max_len - 1))
    cut = -1
    if stops:
        for i in range(begin, len(tokens)):
            if tokens[i] in stops:
                cut = i + 1
                break
    for seq in seqs:
        n = len(seq)
        for i in range(begin, len(tokens) - n + 1):
            if tokens[i: i + n] == seq:
                end = i + n
                if cut < 0 or end < cut:
                    cut = end
                break
    return cut


def scan_host_stops(out_tokens: List[List[int]], requests, act_host,
                    scanned: List[int]) -> List[int]:
    """Per-chunk host-side stop scan shared by the static and speculative
    decode loops (ADVICE r1 early exit): for each still-active request with
    stop_ids/stop_sequences, check only its newly appended tokens; matched
    rows are cleared in ``act_host`` (the loop condition) and returned so
    the caller can batch-clear the device flags. ``scanned`` is the
    per-request resume offset, advanced here."""
    stopped: List[int] = []
    for i, r in enumerate(requests):
        if act_host[i] and (r.stop_ids or r.stop_sequences):
            if find_stop_cut(out_tokens[i], r, start=scanned[i]) >= 0:
                stopped.append(i)
                act_host[i] = False
        scanned[i] = len(out_tokens[i])
    return stopped


def trim_at_stops(tokens: List[int], req: "GenerationRequest"
                  ) -> Tuple[List[int], bool]:
    """Cap at ``max_new_tokens`` and cut at the EARLIEST stop condition,
    keeping the matched stop itself. Returns (trimmed tokens, stopped?).

    One shared trimmer so the static, continuous, speculative, and
    streaming paths cannot disagree about what the final output is."""
    toks = list(tokens[: req.max_new_tokens])
    cut = find_stop_cut(toks, req)
    if cut >= 0:
        return toks[:cut], True
    return toks, False


@dataclass
class GenerationResult:
    request_id: str
    tokens: List[int]                 # generated token ids (no prompt)
    finish_reason: str                # "stop" | "length"
    prompt_tokens: int = 0
    # per generated token: log p(token | prefix) under the model's
    # UNTEMPERED distribution (what scoring APIs report), aligned with
    # ``tokens`` and trimmed identically
    logprobs: List[float] = field(default_factory=list)
    # time to first token. Static/speculative engines measure from the
    # generate dispatch (prefill + first sample); the continuous engine
    # measures from SUBMIT, so queue wait under load is included.
    ttft_s: float = 0.0
    decode_s: float = 0.0
    metadata: Dict[str, Any] = field(default_factory=dict)
