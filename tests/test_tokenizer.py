"""Tokenizer layer: byte fallback, BPE correctness, native C++ core vs the
pure-Python mirror (same ranked-merge algorithm, identical outputs)."""

import json

import pytest

from distributed_inference_engine_tpu.utils.tokenizer import (
    BPETokenizer,
    ByteTokenizer,
    _bytes_to_unicode,
    _py_bpe_encode,
    build_tokenizer,
)


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    s = "hello, TPU! ünïcödé"
    assert t.decode(t.encode(s)) == s
    ids = t.encode("ab", add_bos=True, add_eos=True)
    assert ids[0] == t.BOS and ids[-1] == t.EOS


def _toy_bpe(**kw):
    """Tiny hand-built vocab: bytes for 'abcd ' + merged units."""
    b2u = _bytes_to_unicode()
    base = [b2u[ord(c)] for c in "abcd "]
    vocab = {u: i for i, u in enumerate(base)}
    a, b, c, d = (b2u[ord(x)] for x in "abcd")
    for unit in (a + b, c + d, a + b + c + d):
        vocab[unit] = len(vocab)
    merges = [(a, b), (c, d), (a + b, c + d)]
    return BPETokenizer(vocab, merges, **kw)


def test_bpe_merges_applied_in_rank_order():
    t = _toy_bpe(use_native=False)
    # "abcd" -> ab, cd -> abcd (one token)
    assert len(t.encode("abcd")) == 1
    assert t.encode("ab cd") != t.encode("abcd")
    assert t.decode(t.encode("abcd ab")) == "abcd ab"


def test_native_matches_python():
    t_native = _toy_bpe(use_native=True)
    t_py = _toy_bpe(use_native=False)
    if not t_native.native_enabled:
        pytest.skip("no native toolchain")
    for text in ["", "a", "abcd", "ab cd abcd", "dcba", "abcabcd abcd d",
                 "aaaa bbbb abab"]:
        assert t_native.encode(text) == t_py.encode(text), text


def test_native_matches_python_fuzz():
    import random

    t_native = _toy_bpe(use_native=True)
    t_py = _toy_bpe(use_native=False)
    if not t_native.native_enabled:
        pytest.skip("no native toolchain")
    rng = random.Random(0)
    for _ in range(50):
        s = "".join(rng.choice("abcd ") for _ in range(rng.randrange(1, 60)))
        assert t_native.encode(s) == t_py.encode(s), s


def test_bpe_from_pretrained_dir(tmp_path):
    b2u = _bytes_to_unicode()
    a, b = b2u[ord("a")], b2u[ord("b")]
    vocab = {a: 0, b: 1, a + b: 2}
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(f"#version\n{a} {b}\n")
    t = BPETokenizer.from_pretrained_dir(str(tmp_path))
    assert t.encode("ab") == [2]
    assert t.decode([2, 0]) == "aba"
    assert isinstance(build_tokenizer(str(tmp_path)), BPETokenizer)
    assert isinstance(build_tokenizer(""), ByteTokenizer)


def test_py_core_tie_break_is_leftmost():
    # two applications of the same rank: leftmost merges first
    ranks = {(0, 1): (0, 9)}
    assert _py_bpe_encode([0, 1, 0, 1], ranks) == [9, 9]


# ------------------------------------------------- tokenizer.json parsing

# Llama-3's split regex as serialized in its tokenizer.json: digits chunk
# in groups of AT MOST 3 (vs GPT-2's unbounded ` ?\p{N}+`)
_LLAMA3_SPLIT = (r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+"
                 r"|\p{N}{1,3}| ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+"
                 r"|\s+(?!\S)|\s+")


def _full_byte_vocab():
    """All 256 byte units (passes the byte-level coverage check)."""
    return {u: i for i, u in enumerate(_bytes_to_unicode().values())}


def _write_tokenizer_json(tmp_path, *, pre_tokenizer=None, added_tokens=(),
                          extra_vocab=(), merges=()):
    vocab = _full_byte_vocab()
    for unit in extra_vocab:
        vocab[unit] = len(vocab)
    d = {
        "model": {"type": "BPE", "vocab": vocab,
                  "merges": [list(m) for m in merges]},
        "added_tokens": list(added_tokens),
    }
    if pre_tokenizer is not None:
        d["pre_tokenizer"] = pre_tokenizer
    p = tmp_path / "tokenizer.json"
    p.write_text(json.dumps(d))
    return str(p)


def test_llama3_pre_tokenizer_digit_chunking(tmp_path):
    """A Llama-3-style pre_tokenizer (Sequence[Split(Regex), ByteLevel])
    must be parsed and USED: its 1-3 digit chunks forbid the 3+4 merge
    that GPT-2's unbounded number chunk would apply to "12345"."""
    b2u = _bytes_to_unicode()
    u3, u4 = b2u[ord("3")], b2u[ord("4")]
    pre = {"type": "Sequence", "pretokenizers": [
        {"type": "Split", "pattern": {"Regex": _LLAMA3_SPLIT},
         "behavior": "Isolated", "invert": False},
        {"type": "ByteLevel", "add_prefix_space": False, "use_regex": False},
    ]}
    path = _write_tokenizer_json(tmp_path, pre_tokenizer=pre,
                                 extra_vocab=[u3 + u4], merges=[(u3, u4)])
    t = BPETokenizer.from_tokenizer_json(path, use_native=False)
    gpt2 = BPETokenizer.from_tokenizer_json(path, use_native=False)
    gpt2._pretok_pattern = None          # what the hard-coded regex did
    assert t.decode(t.encode("12345")) == "12345"
    # GPT-2 chunking merges 3+4 across the 123|45 boundary; Llama-3 can't
    assert len(gpt2.encode("12345")) == 4
    assert len(t.encode("12345")) == 5
    # uppercase contraction: (?i:'s) matches "'S" under Llama-3 only
    assert t.decode(t.encode("IT'S")) == "IT'S"


def test_gpt2_pre_tokenizer_no_warning(tmp_path):
    import warnings as w

    pre = {"type": "ByteLevel", "add_prefix_space": False}
    path = _write_tokenizer_json(tmp_path, pre_tokenizer=pre)
    with w.catch_warnings():
        w.simplefilter("error")
        t = BPETokenizer.from_tokenizer_json(path, use_native=False)
    assert t._pretok_pattern is None


def test_unrecognized_pre_tokenizer_warns(tmp_path):
    path = _write_tokenizer_json(
        tmp_path, pre_tokenizer={"type": "Whitespace"})
    with pytest.warns(UserWarning, match="pre_tokenizer"):
        t = BPETokenizer.from_tokenizer_json(path, use_native=False)
    assert t._pretok_pattern is None     # falls back, loudly


def test_added_tokens_encode_atomically(tmp_path):
    """<|eot_id|> must encode to ITS id (chat-template prompts previously
    byte-split specials, so engine eos/stop matching never fired)."""
    eot = {"content": "<|eot_id|>", "id": 1000, "special": True}
    hdr = {"content": "<|start_header_id|>", "id": 1001, "special": True}
    path = _write_tokenizer_json(tmp_path, added_tokens=[eot, hdr])
    t = BPETokenizer.from_tokenizer_json(path, use_native=False)
    ids = t.encode("hi<|eot_id|>")
    assert ids[-1] == 1000 and 1000 not in ids[:-1]
    assert t.encode("<|start_header_id|>user<|eot_id|>")[0] == 1001
    assert t.decode(t.encode("a<|eot_id|>b")) == "a<|eot_id|>b"
    # plain text is untouched by the special pre-split
    assert t.encode("no specials here") == t._encode_ordinary(
        "no specials here")


def test_added_token_id_collision(tmp_path):
    """An added token whose content already sits in model.vocab under a
    DIFFERENT id: the added id must win for encoding (HF semantics) and
    both ids must decode (the old ``setdefault`` silently dropped it)."""
    b2u = _bytes_to_unicode()
    a_unit = b2u[ord("a")]
    model_id = _full_byte_vocab()[a_unit]
    path = _write_tokenizer_json(
        tmp_path, added_tokens=[{"content": "a", "id": 777}])
    t = BPETokenizer.from_tokenizer_json(path, use_native=False)
    assert t.encode("bab")[1] == 777
    assert t.decode([777]) == "a"
    assert t.decode([model_id]) == "a"   # merge-table id still decodes
