"""Mixture-of-experts tests: routing math, dense parity, expert-parallel
sharding on the virtual 8-device mesh, engine decode, HF Mixtral loading.

No reference counterpart (SURVEY.md §2.3 lists expert parallelism as a
reserved axis); the parity oracle is the framework's own dense MLP.
"""

import dataclasses
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import MeshConfig
from distributed_inference_engine_tpu.models.base import (
    ModelSpec,
    causal_lm_loss,
    forward_train,
    forward_train_aux,
    init_params,
)
from distributed_inference_engine_tpu.models.llama import llama_spec, mixtral_spec
from distributed_inference_engine_tpu.ops.moe import moe_capacity, moe_mlp
from distributed_inference_engine_tpu.parallel.mesh import make_mesh
from distributed_inference_engine_tpu.parallel.sharding import (
    ModelShardings,
    shard_params,
)

MOE_SPEC = mixtral_spec(
    "mixtral-tiny", dtype="float32", max_seq_len=64,
    n_layers=2, d_model=64, n_heads=8, n_kv_heads=4, d_ff=96,
    vocab_size=128, n_experts=4, experts_per_token=2,
)


def _tokens(spec, b=2, t=16, seed=0):
    rs = np.random.RandomState(seed)
    toks = jnp.asarray(rs.randint(0, spec.vocab_size, size=(b, t)), jnp.int32)
    return toks, jnp.full((b,), t, dtype=jnp.int32)


def test_moe_capacity_static():
    assert moe_capacity(64, 4, 2, 1.0) == 32
    assert moe_capacity(64, 4, 2, 1.25) == 40
    assert moe_capacity(2, 8, 2, 1.0) == 2   # floor at k


def test_moe_top1_identical_experts_matches_dense():
    """With k=1 routing and every expert holding the dense weights, MoE must
    reproduce the dense SwiGLU MLP exactly (given enough capacity)."""
    dense = llama_spec("llama-tiny", dtype="float32",
                       d_model=32, d_ff=48, n_heads=4, n_kv_heads=2)
    moe = dense.validate().__class__(**{
        **dense.to_dict(), "n_experts": 4, "experts_per_token": 1,
        # every token routes to one expert: worst case all to the same one
        "capacity_factor": 4.0,
    }).validate()
    rs = np.random.RandomState(0)
    d, f, e = dense.d_model, dense.d_ff, moe.n_experts
    w_gate = jnp.asarray(rs.randn(d, f).astype(np.float32) * 0.1)
    w_up = jnp.asarray(rs.randn(d, f).astype(np.float32) * 0.1)
    w_down = jnp.asarray(rs.randn(f, d).astype(np.float32) * 0.1)
    x = jnp.asarray(rs.randn(2, 8, d).astype(np.float32))

    # dense oracle
    h = jax.nn.silu(x @ w_gate) * (x @ w_up)
    ref = h @ w_down

    blk = {
        "w_router": jnp.zeros((d, e), jnp.float32),   # uniform -> argmax = 0
        "w_gate": jnp.tile(w_gate[None], (e, 1, 1)),
        "w_up": jnp.tile(w_up[None], (e, 1, 1)),
        "w_down": jnp.tile(w_down[None], (e, 1, 1)),
    }
    got, aux = moe_mlp(moe, blk, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_forward_and_loss_finite():
    params = init_params(MOE_SPEC, jax.random.key(0))
    toks, lens = _tokens(MOE_SPEC)
    logits, aux = forward_train_aux(MOE_SPEC, params, toks, lens)
    assert logits.shape == (2, 16, MOE_SPEC.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # balanced-ish fresh router: aux should sit near its floor of 1.0
    assert 0.5 < float(aux) / MOE_SPEC.n_layers < 2.0
    loss = causal_lm_loss(MOE_SPEC, params, toks, lens)
    assert np.isfinite(float(loss))


def test_moe_capacity_overflow_drops_but_stays_finite():
    tight = mixtral_spec(
        "mixtral-tiny", dtype="float32", max_seq_len=64,
        n_layers=1, d_model=32, n_heads=4, n_kv_heads=2, d_ff=32,
        vocab_size=64, n_experts=4, experts_per_token=2,
        capacity_factor=0.25,
    )
    params = init_params(tight, jax.random.key(1))
    toks, lens = _tokens(tight, b=2, t=32, seed=3)
    # the TRAINING path keeps capacity dropping (a regularizer)
    logits, _aux = forward_train_aux(tight, params, toks, lens)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_exact_never_drops_under_skew():
    """Inference must not lose expert outputs to batch-composition luck:
    with every token routed to ONE expert (identical inputs) and capacity
    far below the batch, the capacity path zeroes overflow tokens while the
    exact path treats all tokens identically (review finding: capacity
    dropping corrupted served generations)."""
    spec = MOE_SPEC
    params = init_params(spec, jax.random.key(5))
    blk = jax.tree_util.tree_map(lambda a: a[0], params["blocks"])
    # 32 identical tokens -> identical routing -> one expert gets them all
    x = jnp.broadcast_to(jax.random.normal(jax.random.key(6), (1, 1, spec.d_model)),
                         (1, 32, spec.d_model)).astype(jnp.float32)

    out_exact, aux_e = moe_mlp(spec, blk, x, exact=True)
    out_cap, aux_c = moe_mlp(spec, blk, x, exact=False)
    out_exact, out_cap = np.asarray(out_exact), np.asarray(out_cap)

    # exact: every (identical) token gets the same, non-zero output
    assert np.abs(out_exact).max() > 0
    assert np.abs(out_exact[0] - out_exact[0, :1]).max() == 0.0
    # capacity path: C = ceil(32*2/4 * 1.25) = 20 slots < 32 tokens -> the
    # overflow tokens' rows are exactly zero (dropped)
    zero_rows = np.all(out_cap[0] == 0.0, axis=-1).sum()
    assert zero_rows > 0, "capacity path should drop under this skew"
    # aux loss identical across paths (same routing)
    np.testing.assert_allclose(float(aux_e), float(aux_c), rtol=1e-6)


def test_moe_decode_matches_prefill_logits():
    """Paged decode (exact MoE) must agree with the exact prefill forward:
    generate one token greedily from a prompt and check it equals the
    argmax of the prefill logits at the last position."""
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )
    from distributed_inference_engine_tpu.engine.types import GenerationRequest
    from distributed_inference_engine_tpu.models.base import forward_train

    # paged layout needs n_kv_heads*head_dim % 128 == 0
    spec = mixtral_spec(
        "mixtral-tiny", dtype="float32", max_seq_len=64,
        n_layers=2, d_model=128, n_heads=2, n_kv_heads=2, d_ff=96,
        vocab_size=128, n_experts=4, experts_per_token=2,
    )
    params = init_params(spec, jax.random.key(7))
    prompt = [3, 1, 4, 1, 5]
    toks = jnp.asarray([prompt], jnp.int32)
    lens = jnp.asarray([len(prompt)], jnp.int32)
    logits = forward_train(spec, params, toks, lens)
    expect_first = int(np.asarray(logits)[0, len(prompt) - 1].argmax())

    eng = ContinuousEngine(spec, params=params, config=EngineConfig(
        max_slots=2, max_seq_len=32, page_size=8, num_pages=16,
        attention_impl="xla", kv_dtype="float32", decode_steps_per_call=2,
    ))
    out = eng.generate([GenerationRequest(prompt=prompt, max_new_tokens=3,
                                          temperature=0.0)])
    assert out[0].tokens[0] == expect_first


def test_moe_router_gets_gradient():
    params = init_params(MOE_SPEC, jax.random.key(2))
    toks, lens = _tokens(MOE_SPEC, seed=1)
    grads = jax.grad(
        lambda p: causal_lm_loss(MOE_SPEC, p, toks, lens)
    )(params)
    g_router = np.asarray(grads["blocks"]["w_router"])
    g_expert = np.asarray(grads["blocks"]["w_up"])
    assert np.abs(g_router).max() > 0
    assert np.abs(g_expert).max() > 0


def test_moe_ep_sharded_matches_unsharded():
    """The expert-parallel guarantee: sharding experts over ep (and FFN dims
    over tp) must not change the math — GSPMD inserts the all-to-alls."""
    params = init_params(MOE_SPEC, jax.random.key(3))
    toks, lens = _tokens(MOE_SPEC, seed=2)
    ref = forward_train(MOE_SPEC, params, toks, lens)

    mesh = make_mesh(MeshConfig(dp=2, tp=2, ep=2))
    shardings = ModelShardings.build(MOE_SPEC, mesh)
    sharded = shard_params(params, shardings)
    with mesh:
        got = jax.jit(lambda p, t, s: forward_train(MOE_SPEC, p, t, s))(
            sharded, toks, lens
        )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-4, atol=5e-4)


def test_moe_engine_generates():
    from distributed_inference_engine_tpu.engine.engine import Engine
    from distributed_inference_engine_tpu.engine.types import GenerationRequest

    eng = Engine(MOE_SPEC)
    out = eng.generate([GenerationRequest(prompt=[3, 5, 7], max_new_tokens=6)])
    assert len(out) == 1
    assert len(out[0].tokens) == 6
    assert all(0 <= t < MOE_SPEC.vocab_size for t in out[0].tokens)


def test_moe_spec_validation():
    with pytest.raises(ValueError, match="experts_per_token"):
        ModelSpec(vocab_size=8, d_model=8, n_layers=1, n_heads=1,
                  n_kv_heads=1, d_ff=8, n_experts=2,
                  experts_per_token=3).validate()
    with pytest.raises(ValueError, match="biases"):
        ModelSpec(vocab_size=8, d_model=8, n_layers=1, n_heads=1,
                  n_kv_heads=1, d_ff=8, n_experts=2, experts_per_token=1,
                  use_bias=True).validate()


def test_mixtral_hf_checkpoint_loads(tmp_path: pathlib.Path):
    """Fabricate a tiny HF-Mixtral-named safetensors checkpoint and load it."""
    from safetensors.numpy import save_file

    from distributed_inference_engine_tpu.models.loader import (
        load_checkpoint,
        spec_from_hf_config,
    )

    spec = mixtral_spec(
        "mixtral-tiny", dtype="float32", n_layers=1, d_model=16,
        n_heads=2, n_kv_heads=2, d_ff=24, vocab_size=32,
        n_experts=2, experts_per_token=1, max_seq_len=64,
    )
    rs = np.random.RandomState(0)
    D, F, V, E = spec.d_model, spec.d_ff, spec.vocab_size, spec.n_experts
    Hq = spec.n_heads * spec.head_dim
    Hkv = spec.n_kv_heads * spec.head_dim
    raw = {
        "model.embed_tokens.weight": rs.randn(V, D).astype(np.float32),
        "model.norm.weight": np.ones(D, np.float32),
        "lm_head.weight": rs.randn(V, D).astype(np.float32),
        "model.layers.0.input_layernorm.weight": np.ones(D, np.float32),
        "model.layers.0.post_attention_layernorm.weight": np.ones(D, np.float32),
        "model.layers.0.self_attn.q_proj.weight": rs.randn(Hq, D).astype(np.float32),
        "model.layers.0.self_attn.k_proj.weight": rs.randn(Hkv, D).astype(np.float32),
        "model.layers.0.self_attn.v_proj.weight": rs.randn(Hkv, D).astype(np.float32),
        "model.layers.0.self_attn.o_proj.weight": rs.randn(D, Hq).astype(np.float32),
        "model.layers.0.block_sparse_moe.gate.weight": rs.randn(E, D).astype(np.float32),
    }
    for e in range(E):
        pre = f"model.layers.0.block_sparse_moe.experts.{e}."
        raw[pre + "w1.weight"] = rs.randn(F, D).astype(np.float32)
        raw[pre + "w2.weight"] = rs.randn(D, F).astype(np.float32)
        raw[pre + "w3.weight"] = rs.randn(F, D).astype(np.float32)
    save_file(raw, str(tmp_path / "model.safetensors"))
    (tmp_path / "config.json").write_text(json.dumps({
        "architectures": ["MixtralForCausalLM"], "model_type": "mixtral",
        "vocab_size": V, "hidden_size": D, "num_hidden_layers": 1,
        "num_attention_heads": spec.n_heads,
        "num_key_value_heads": spec.n_kv_heads, "intermediate_size": F,
        "num_local_experts": E, "num_experts_per_tok": 1,
        "max_position_embeddings": 64,
    }))

    hf_spec = spec_from_hf_config(str(tmp_path))
    assert hf_spec.n_experts == E and hf_spec.experts_per_token == 1
    hf_spec = dataclasses.replace(hf_spec, dtype="float32")
    params = load_checkpoint(str(tmp_path), hf_spec)
    assert params["blocks"]["w_gate"].shape == (1, E, D, F)
    assert params["blocks"]["w_router"].shape == (1, D, E)
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["w_down"][0, 1]),
        raw["model.layers.0.block_sparse_moe.experts.1.w2.weight"].T,
        rtol=1e-6,
    )
    # loaded tree must run
    toks, lens = _tokens(hf_spec, b=1, t=8)
    logits = forward_train(hf_spec, params, toks, lens)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_ep_sharded_engine_generate_matches_unsharded():
    """Expert-parallel SERVING: an Engine with experts sharded over ep
    (and FFN dims over tp) generates the same greedy tokens as the
    unsharded engine — GSPMD's all-to-alls must not change the math."""
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.engine import Engine
    from distributed_inference_engine_tpu.engine.types import GenerationRequest

    cfg = EngineConfig(max_slots=2, max_seq_len=64, prefill_buckets=[16],
                       kv_dtype="float32", decode_steps_per_call=4)
    base = Engine(MOE_SPEC, config=cfg, seed=0)

    mesh = make_mesh(MeshConfig(dp=1, sp=1, tp=2, ep=2), jax.devices()[:4])
    shardings = ModelShardings.build(MOE_SPEC, mesh)
    reqs = lambda: [GenerationRequest(prompt=[3, 1, 4, 1, 5],
                                      max_new_tokens=6, temperature=0.0,
                                      request_id="m0"),
                    GenerationRequest(prompt=[9, 2, 6],
                                      max_new_tokens=5, temperature=0.0,
                                      request_id="m1")]
    with mesh:
        ep = Engine(MOE_SPEC, params=base.params, config=cfg, seed=0,
                    shard_fn=shardings.shard_fn())
        out_ep = {r.request_id: r.tokens for r in ep.generate(reqs())}
    out_base = {r.request_id: r.tokens for r in base.generate(reqs())}
    assert out_ep == out_base
    # expert weights actually live sharded over ep
    w_up = ep.params["blocks"]["w_up"]
    shard = w_up.sharding.shard_shape(w_up.shape)
    assert shard[1] == MOE_SPEC.n_experts // 2
