"""Shared framed-RPC client plumbing.

One implementation of connect/reconnect/locking/call for every framed-RPC
peer (worker client, coordinator client) — the reference had no client class
at all, and two hand-rolled copies would drift (they briefly did: one copy
lost the malformed-response guard).
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from .framing import read_frame, write_frame


class RPCError(RuntimeError):
    """Peer-reported request failure (distinct from transport failure)."""


class FramedRPCClient:
    """Persistent framed-RPC connection: one in-flight call at a time,
    transparent reconnect after a drop, poisoned-connection teardown."""

    def __init__(self, host: str, port: int,
                 timeout: float = 30.0,
                 max_frame: int = 64 * 1024 * 1024) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._seq = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _ensure_connected(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            self._reader = self._writer = None

    async def call(self, method: str, *, timeout: Optional[float] = None,
                   **params: Any) -> Any:
        """Send one request frame, await one response frame.

        Raises ``RPCError`` when the peer reports failure; transport trouble
        (``OSError``/``asyncio.TimeoutError``/...) propagates for callers —
        router/LB — to turn into health signals.
        """
        self._seq += 1
        msg = {"method": method, "id": f"{id(self):x}-{self._seq}", **params}
        effective = timeout if timeout is not None else self.timeout
        async with self._lock:  # one in-flight call per connection
            # the timeout must bound the connect too — a blackholed host
            # otherwise hangs the OS TCP connect (~2 min) with the lock held
            await asyncio.wait_for(self._ensure_connected(), timeout=effective)
            assert self._reader is not None and self._writer is not None
            try:
                await write_frame(self._writer, msg)
                response = await read_frame(
                    self._reader, max_frame=self.max_frame, timeout=effective,
                )
            except Exception:
                await self.close()  # poisoned connection — drop it
                raise
        if not isinstance(response, dict):
            raise RPCError(f"malformed response: {response!r}")
        if not response.get("success"):
            raise RPCError(response.get("error", "unknown peer error"))
        return response.get("result")
