from .cache import ResponseCache, KVStore, create_kv_store, EvictionPolicy  # noqa: F401
from .batcher import Batcher, BatchedRequest, Batch  # noqa: F401
