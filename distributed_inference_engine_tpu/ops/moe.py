"""Mixture-of-experts MLP: top-k token-choice routing, capacity-bounded
dense dispatch, experts sharded over the ``ep`` mesh axis.

No reference counterpart exists (the reference has no model math at all —
SURVEY.md §2.3 lists expert parallelism as "mesh axis reserved"); this
realizes that reserved axis. The design is the TPU-classic GShard/Switch
shape rather than a scatter/gather kernel:

- **Routing** is a tiny fp32 matmul + ``lax.top_k``; top-k gate weights are
  renormalized (Mixtral convention).
- **Dispatch/combine are einsums against one-hot tensors** ``[n, E, C]``
  (n tokens, E experts, C capacity slots). That keeps every FLOP on the MXU
  with fully static shapes — no dynamic gather, nothing XLA can't tile.
- **Capacity** is static: ``C = ceil(n·k/E · capacity_factor)``. Tokens that
  overflow an expert's capacity are dropped from that expert (their one-hot
  slot index lands out of range, so the dispatch row is all-zero) and the
  residual connection carries them through — standard Switch behavior.
- **Expert parallelism**: expert weights carry a leading ``E`` axis sharded
  over ``ep`` (``parallel/sharding.py``); GSPMD turns the dispatch einsum
  into the all-to-all over ICI. Inside each expert the FFN dims still shard
  over ``tp``, so ep×tp compose.

Also returns the Switch-style load-balancing auxiliary loss (E · Σ_e f_e·P_e,
=1 at perfect balance) so the training step can regularize routing.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .quant import matmul_any


def moe_capacity(n_tokens: int, n_experts: int, experts_per_token: int,
                 capacity_factor: float) -> int:
    """Static per-expert capacity for a batch of ``n_tokens`` tokens."""
    c = math.ceil(n_tokens * experts_per_token / n_experts * capacity_factor)
    return max(int(c), experts_per_token)


def moe_mlp(
    spec,                       # ModelSpec (avoid circular import)
    blk: Dict[str, Any],        # one layer's params: w_router + expert FFN
    x: jnp.ndarray,             # [B, T, D]
    exact: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE feed-forward over a token batch.

    Returns (out [B, T, D], aux_loss scalar fp32).

    ``exact=False`` (training): capacity-bounded GShard dispatch — tokens
    that overflow an expert's capacity are dropped from it and ride the
    residual. Dropping is a *training regularizer*; served generations must
    never lose expert outputs to batch-composition luck.

    ``exact=True`` (inference): every expert runs over every token and the
    routed combine keeps only each token's top-k — no capacity, no drops,
    bit-exact routing semantics. Costs E/K× the expert FLOPs, the right
    trade for decode (tiny n, memory-bound: the expert weights dominate HBM
    traffic either way) and for correctness-first prefill.
    """
    b, t, d = x.shape
    E, K = spec.n_experts, spec.experts_per_token
    n = b * t
    C = moe_capacity(n, E, K, spec.capacity_factor)
    xf = x.reshape(n, d)

    # --- route (fp32: tiny, and router logits are precision-sensitive)
    logits = jnp.einsum(
        "nd,de->ne", xf.astype(jnp.float32), blk["w_router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)                    # [n, E]
    gate, idx = lax.top_k(probs, K)                            # [n, K]
    gate = gate / jnp.maximum(gate.sum(axis=-1, keepdims=True), 1e-9)

    # --- Switch load-balance loss (identical for both paths)
    assign = jax.nn.one_hot(idx, E, dtype=jnp.float32)         # [n, K, E]
    frac = assign.sum(axis=(0, 1)) / float(n * K)              # [E], sums to 1
    mean_prob = probs.mean(axis=0)                             # [E]
    aux = jnp.float32(E) * jnp.sum(frac * mean_prob)

    if exact:
        # dense-all-experts: h_e(x) for every (expert, token) pair, then a
        # [n, E] combine keeps each token's top-k gates. Static shapes, all
        # MXU; no dispatch tensor, no drops. matmul_any: expert weights may
        # be int8-quantized for serving (ops/quant.py).
        if spec.mlp == "swiglu":
            g = matmul_any("nd,edf->enf", xf, blk["w_gate"])
            u = matmul_any("nd,edf->enf", xf, blk["w_up"])
            h = jax.nn.silu(g.astype(jnp.float32)).astype(x.dtype) * u
        else:
            u = matmul_any("nd,edf->enf", xf, blk["w_up"])
            h = jax.nn.gelu(u.astype(jnp.float32), approximate=True
                            ).astype(x.dtype)
        out_e = matmul_any("enf,efd->end", h, blk["w_down"])   # [E, n, D]
        weights = (assign * gate[..., None]).sum(axis=1)       # [n, E]
        out = jnp.einsum("ne,end->nd", weights,
                         out_e.astype(jnp.float32)).astype(x.dtype)
        return out.reshape(b, t, d), aux

    # --- capacity assignment. GShard priority order: all tokens' choice-0
    # first, then choice-1, ... so a token's primary expert wins slots over
    # another token's backup.
    flat = assign.transpose(1, 0, 2).reshape(K * n, E)         # choice-major
    pos = jnp.cumsum(flat, axis=0) - flat                      # slots used before
    pos = pos.reshape(K, n, E).transpose(1, 0, 2)              # [n, K, E]
    slot = jnp.sum(pos * assign, axis=-1).astype(jnp.int32)    # [n, K]
    slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32)       # [n, K, C]; >=C -> 0
    dispatch = jnp.einsum("nke,nkc->nec", assign, slot_oh)     # [n, E, C] 0/1
    combine = jnp.einsum("nke,nkc->nec", assign * gate[..., None], slot_oh)

    # --- dispatch -> expert FFN -> combine (all MXU einsums)
    expert_in = jnp.einsum(
        "nec,nd->ecd", dispatch, xf.astype(jnp.float32)
    ).astype(x.dtype)                                          # [E, C, D]
    if spec.mlp == "swiglu":
        g = jnp.einsum("ecd,edf->ecf", expert_in, blk["w_gate"])
        u = jnp.einsum("ecd,edf->ecf", expert_in, blk["w_up"])
        h = jax.nn.silu(g.astype(jnp.float32)).astype(x.dtype) * u
    else:
        u = jnp.einsum("ecd,edf->ecf", expert_in, blk["w_up"])
        h = jax.nn.gelu(u.astype(jnp.float32), approximate=True).astype(x.dtype)
    expert_out = jnp.einsum("ecf,efd->ecd", h, blk["w_down"])  # [E, C, D]
    out = jnp.einsum(
        "nec,ecd->nd", combine, expert_out.astype(jnp.float32)
    ).astype(x.dtype)
    return out.reshape(b, t, d), aux


def init_moe_blocks(spec, keys, norm_init) -> Dict[str, jnp.ndarray]:
    """Expert-FFN + router params for the stacked block tree ([L, E, ...])."""
    L, D, F, E = spec.n_layers, spec.d_model, spec.d_ff, spec.n_experts
    out_std = 0.02 / math.sqrt(2.0 * L)
    blocks: Dict[str, jnp.ndarray] = {
        "w_router": norm_init((L, D, E), next(keys)),
        "w_up": norm_init((L, E, D, F), next(keys)),
        "w_down": norm_init((L, E, F, D), next(keys), out_std),
    }
    if spec.mlp == "swiglu":
        blocks["w_gate"] = norm_init((L, E, D, F), next(keys))
    return blocks
