"""Paged HBM KV cache: fixed-size page pool + per-slot page tables.

The full realisation of BASELINE.json's north star for the reference's
``src/kvstore.py`` ("repurposed as an HBM-resident paged KV cache with LRU
eviction"): instead of one contiguous ``max_seq_len`` row per slot
(``SlotKVCache``), attention state lives in a shared pool of
``page_size``-token pages. Short sequences hold few pages, long ones many;
freeing a sequence returns its pages to the pool immediately (the recycling
that LRU-evicting whole rows only approximates).

Split of responsibilities:

- **Host (this class):** page accounting — free list, per-slot page lists,
  capacity reservations. Pure Python, mirrors the reference's free-list slot
  discipline (``src/kvstore.py:82-102``'s eviction loop becomes page
  recycling).
- **Device:** ``k_pages``/``v_pages`` ``[L, num_pages, page_size, Hkv*Dh]``
  and an int32 ``page_table`` ``[max_slots, max_pages_per_seq]`` that jitted
  decode indexes through (``ops/paged_attention.py``). The table is rebuilt
  on device only when host accounting changes (admission / page growth), so
  steady-state decode does zero host→device traffic for metadata.

Chunked-decode contract: callers must ``reserve(slot, n_tokens)`` the whole
chunk before launching it — the table is static while the chunk runs, so page
boundaries crossed mid-chunk already have physical pages behind them.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..models.base import ModelSpec


class OutOfPagesError(RuntimeError):
    """Pool exhausted — the scheduler must queue or preempt."""


class PagedKVCache:
    """Host-side page allocator + device-side page pool for one model."""

    def __init__(
        self,
        spec: ModelSpec,
        max_slots: int,
        page_size: int = 128,
        num_pages: int = 512,
        max_seq_len: Optional[int] = None,
        dtype: Optional[str] = None,
    ) -> None:
        fused = spec.n_kv_heads * spec.head_dim
        if fused % 128:
            raise ValueError(
                f"n_kv_heads*head_dim = {fused} must be a multiple of 128 "
                "for the paged layout (TPU lane alignment)"
            )
        self.spec = spec
        self.max_slots = max_slots
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_seq_len = max_seq_len or spec.max_seq_len
        self.max_pages_per_seq = -(-self.max_seq_len // page_size)
        self.dtype = jnp.dtype(dtype) if dtype else spec.jnp_dtype

        shape = (spec.n_layers, num_pages, page_size, fused)
        self.k_pages = jnp.zeros(shape, dtype=self.dtype)
        self.v_pages = jnp.zeros(shape, dtype=self.dtype)

        self._free: List[int] = list(range(num_pages))
        self._slot_pages: Dict[int, List[int]] = {}   # slot -> physical pages
        self._slot_len: Dict[int, int] = {}           # slot -> reserved tokens
        self._free_slots: List[int] = list(range(max_slots))
        self._table = np.zeros((max_slots, self.max_pages_per_seq), dtype=np.int32)
        self._table_dirty = True
        self._table_dev: Optional[jnp.ndarray] = None
        self._peak_pages_used = 0

    # ------------------------------------------------------------ slots

    def alloc_slot(self, n_tokens: int) -> Optional[int]:
        """Claim a slot with capacity for ``n_tokens``; None if no slot or
        not enough pages (caller queues the request)."""
        need = self._pages_for(n_tokens)
        if not self._free_slots or len(self._free) < need:
            return None
        slot = self._free_slots.pop(0)
        pages = [self._free.pop(0) for _ in range(need)]
        self._slot_pages[slot] = pages
        self._slot_len[slot] = n_tokens
        self._table[slot, : len(pages)] = pages
        self._table[slot, len(pages):] = 0
        self._table_dirty = True
        used = self.num_pages - len(self._free)
        self._peak_pages_used = max(self._peak_pages_used, used)
        return slot

    def reserve(self, slot: int, n_tokens: int) -> int:
        """Grow the slot by up to ``n_tokens`` more tokens of capacity.

        Returns the number of tokens actually granted — less than
        ``n_tokens`` when ``max_seq_len`` truncates the request, ``0`` when
        the page pool can't cover it. Callers running a decode chunk must
        bound the chunk's steps by the grant (SURVEY.md §7 hard-part #2:
        positions past the grant would index past the page table's width)."""
        if slot not in self._slot_pages:
            raise KeyError(f"slot {slot} not live")
        total = min(self._slot_len[slot] + n_tokens, self.max_seq_len)
        granted = total - self._slot_len[slot]
        if granted <= 0:
            return 0
        need = self._pages_for(total) - len(self._slot_pages[slot])
        if need <= 0:
            self._slot_len[slot] = total
            return granted
        if len(self._free) < need:
            return 0
        pages = [self._free.pop(0) for _ in range(need)]
        cur = self._slot_pages[slot]
        self._table[slot, len(cur): len(cur) + len(pages)] = pages
        cur.extend(pages)
        self._slot_len[slot] = total
        self._table_dirty = True
        used = self.num_pages - len(self._free)
        self._peak_pages_used = max(self._peak_pages_used, used)
        return granted

    def ensure_capacity(self, slot: int, total_tokens: int) -> int:
        """Best-effort growth toward ``total_tokens`` of total capacity.

        Unlike ``reserve`` (all-or-nothing increments), this takes as many
        pages as the pool can spare and returns the slot's resulting token
        capacity (clamped to ``max_seq_len``) — the continuous engine bounds
        its decode chunk by this, so pool pressure shortens chunks instead
        of failing them."""
        if slot not in self._slot_pages:
            raise KeyError(f"slot {slot} not live")
        target = min(total_tokens, self.max_seq_len)
        pages = self._slot_pages[slot]
        need = self._pages_for(target) - len(pages)
        take = min(max(need, 0), len(self._free))
        if take > 0:
            fresh = [self._free.pop(0) for _ in range(take)]
            self._table[slot, len(pages): len(pages) + take] = fresh
            pages.extend(fresh)
            self._table_dirty = True
            used = self.num_pages - len(self._free)
            self._peak_pages_used = max(self._peak_pages_used, used)
        cap = min(len(pages) * self.page_size, self.max_seq_len)
        self._slot_len[slot] = max(self._slot_len[slot], min(target, cap))
        return cap

    def free_slot(self, slot: int) -> None:
        pages = self._slot_pages.pop(slot, None)
        if pages is None:
            return
        self._free.extend(pages)
        del self._slot_len[slot]
        self._free_slots.append(slot)
        self._table[slot, :] = 0
        self._table_dirty = True

    def _pages_for(self, n_tokens: int) -> int:
        return max(1, -(-n_tokens // self.page_size))

    # ----------------------------------------------------------- device

    @property
    def page_table(self) -> jnp.ndarray:
        """Device copy of the table; re-uploaded only after host changes.
        ``jnp.array`` (not ``asarray``): on CPU backends asarray may
        zero-copy-alias the mutable host table, making the "snapshot" track
        live host mutations."""
        if self._table_dirty or self._table_dev is None:
            self._table_dev = jnp.array(self._table)
            self._table_dirty = False
        return self._table_dev

    def swap(self, new_k: jnp.ndarray, new_v: jnp.ndarray) -> None:
        """Adopt page pools returned by a jitted (donating) decode step."""
        self.k_pages, self.v_pages = new_k, new_v

    # ------------------------------------------------------------ stats

    @property
    def n_free_pages(self) -> int:
        return len(self._free)

    @property
    def n_free_slots(self) -> int:
        return len(self._free_slots)

    def slot_capacity(self, slot: int) -> int:
        return len(self._slot_pages[slot]) * self.page_size

    def get_stats(self) -> Dict[str, float]:
        bytes_total = 2 * self.k_pages.size * self.k_pages.dtype.itemsize
        used = self.num_pages - len(self._free)
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "pages_used": used,
            "pages_free": len(self._free),
            "peak_pages_used": self._peak_pages_used,
            "utilization": used / self.num_pages if self.num_pages else 0.0,
            "live_slots": len(self._slot_pages),
            "free_slots": len(self._free_slots),
            "hbm_bytes": bytes_total,
            "hbm_gib": bytes_total / (1 << 30),
        }
