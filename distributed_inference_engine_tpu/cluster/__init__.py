from .registry import (  # noqa: F401
    ModelStatus,
    ModelShard,
    ModelVersion,
    ModelRegistry,
)
from .worker import (  # noqa: F401
    WorkerServer,
    WorkerClient,
    WorkerRPCError,
    build_engine,
)
from .router import (  # noqa: F401
    Router,
    RouteResult,
    RoutingError,
    WorkerHealth,
    WorkerInfo,
)
from .load_balancer import (  # noqa: F401
    LoadBalancer,
    LoadBalancerStrategy,
    NoHealthyWorkerError,
    WorkerStats,
)
