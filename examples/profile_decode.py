"""Profile the flagship decode chunk and attribute device time per op.

Captures a ``jax.profiler`` trace of a few steady-state decode chunks on
the continuous engine (same env knobs as bench.py), parses the xplane
protobuf directly (the tensorboard converter is broken against the
installed protobuf), and prints a device-time table grouped by op class —
the itemization VERDICT r3 item 5 asked for.

    BENCH_QUANT=1 python examples/profile_decode.py      # int8 rung
    BENCH_QUANT=4 python examples/profile_decode.py      # int4 kernel rung
"""

import collections
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

import bench  # noqa: E402
from bench import log  # noqa: E402


def classify(name: str) -> str:
    n = name.lower()
    if "int4_matmul" in n or "tpu_custom_call" in n:
        return "int4 kernel (weights)"
    if "dot" in n or "convolution" in n or "einsum" in n:
        return "matmul fusions (weights/attn)"
    if "gather" in n:
        return "ctx gather (KV pages)"
    if "scatter" in n or "dynamic-update" in n:
        return "KV writeback/scatter"
    if "fusion" in n:
        return "other fusions (elementwise/attn)"
    if "copy" in n or "bitcast" in n or "transpose" in n or "reshape" in n:
        return "layout/copies"
    if "infeed" in n or "outfeed" in n or "send" in n or "recv" in n:
        return "host transfer"
    return "other"


def parse_xplane(trace_dir: str):
    """Sum device-time (ps) per HLO op name on the TPU plane."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    per_op = collections.Counter()
    total_ps = 0
    for path in paths:
        space = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            space.ParseFromString(f.read())
        for plane in space.planes:
            if "TPU" not in plane.name or "device" not in plane.name.lower():
                continue
            meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
            for line in plane.lines:
                for ev in line.events:
                    name = meta.get(ev.metadata_id, "?")
                    per_op[name] += ev.duration_ps
                    total_ps += ev.duration_ps
    return per_op, total_ps


def main() -> None:
    import jax

    log(f"devices: {jax.devices()}")
    spec = bench._spec()
    steps = int(os.environ.get("BENCH_STEPS", "16"))
    params = bench._build_params(spec, bench.QUANT)
    engine = bench._engine(spec, params, "continuous", bench.BATCH, steps)
    log("engine up; warming")
    engine.generate(bench._requests(spec, 1, bench.BATCH))   # compile+prime

    # steady state: fill slots, then profile a few pure-decode chunks
    for r in bench._requests(spec, 2, bench.BATCH):
        engine.submit(r)
    engine.step()                                    # admission + chunk 1
    trace_dir = os.environ.get("PROFILE_DIR", "/tmp/decode_trace")
    with jax.profiler.trace(trace_dir):
        for _ in range(3):
            engine.step()
    engine.abort_all()
    log(f"trace captured in {trace_dir}")

    per_op, total_ps = parse_xplane(trace_dir)
    by_class = collections.Counter()
    for name, ps in per_op.items():
        by_class[classify(name)] += ps
    print(f"\ndevice time over 3 decode chunks "
          f"({steps} steps each, bs{bench.BATCH}, "
          f"int{'4' if bench.QUANT_BITS == 4 and bench.QUANT else '8' if bench.QUANT else 'none'}):")
    print(f"{'class':36s} {'ms':>9s} {'share':>7s}")
    for cls, ps in by_class.most_common():
        print(f"{cls:36s} {ps / 1e9:9.2f} {ps / total_ps:7.1%}")
    print(f"{'TOTAL':36s} {total_ps / 1e9:9.2f}")
    print("\ntop 20 ops:")
    for name, ps in per_op.most_common(20):
        print(f"  {ps / 1e9:8.2f} ms  {name[:100]}")


if __name__ == "__main__":
    main()
