"""fp8 (e4m3) KV cache: half the KV HBM of bf16, so double the live
sequences per chip — ``EngineConfig.kv_dtype="float8_e4m3fn"`` flows
through the contiguous cache, the paged pools (XLA and Pallas paths), the
prefix cache, and the disaggregated handoff. The attention ops upcast at
the boundary (fp8 has no implicit promotion path in jax)."""

import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=128).replace(dtype="float32")
CFG = dict(max_slots=2, max_seq_len=128, prefill_buckets=[16],
           decode_steps_per_call=4)


def _req(n=10):
    return GenerationRequest(prompt=[1, 2, 3, 4], max_new_tokens=n)


def test_static_engine_fp8_kv_matches_bf16_greedy():
    ref = Engine(SPEC, config=EngineConfig(**CFG), seed=0)
    base = ref.generate([_req()])[0].tokens
    e8 = Engine(SPEC, params=ref.params,
                config=EngineConfig(**CFG, kv_dtype="float8_e4m3fn"))
    assert e8.generate([_req()])[0].tokens == base


def test_continuous_fp8_pages_half_the_bytes():
    ref = ContinuousEngine(SPEC, config=EngineConfig(
        **CFG, page_size=16, num_pages=24), seed=0)
    base = ref.generate([_req()])[0].tokens
    c8 = ContinuousEngine(SPEC, params=ref.params, config=EngineConfig(
        **CFG, page_size=16, num_pages=24, kv_dtype="float8_e4m3fn"))
    assert c8.generate([_req()])[0].tokens == base
    assert c8.kv.k_pages.dtype.itemsize == 1
    assert (c8.kv.get_stats()["hbm_bytes"]
            == ref.kv.get_stats()["hbm_bytes"] // 2)


def test_fp8_pages_pallas_interpret_matches_xla():
    import jax
    import jax.numpy as jnp

    from distributed_inference_engine_tpu.ops.paged_attention import (
        paged_attention_pallas,
        paged_attention_xla,
    )

    B, H, Hkv, Dh, N, P, MP = 2, 4, 4, 32, 8, 16, 4
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, H, Dh), jnp.float32)
    kp = jnp.asarray(rs.randn(N, P, Hkv * Dh), jnp.float8_e4m3fn)
    vp = jnp.asarray(rs.randn(N, P, Hkv * Dh), jnp.float8_e4m3fn)
    pt = jnp.asarray(rs.randint(0, N, (B, MP)), jnp.int32)
    lengths = jnp.asarray([20, 55], jnp.int32)
    ref = paged_attention_xla(q, kp, vp, pt, lengths, n_kv_heads=Hkv)
    out = paged_attention_pallas(q, kp, vp, pt, lengths, n_kv_heads=Hkv,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_disagg_handoff_fp8_roundtrip():
    from distributed_inference_engine_tpu.engine.disagg import (
        PrefillEngine,
        handoff_from_wire,
        handoff_to_wire,
    )

    eng = PrefillEngine(SPEC, config=EngineConfig(
        **CFG, kv_dtype="float8_e4m3fn"), seed=0)
    h = eng.prefill([GenerationRequest(prompt=[1, 2, 3, 4],
                                       max_new_tokens=2,
                                       request_id="r")])[0]
    assert h.k.dtype.itemsize == 1
    h2 = handoff_from_wire(handoff_to_wire(h))
    np.testing.assert_array_equal(
        h.k.view(np.uint8), h2.k.view(np.uint8))

    # and the decode side admits it
    dec = ContinuousEngine(SPEC, params=eng.params, config=EngineConfig(
        **CFG, page_size=16, num_pages=24, kv_dtype="float8_e4m3fn"))
    dec.submit_prefilled(GenerationRequest(prompt=[1, 2, 3, 4],
                                           max_new_tokens=4,
                                           request_id="r"), h2)
    out = dec.run_until_idle()[0]
    assert len(out.tokens) == 4
