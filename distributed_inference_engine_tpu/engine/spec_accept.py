"""Shared speculative-decoding acceptance math (Leviathan et al. / Chen
et al. rejection sampling), extracted from the r5 synchronous engine so
the async bubble-scheduled path (``engine/spec_async.py`` + the
continuous engine's verify chunk) accepts with BIT-IDENTICAL rules.

Two exactness contracts hang off this module, both pinned by tests:

1. **r5 parity.** ``rejection_accept`` is the r5 ``_round_core``
   acceptance block verbatim — same op order, same key usage — so the
   synchronous ``SpeculativeEngine``'s outputs are unchanged by the
   refactor (tests/test_spec_async.py pins this against a frozen copy).
2. **Greedy chain identity.** For greedy rows the accept rule is
   ``argmax p_j == d_j`` and the final token is ``argmax`` of the
   final distribution, so the emitted run is token-for-token the
   target's own greedy chain regardless of WHAT the draft proposed —
   which is why draft-side state (async drafter caches, stale
   proposals) can never corrupt output, only acceptance rate.

The async path adds one degree of freedom the sync engine never needed:
per-row ``valid`` masks. A verify batch mixes drafted rows (k draft
columns) with plain decode rows (zero draft columns riding the same
program); plain rows pass an all-False mask plus ZERO ``q_probs``, which
drives the residual ``max(p - q, 0)`` to exactly ``p`` — their "final"
token is then a plain sample from the target distribution, identical to
the non-speculative decode step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.sampling import SamplingParams, masked_sampling_probs


def draft_sample(q_logits: jnp.ndarray, sampling: SamplingParams,
                 greedy: jnp.ndarray, key: jax.Array
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One draft proposal: sample from the knob-MODIFIED draft
    distribution (``masked_sampling_probs``) so the proposal stays inside
    the target's support; greedy rows take the raw argmax (exactly the r5
    propose step). Returns (token [B] int32, q_probs [B, V])."""
    probs = masked_sampling_probs(q_logits, sampling)
    d_samp = jax.random.categorical(
        key, jnp.log(jnp.maximum(probs, 1e-30)), axis=-1)
    greedy1 = greedy[:, 0] if greedy.ndim == 2 else greedy
    d_tok = jnp.where(greedy1, q_logits.argmax(-1), d_samp)
    return d_tok.astype(jnp.int32), probs


def rejection_accept(
    p_probs: jnp.ndarray,      # [B, k+1, V] knob-modified target probs
    q_probs: jnp.ndarray,      # [B, k, V] knob-modified draft probs
    drafts: jnp.ndarray,       # [B, k] int32 proposed tokens
    greedy: jnp.ndarray,       # [B] (or [B, 1]) bool: temperature <= 0
    key_resid: jax.Array,      # acceptance uniforms (r5 key order)
    key_bonus: jax.Array,      # bonus/residual categorical draw
    valid: Optional[jnp.ndarray] = None,   # [B, k] bool draft-column mask
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Rejection-sampling acceptance over one verify window.

    Greedy rows accept while ``argmax p_j == d_j``; sampled rows accept
    ``d_j`` with probability ``min(1, p_j[d_j]/q_j[d_j])`` and the first
    rejection resamples from ``norm(max(p - q, 0))`` (falling back to
    ``p`` when the residual is degenerate). All-accepted rows draw a
    bonus token from ``p_k``. Both p and q must already be the
    knob-modified distributions (``masked_sampling_probs``) — identical
    masking is what makes the ratio exact for the request's settings.

    ``valid`` (async path) force-rejects masked columns BEFORE the
    cumulative-run product, so a row with zero valid columns lands on
    ``n_acc == 0`` with its final drawn from position 0 — the plain
    decode sample when its ``q_probs`` row is zeros (see module doc).

    Returns ``(n_acc [B] int32, final [B] int32, accept [B, k] bool)``;
    the emitted run is ``drafts[:, :n_acc]`` then ``final``.
    """
    b, k = drafts.shape
    bidx = jnp.arange(b)
    greedy2 = greedy if greedy.ndim == 2 else greedy[:, None]   # [B, 1]

    p_at_d = jnp.take_along_axis(
        p_probs[:, :k], drafts[:, :, None], axis=-1)[..., 0]
    q_at_d = jnp.take_along_axis(
        q_probs, drafts[:, :, None], axis=-1)[..., 0]
    u = jax.random.uniform(key_resid, drafts.shape)
    acc_samp = u * q_at_d < p_at_d
    acc_greedy = p_probs[:, :k].argmax(-1) == drafts
    accept = jnp.where(greedy2, acc_greedy, acc_samp)           # [B, k]
    if valid is not None:
        accept = accept & valid
    acc_run = jnp.cumprod(accept.astype(jnp.int32), axis=1)
    n_acc = acc_run.sum(axis=1)                                 # [B] 0..k

    # final token: bonus sample from p_k when all accepted, else resample
    # from the residual at the first rejected position
    all_acc = n_acc == k
    pos_r = jnp.minimum(n_acc, k - 1)
    p_rej = p_probs[bidx, pos_r]                                # [B, V]
    q_rej = q_probs[bidx, pos_r]
    resid = jnp.maximum(p_rej - q_rej, 0.0)
    resid_sum = resid.sum(-1, keepdims=True)
    # degenerate residual (q covers p): fall back to p
    resid = jnp.where(resid_sum > 1e-9, resid, p_rej)
    resid = resid / resid.sum(-1, keepdims=True)
    p_bonus = p_probs[bidx, jnp.int32(k)]
    final_dist = jnp.where(all_acc[:, None], p_bonus, resid)
    f_samp = jax.random.categorical(
        key_bonus, jnp.log(jnp.maximum(final_dist, 1e-30)), axis=-1)
    final = jnp.where(greedy2[:, 0], final_dist.argmax(-1), f_samp)
    return n_acc.astype(jnp.int32), final.astype(jnp.int32), accept
