"""Sequence-parallel long-context prefill (parallel/long_context.py):
ring attention shards the prompt over the sp axis, feeding the unchanged
decode loop / disaggregated handoff. SURVEY.md §5 long-context row —
capability extension, held to exact-parity tests against the dense prefill
on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig, MeshConfig
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.base import (
    forward_prefill,
    init_params,
)
from distributed_inference_engine_tpu.models.llama import llama_spec
from distributed_inference_engine_tpu.models.mistral import mistral_spec
from distributed_inference_engine_tpu.parallel.long_context import (
    prefill_fn_for,
    sp_forward_prefill,
)
from distributed_inference_engine_tpu.parallel.mesh import make_mesh

SPEC = llama_spec("llama-tiny", max_seq_len=256).replace(dtype="float32")


def _mesh(sp=4, dp=2):
    return make_mesh(MeshConfig(dp=dp, sp=sp),
                     devices=jax.devices()[: dp * sp])


def test_sp_prefill_matches_dense():
    mesh = _mesh()
    params = init_params(SPEC, jax.random.key(0))
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(1, 1000, (2, 64)), jnp.int32)
    lens = jnp.asarray([64, 40], jnp.int32)
    h_ref, k_ref, v_ref = forward_prefill(SPEC, params, tokens, lens)
    h_sp, k_sp, v_sp = sp_forward_prefill(SPEC, params, tokens, lens, mesh)
    np.testing.assert_allclose(np.asarray(h_sp), np.asarray(h_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(k_sp), np.asarray(k_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(v_sp), np.asarray(v_ref),
                               rtol=2e-4, atol=2e-4)


def test_engine_with_sp_mesh_matches_plain_engine():
    """The serving contract: an sp-prefill engine produces token-identical
    greedy output — the sequence sharding is an execution layout, not a
    model change."""
    mesh = _mesh()
    cfg = EngineConfig(max_slots=2, max_seq_len=256, prefill_buckets=[64],
                       decode_steps_per_call=8)
    plain = Engine(SPEC, config=cfg, seed=0)
    sp = Engine(SPEC, params=plain.params, config=cfg, sp_mesh=mesh)
    prompt = list(range(1, 61))
    a = plain.generate([GenerationRequest(prompt=list(prompt),
                                          max_new_tokens=10)])[0]
    b = sp.generate([GenerationRequest(prompt=list(prompt),
                                       max_new_tokens=10)])[0]
    assert a.tokens == b.tokens


def test_prefill_engine_with_sp_mesh_handoff_parity():
    from distributed_inference_engine_tpu.engine.disagg import PrefillEngine

    mesh = _mesh()
    cfg = EngineConfig(max_slots=2, max_seq_len=256, prefill_buckets=[64])
    plain = PrefillEngine(SPEC, config=cfg, seed=0)
    sp = PrefillEngine(SPEC, params=plain.params, config=cfg, sp_mesh=mesh)
    req = GenerationRequest(prompt=list(range(1, 50)), max_new_tokens=4,
                            request_id="h1")
    h_plain = plain.prefill([req])[0]
    h_sp = sp.prefill([req])[0]
    assert h_sp.first_token == h_plain.first_token
    assert h_sp.prompt_len == h_plain.prompt_len
    np.testing.assert_allclose(
        h_sp.k.astype(np.float32), h_plain.k.astype(np.float32),
        rtol=2e-2, atol=2e-2)   # kv dtype is bf16


def test_sp_prefill_rejects_misaligned_bucket_and_window():
    mesh = _mesh()
    params = init_params(SPEC, jax.random.key(0))
    tokens = jnp.ones((1, 30), jnp.int32)        # 30 % 4 != 0
    with pytest.raises(ValueError, match="not divisible by sp"):
        sp_forward_prefill(SPEC, params, tokens, jnp.asarray([30]), mesh)
    wspec = mistral_spec("mistral-tiny", max_seq_len=256).replace(
        dtype="float32")
    wparams = init_params(wspec, jax.random.key(0))
    with pytest.raises(ValueError, match="sliding-window"):
        sp_forward_prefill(wspec, wparams, jnp.ones((1, 64), jnp.int32),
                           jnp.asarray([64]), mesh)


def test_prefill_fn_selector():
    assert prefill_fn_for(SPEC, None) is forward_prefill
    mesh1 = make_mesh(MeshConfig(dp=8), devices=jax.devices()[:8])
    assert prefill_fn_for(SPEC, mesh1) is forward_prefill   # sp == 1
    assert prefill_fn_for(SPEC, _mesh()) is not forward_prefill


def test_engine_construction_fails_fast_on_bad_sp_config():
    """Misconfiguration must fail the deploy, not the first request."""
    mesh = _mesh()
    wspec = mistral_spec("mistral-tiny", max_seq_len=256).replace(
        dtype="float32")
    with pytest.raises(ValueError, match="sliding-window"):
        Engine(wspec, config=EngineConfig(max_slots=2, max_seq_len=256,
                                          prefill_buckets=[64]),
               sp_mesh=mesh)
    with pytest.raises(ValueError, match="not divisible by sp"):
        Engine(SPEC, config=EngineConfig(max_slots=2, max_seq_len=256,
                                         prefill_buckets=[30]),
               sp_mesh=mesh)
