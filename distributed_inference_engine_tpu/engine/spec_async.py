"""Bubble-scheduled asynchronous speculation (ISSUE 15 / ROADMAP 5).

Round 5's *synchronous* speculative engine measured 0.80x against the
int4 flagship: every draft+verify round sits ON the critical path, so
the draft's latency is paid even when acceptance is high. PipeInfer
(PAPERS.md) inverts the schedule — draft in the HOST GAPS between the
serving engine's device dispatches, verify by piggybacking the drafted
tokens onto the next megastep as extra query columns — so the draft
model's compute hides in time the device was idle anyway and the only
on-path cost is the (wider, still one-dispatch) verify step.

``AsyncSpeculator`` layers that schedule over ``ContinuousEngine``:

- **Drafting** runs a small draft model (a truncated self-draft by
  default — ``engine.speculative.truncated_draft`` — or an r13 serving
  artifact via ``spec_draft_model="artifact:<path>"``) over dense
  per-slot caches, for STREAMING-flagged slots only: batch-throughput
  traffic gains nothing from speculation (the batch already fills the
  device) while latency-priced streams are exactly where accepted
  drafts compress inter-token latency.
- **Scheduling** is bubble-budgeted: ``schedule()`` is called from the
  serving pump's overlap hook (right after ``poll_stream()``, while a
  chunk is in flight) and from the engine's step top (the gap between
  dispatch brackets). Each call first estimates the live per-step host
  bubble from ``obs.timeline.busy_gap_split`` (falling back to the
  engine's dispatch/gap accumulators when the timeline ring is off) and
  SKIPS the round when the estimate is below
  ``EngineConfig.spec_bubble_floor_s`` — at saturation the gap
  collapses, the estimate falls under the floor, and speculation
  auto-idles to zero overhead (the ``auto_idles`` counter is the
  regression guard).
- **Verification is asynchronous**: proposals never block. They are
  parked on device (``_drafts``/``_qprobs``) and ride the NEXT decode
  step as extra verify columns through the ragged mixed-step path
  (``ContinuousEngine._verify_chunk``); acceptance is the shared
  rejection-sampling rule in ``engine.spec_accept``, so greedy output
  is token-for-token the non-speculative engine's.

Correctness never depends on the draft. The verify step recomputes the
target distribution at every position, so a stale basis, a clamped
draft cache, or plain garbage proposals can only lower the ACCEPTANCE
rate — the emitted tokens are always target-model tokens. That one
property keeps every edge case here (slot reuse, mid-flight
invalidation, capacity-clipped windows) a performance concern, not a
correctness one; the engine drops invalidated proposals and counts
them in ``wasted_tokens``.

Draft-cache bookkeeping (the catch-up/propose split):

- ``_dlen[slot]`` is the draft KV's valid prefix: positions
  ``[0, _dlen)`` hold KV for the COMMITTED sequence (admitted prompt +
  harvested tokens). The host always knows that sequence, so catch-up
  needs no device reads: it forwards the missing window
  ``seq[_dlen : total]`` through ``models.base.forward_window`` (ragged
  ``n_valid``, out-of-range scatters dropped).
- Catch-up is always safe — committed tokens never change — so it runs
  even while a chunk is in flight (the overlap-hook call). PROPOSING
  needs a frontier basis: it runs only when no chunk is in flight
  (``engine._inflight_chunks == 0``, i.e. the step-top call) and no
  proposal is already pending, drafts ``spec_max_draft`` tokens in one
  scan, and records the basis ``(L, last_token)`` per slot. The verify
  step re-checks that basis against the live host state; any mismatch
  (a mixed step advanced the slot, a swap, slot reuse) wastes the
  proposal, nothing more.
- Steady state is one catch-up token per accepted run: the verify
  step's bonus/rejection token is sampled from the TARGET distribution,
  so the draft has never seen it — the next round's deficit is 1.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import ModelSpec, Params, forward_window
from ..obs.timeline import busy_gap_split
from ..ops.sampling import SamplingParams
from ..utils.hotpath import hot_path
from .spec_accept import draft_sample

__all__ = ["AsyncSpeculator", "resolve_draft"]


def resolve_draft(spec: ModelSpec, params: Params, name: str,
                  ) -> Tuple[ModelSpec, Params]:
    """Build (draft_spec, draft_params) from ``EngineConfig
    .spec_draft_model``:

    - ``"layers:N"`` (and ``""`` → ``layers:2``): truncated self-draft —
      the target's own first N blocks with shared embeddings/head
      (``engine.speculative.truncated_draft``; works on the engine's
      already-prepared tree, QuantizedTensor leaves slice payload and
      scales together).
    - ``"artifact:<path>"``: an r13 serving artifact
      (``engine/artifact.py``) — the cold-start path for a real trained
      drafter; the sidecar tree is already post-``prepare_params``.

    The draft must share the target's vocabulary: acceptance compares
    per-token probabilities index-by-index.
    """
    from .speculative import truncated_draft

    name = name or "layers:2"
    if name.startswith("artifact:"):
        from .artifact import load_artifact

        d_spec, d_params, _ = load_artifact(name.split(":", 1)[1])
        if d_spec.vocab_size != spec.vocab_size:
            raise ValueError(
                f"draft vocab {d_spec.vocab_size} != target vocab "
                f"{spec.vocab_size}: rejection sampling compares "
                "distributions index-by-index")
        return d_spec, d_params
    if name.startswith("layers:"):
        n = int(name.split(":", 1)[1])
        if spec.n_layers < 2:
            raise ValueError(
                "spec_async truncated self-draft needs n_layers >= 2 "
                "(pass spec_draft_model='artifact:...' for a 1-layer "
                "target)")
        n = max(1, min(n, spec.n_layers - 1))
        return truncated_draft(spec, params, n)
    raise ValueError(
        f"spec_draft_model {name!r} is not 'layers:N'|'artifact:<path>'")


class AsyncSpeculator:
    """Drafter subsystem over one ``ContinuousEngine`` (module doc)."""

    # catch-up window pow2 buckets: the whole run compiles at most
    # len(buckets) x {catch-up, propose} draft programs. Steady state
    # lives in the smallest bucket (deficit 1 = the bonus token); the
    # large bucket drains fresh prompts a window at a time.
    _W_BUCKETS = (8, 64)

    def __init__(self, engine: Any, draft_spec: ModelSpec,
                 draft_params: Params, *, k: int,
                 bubble_floor_s: float, seed: int = 0) -> None:
        if k < 1:
            raise ValueError(f"spec_max_draft {k} < 1")
        self.engine = engine
        self.draft_spec = draft_spec
        self.draft_params = draft_params
        self.k = int(k)
        self.bubble_floor_s = float(bubble_floor_s)
        self._rng = jax.random.key(seed ^ 0x5bec)

        b = engine.max_slots
        cfg = engine.config
        # dense per-slot draft caches ([L, max_slots, S, Hkv, Dh] — the
        # sync engine's layout, reused so forward_window serves both).
        # +k+1 headroom: proposal KV lands past the committed frontier;
        # forward_window's mode="drop" scatter bounds everything else.
        s_d = min(cfg.max_seq_len, engine.spec.max_seq_len) + self.k + 1
        dt = jnp.dtype(cfg.kv_dtype)
        shape = (draft_spec.n_layers, b, s_d, draft_spec.n_kv_heads,
                 draft_spec.head_dim)
        self._S = s_d
        self._dck = jnp.zeros(shape, dt)
        self._dcv = jnp.zeros(shape, dt)

        # host mirrors: valid draft-KV prefix per slot row, the _Slot
        # identity the row belongs to (slot ids are reused), and the
        # ADMITTED prompt (overlong prompts keep their tail — the
        # engine's clamp, re-derived from prompt_len)
        self._dlen = np.zeros((b,), np.int64)
        self._ident: Dict[int, Any] = {}
        self._prompt: Dict[int, List[int]] = {}
        # pending proposals: slot -> (basis L, basis last token). The
        # proposal tensors stay ON DEVICE until the verify step consumes
        # them — drafting costs zero host syncs.
        self._pending: Dict[int, Tuple[int, int]] = {}
        self._drafts: Optional[jnp.ndarray] = None    # [B, k] int32
        self._qprobs: Optional[jnp.ndarray] = None    # [B, k, V] f32

        # metrics (engine.get_metrics exports these as spec_async_*)
        self._drafted_tokens = 0
        self._accepted_tokens = 0
        self._wasted_tokens = 0
        self._catchup_tokens = 0
        self._draft_rounds = 0
        self._propose_rounds = 0
        self._auto_idles = 0
        self._bubble_consumed_s = 0.0
        self._cost_ema: Optional[float] = None
        # accumulator-fallback bubble estimate state
        self._gap_mark = (0.0, 0)
        self._last_est = 0.0

        d_spec = draft_spec
        kk = self.k

        @partial(jax.jit, static_argnames=("w", "propose"),
                 donate_argnums=(1, 2))
        def _round(params, dck, dcv, tokens, n_valid, start, sampling,
                   key, w: int, propose: bool):
            """One draft round: catch the per-slot caches up over a
            ragged token window, then (propose=True) autoregress ``k``
            proposals. Rows not participating pass ``start = S`` — every
            scatter lands out of range and drops; their outputs are
            garbage the host never reads. ``w`` is the pow2 window
            bucket (static → one program per (bucket, propose))."""
            del w
            logits, dck, dcv = forward_window(
                d_spec, params, tokens, n_valid, start, dck, dcv)
            if not propose:
                return dck, dcv
            b_ = tokens.shape[0]
            # distribution AFTER the last caught-up token (= after the
            # committed frontier token for propose rows)
            q_logits = logits[jnp.arange(b_),
                              jnp.maximum(n_valid - 1, 0)]
            greedy = sampling.temperature <= 0.0
            pos0 = (start + n_valid).astype(jnp.int32)
            one = jnp.ones((b_,), jnp.int32)

            def prop(carry, step_key):
                dck, dcv, q_logits, pos = carry
                d_tok, q_probs = draft_sample(
                    q_logits, sampling, greedy, step_key)
                nxt, dck, dcv = forward_window(
                    d_spec, params, d_tok[:, None], one, pos, dck, dcv)
                return (dck, dcv, nxt[:, 0], pos + 1), (d_tok, q_probs)

            keys = jax.random.split(key, kk)
            (dck, dcv, _, _), (dr, qp) = jax.lax.scan(
                prop, (dck, dcv, q_logits, pos0), keys)
            return dck, dcv, dr.T, jnp.swapaxes(qp, 0, 1)

        self._round = _round

    # ------------------------------------------------------------ budget

    def _bubble_estimate(self) -> float:
        """Live per-step host-bubble estimate, in seconds.

        Timeline ring on: ``busy_gap_split`` over the most recent
        records — gap seconds per inter-dispatch gap. Ring off: delta of
        the engine's always-on ``_host_gap_s`` accumulator over the
        steps since the last estimate. Cold start (nothing measured)
        reads 0.0, so a positive floor idles the drafter until real gap
        data exists — the conservative direction."""
        eng = self.engine
        tl = eng.timeline
        if tl is not None:
            ev = tl.events()
            if len(ev) < 2:
                return 0.0
            split = busy_gap_split(ev[-32:])
            return split["gap_s"] / max(1, split["n_events"] - 1)
        steps = (eng._steps + eng._mixed_steps
                 + getattr(eng, "_spec_verify_steps", 0))
        d_gap = eng._host_gap_s - self._gap_mark[0]
        d_n = steps - self._gap_mark[1]
        if d_n <= 0:
            return self._last_est
        self._gap_mark = (eng._host_gap_s, steps)
        self._last_est = d_gap / d_n
        return self._last_est

    # ------------------------------------------------------- host mirror

    def _sync_ident(self) -> None:
        """Reconcile slot rows with the engine's live ``_Slot`` objects:
        finished/reused slots reset their draft row (dlen=0) and waste
        any pending proposal; new slots cache their ADMITTED prompt."""
        eng = self.engine
        for slot in list(self._ident):
            st = eng._slots.get(slot)
            if st is None or st is not self._ident[slot]:
                del self._ident[slot]
                self._prompt.pop(slot, None)
                self._dlen[slot] = 0
                if self._pending.pop(slot, None) is not None:
                    self._wasted_tokens += self.k
        for slot, st in eng._slots.items():
            if slot not in self._ident:
                self._ident[slot] = st
                self._dlen[slot] = 0
                p = st.request.prompt
                self._prompt[slot] = (
                    list(p) if len(p) == st.prompt_len
                    else list(p[-st.prompt_len:]))

    def _seq_tok(self, slot: int, st: Any, i: int) -> int:
        p = self._prompt[slot]
        return p[i] if i < len(p) else int(st.tokens[i - len(p)])

    # --------------------------------------------------------- schedule

    @hot_path
    def schedule(self) -> int:
        """One bubble-budgeted draft round; returns rows worked.

        Called from the pump's overlap hook (after ``poll_stream()``;
        catch-up only — a chunk is in flight, so the frontier is about
        to move) and from the engine's step top (the inter-dispatch gap;
        the host state IS the frontier, so proposing is allowed). The
        round is one async device dispatch — no host syncs — so an
        overrun queues behind the next chunk instead of delaying its
        dispatch."""
        eng = self.engine
        if not eng._slots:
            return 0
        t_start = time.perf_counter()
        self._sync_ident()
        est = self._bubble_estimate()
        if est < self.bubble_floor_s:
            self._auto_idles += 1
            return 0
        can_propose = (eng._inflight_chunks == 0 and not self._pending)
        wmax = self._W_BUCKETS[-1]
        rows: List[Tuple[int, int, int]] = []     # (slot, start, cat)
        propose_rows: List[int] = []
        for slot, st in eng._slots.items():
            if st.on_tokens is None or st.first_pending:
                continue     # speculation serves streaming slots only
            total = st.prompt_len + len(st.tokens)      # = L + 1
            deficit = total - int(self._dlen[slot])
            if can_propose and deficit <= wmax:
                # deficit 0 (proposal was wasted without the slot
                # moving): re-forward the frontier token — idempotent KV
                # write, recovers the propose distribution
                start = total - 1 if deficit <= 0 else int(
                    self._dlen[slot])
                rows.append((slot, start, total - start))
                propose_rows.append(slot)
            elif deficit > 0:
                start = int(self._dlen[slot])
                rows.append((slot, start, min(deficit, wmax)))
        if not rows:
            return 0

        w = self._W_BUCKETS[0]
        need = max(c for _, _, c in rows)
        for b_ in self._W_BUCKETS:
            if b_ >= need:
                w = b_
                break
        b = eng.max_slots
        tok_m = np.zeros((b, w), np.int32)
        n_valid = np.zeros((b,), np.int32)
        start_v = np.full((b,), self._S, np.int32)   # sentinel: drop all
        for slot, start, cat in rows:
            st = eng._slots[slot]
            tok_m[slot, :cat] = [self._seq_tok(slot, st, i)
                                 for i in range(start, start + cat)]
            n_valid[slot] = cat
            start_v[slot] = start

        sampling = SamplingParams(eng._temps, eng._top_k, eng._top_p,
                                  eng._min_p)
        self._rng, kr = jax.random.split(self._rng)
        do_prop = bool(propose_rows)
        out = self._round(self.draft_params, self._dck, self._dcv,
                          jnp.asarray(tok_m), jnp.asarray(n_valid),
                          jnp.asarray(start_v), sampling, kr,
                          w=w, propose=do_prop)
        if do_prop:
            self._dck, self._dcv, self._drafts, self._qprobs = out
        else:
            self._dck, self._dcv = out

        for slot, start, cat in rows:
            self._dlen[slot] = start + cat
            self._catchup_tokens += cat
        for slot in propose_rows:
            st = eng._slots[slot]
            total = st.prompt_len + len(st.tokens)
            self._pending[slot] = (
                total - 1, self._seq_tok(slot, st, total - 1))
            self._drafted_tokens += self.k
        self._draft_rounds += 1
        self._propose_rounds += do_prop
        dt = time.perf_counter() - t_start
        self._bubble_consumed_s += dt
        self._cost_ema = (dt if self._cost_ema is None
                          else 0.8 * self._cost_ema + 0.2 * dt)
        return len(rows)

    # ----------------------------------------------------------- verify

    def take_verifiable(self):
        """Consume pending proposals for the next decode step. Returns
        ``(drafts_dev, qprobs_dev, n_drafts, verified)`` — ``n_drafts``
        is a per-slot column count (0 = plain decode row) and
        ``verified`` maps slot -> (basis L, columns granted) — or None
        when nothing survives the freshness + capacity checks.

        Freshness: the recorded basis must still be the slot's live
        frontier (same ``_Slot``, same committed length, same last
        token). Capacity: the verify window writes KV at
        ``[L, L + m + 1)``, so columns are clipped to the slot's page
        grant — writing through a stale page-table entry would corrupt
        OTHER slots, the one draft failure mode that is not
        performance-only. Every drop or clip lands in
        ``wasted_tokens``."""
        if not self._pending:
            return None
        eng = self.engine
        self._sync_ident()                 # drops dead/reused slots
        n_drafts = np.zeros((eng.max_slots,), np.int32)
        verified: Dict[int, Tuple[int, int]] = {}
        for slot, (basis_len, basis_last) in list(self._pending.items()):
            del self._pending[slot]
            st = eng._slots.get(slot)
            if st is None or self._ident.get(slot) is not st:
                self._wasted_tokens += self.k
                continue
            total = st.prompt_len + len(st.tokens)
            fresh = (total - 1 == basis_len
                     and self._seq_tok(slot, st, basis_len) == basis_last)
            cap_tok = min(eng.kv.slot_capacity(slot), eng.max_seq_len)
            m = max(0, min(self.k, cap_tok - basis_len - 1))
            if not fresh or m <= 0:
                self._wasted_tokens += self.k
                continue
            self._wasted_tokens += self.k - m
            n_drafts[slot] = m
            verified[slot] = (basis_len, m)
        if not verified:
            return None
        return self._drafts, self._qprobs, n_drafts, verified

    def note_verified(self, entry: Any, verified: Dict[int, Tuple[int,
                                                                  int]],
                      ) -> None:
        """Post-verify bookkeeping from the chunk's packed host read
        (``entry.host`` — zero extra device syncs): acceptance counters
        and the draft-KV validity extension. ``n_acc`` is clipped to
        tokens actually EMITTED (budget/cap/eos cuts discard accepted
        tokens; greedy re-derives them identically later, sampled rows
        re-sample — either way the draft KV past the committed frontier
        may no longer match, so only the emitted prefix extends
        ``_dlen``)."""
        n = entry.n_steps
        acc_row = entry.host[2 * n + 4]
        toks = entry.host[:n]
        eng = self.engine
        for slot, (basis_len, m) in verified.items():
            n_acc = int(acc_row[slot])
            emitted = int((toks[:, slot] >= 0).sum())
            n_eff = max(0, min(n_acc, m, emitted))
            self._accepted_tokens += n_eff
            self._wasted_tokens += m - n_eff
            st = eng._slots.get(slot)
            if st is not None and self._ident.get(slot) is st:
                total = st.prompt_len + len(st.tokens)
                self._dlen[slot] = min(basis_len + 1 + n_eff, total)

    # ---------------------------------------------------------- metrics

    def get_metrics(self) -> Dict[str, Any]:
        drafted = self._drafted_tokens
        return {
            "drafted_tokens": drafted,
            "accepted_tokens": self._accepted_tokens,
            "wasted_tokens": self._wasted_tokens,
            "catchup_tokens": self._catchup_tokens,
            "accept_rate": (self._accepted_tokens / drafted
                            if drafted else 0.0),
            "draft_rounds": self._draft_rounds,
            "propose_rounds": self._propose_rounds,
            "auto_idles": self._auto_idles,
            "bubble_consumed_s": self._bubble_consumed_s,
            "draft_cost_ema_s": self._cost_ema or 0.0,
            "pending": len(self._pending),
        }
