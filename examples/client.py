"""Standalone client against a running coordinator — the reference README's
promised ``examples/example_client.py`` (``/root/reference/README.md:37``)
that was never shipped.

Pair it with the committed config (see ``examples/demo_config.toml`` for the
worker/coordinator commands), then:

    # one-shot, token-space prompt
    python examples/client.py --port 8000 --prompt "1 2 3" -n 8

    # streamed, text-space (works when the deployed model has a tokenizer)
    python examples/client.py --port 8000 --text "hello" --stream

    # fan out 16 concurrent requests and report throughput
    python examples/client.py --port 8000 --prompt "1 2 3" --requests 16

Exit status is non-zero on any failed request, so the script doubles as a
smoke probe in scripts/CI.
"""

import argparse
import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_inference_engine_tpu.api.frontend import (  # noqa: E402
    CoordinatorClient,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="examples/client.py",
        description="send generate requests to a running coordinator")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--model", default="tiny",
                   help="deployed model name (see demo_config.toml)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--prompt", help="space-separated token ids, e.g. '1 2 3'")
    src.add_argument("--text", help="text prompt (coordinator tokenizes)")
    p.add_argument("-n", "--max-new-tokens", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--stream", action="store_true",
                   help="print tokens as they arrive (single request only)")
    p.add_argument("--requests", type=int, default=1,
                   help="concurrent copies of the request to send")
    p.add_argument("--timeout", type=float, default=120.0)
    return p


async def amain(args: argparse.Namespace) -> int:
    client = CoordinatorClient(args.host, args.port, timeout=args.timeout)
    kwargs = dict(model=args.model, max_new_tokens=args.max_new_tokens,
                  temperature=args.temperature)
    if args.text is not None:
        kwargs["text"] = args.text
    else:
        kwargs["prompt"] = [int(t) for t in args.prompt.split()]

    async def one(i: int):
        if args.stream and args.requests == 1:
            def on_tokens(toks):
                print(f"stream: {toks}", flush=True)
            return await client.generate_stream(on_tokens=on_tokens, **kwargs)
        return await client.generate(**kwargs)

    t0 = time.perf_counter()
    results = await asyncio.gather(
        *(one(i) for i in range(args.requests)), return_exceptions=True)
    dt = time.perf_counter() - t0

    failures = 0
    tokens_out = 0
    for i, r in enumerate(results):
        if isinstance(r, BaseException):
            failures += 1
            print(f"request {i}: FAILED — {type(r).__name__}: {r}",
                  file=sys.stderr, flush=True)
            continue
        toks = r.get("tokens", [])
        tokens_out += len(toks)
        line = f"request {i}: tokens={toks}"
        if r.get("text") is not None:
            line += f" text={r['text']!r}"
        if r.get("finish_reason"):
            line += f" finish={r['finish_reason']}"
        print(line, flush=True)

    ok = len(results) - failures
    rate = tokens_out / dt if dt > 0 else 0.0
    print(f"done: {ok}/{len(results)} ok, {tokens_out} tokens "
          f"in {dt:.2f}s ({rate:.0f} tok/s)", flush=True)
    await client.close()
    return 1 if failures else 0


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    sys.exit(asyncio.run(amain(args)))


if __name__ == "__main__":
    main()
