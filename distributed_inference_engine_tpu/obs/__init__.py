"""Unified telemetry (ISSUE 4): metrics registry + OpenMetrics exposition,
engine step-timeline recording (Perfetto/Chrome trace export), and the
collector mappings that translate every component's ad-hoc ``get_stats()``
/ ``get_metrics()`` dict into stable metric families.

Import discipline: nothing in this package imports jax (or anything that
does) — the coordinator control plane and the docs/metric-name lint must
be able to import it on a bare interpreter.
"""

from .registry import (  # noqa: F401
    OPENMETRICS_CONTENT_TYPE,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from .timeline import StepTimeline  # noqa: F401
from .events import EVENTS, EventLog  # noqa: F401
from .clocksync import estimate_offset, merge_fleet_trace  # noqa: F401
from .slo import BurnObjective, BurnRateEngine  # noqa: F401
from .postmortem import read_bundle, write_bundle  # noqa: F401
