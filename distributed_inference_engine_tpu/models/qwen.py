"""Qwen2/2.5 family specs.

Llama-shaped (RoPE, RMSNorm, SwiGLU, GQA) with one family quirk the unified
spec carries as ``qkv_bias``: biases on the q/k/v projections only (no bias
on the output projection or MLP). Small sizes tie embeddings.

Capability-extension beyond the reference (which has no real models at all —
SURVEY.md §0: its engine is ``asyncio.sleep``, ``src/mock_models/
fake_model.py:47``); sizes follow the published family ladder, "-tiny" is the
CPU-test-scale shape.
"""

from __future__ import annotations

from .base import ModelSpec

_FAMILY = {
    # name: (layers, d_model, heads, kv_heads, d_ff, vocab, theta, max_seq, tie)
    "qwen2-72b": (80, 8192, 64, 8, 29568, 152064, 1e6, 32768, False),
    "qwen2-7b": (28, 3584, 28, 4, 18944, 152064, 1e6, 32768, False),
    "qwen2-1.5b": (28, 1536, 12, 2, 8960, 151936, 1e6, 32768, True),
    "qwen2-0.5b": (24, 896, 14, 2, 4864, 151936, 1e6, 32768, True),
    "qwen-tiny": (4, 256, 8, 4, 688, 1024, 10000.0, 512, True),
}


def qwen_spec(size: str = "qwen2-7b", **overrides) -> ModelSpec:
    if size not in _FAMILY:
        raise ValueError(f"unknown qwen size {size!r}; choose from {sorted(_FAMILY)}")
    layers, d_model, heads, kv_heads, d_ff, vocab, theta, max_seq, tie = _FAMILY[size]
    base = dict(
        vocab_size=vocab,
        d_model=d_model,
        n_layers=layers,
        n_heads=heads,
        n_kv_heads=kv_heads,
        d_ff=d_ff,
        max_seq_len=max_seq,
        pos_emb="rope",
        norm="rmsnorm",
        mlp="swiglu",
        use_bias=False,
        qkv_bias=True,
        tie_embeddings=tie,
        rope_theta=theta,
        norm_eps=1e-6,
    )
    base.update(overrides)
    return ModelSpec(**base).validate()
