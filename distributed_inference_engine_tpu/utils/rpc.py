"""Shared framed-RPC plumbing: client class + server connection loop.

One implementation of connect/reconnect/locking/call for every framed-RPC
peer (worker client, coordinator client) — the reference had no client class
at all, and two hand-rolled copies would drift (they briefly did: one copy
lost the malformed-response guard; later the two hand-rolled *server* loops
drifted the same way, hence ``FramedServerMixin``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from .framing import FrameError, read_frame, write_frame

logger = logging.getLogger(__name__)


class RPCError(RuntimeError):
    """Peer-reported request failure (distinct from transport failure)."""


class FramedRPCClient:
    """Persistent framed-RPC connection: one in-flight call at a time,
    transparent reconnect after a drop, poisoned-connection teardown."""

    def __init__(self, host: str, port: int,
                 timeout: float = 30.0,
                 max_frame: int = 64 * 1024 * 1024) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._seq = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _ensure_connected(self) -> None:
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
            self._reader = self._writer = None

    async def call(self, method: str, *, timeout: Optional[float] = None,
                   **params: Any) -> Any:
        """Send one request frame, await one response frame.

        Raises ``RPCError`` when the peer reports failure; transport trouble
        (``OSError``/``asyncio.TimeoutError``/...) propagates for callers —
        router/LB — to turn into health signals.
        """
        self._seq += 1
        msg = {"method": method, "id": f"{id(self):x}-{self._seq}", **params}
        effective = timeout if timeout is not None else self.timeout
        async with self._lock:  # one in-flight call per connection
            # the timeout must bound the connect too — a blackholed host
            # otherwise hangs the OS TCP connect (~2 min) with the lock held
            await asyncio.wait_for(self._ensure_connected(), timeout=effective)
            assert self._reader is not None and self._writer is not None
            try:
                await write_frame(self._writer, msg)
                response = await read_frame(
                    self._reader, max_frame=self.max_frame, timeout=effective,
                )
            except Exception:
                await self.close()  # poisoned connection — drop it
                raise
        if not isinstance(response, dict):
            raise RPCError(f"malformed response: {response!r}")
        if not response.get("success"):
            raise RPCError(response.get("error", "unknown peer error"))
        return response.get("result")


class FramedServerMixin:
    """Framed-RPC server connection loop, shared by ``WorkerServer`` and
    ``CoordinatorServer``.

    Subclass contract: set ``self._methods`` (method name → async handler)
    and ``self._conn_writers`` (a set) before serving, expose
    ``self.max_frame_bytes``. Responses come back in frame order on one
    stream; concurrent clients use concurrent connections.

    Hooks (all optional overrides):
    - ``_run_handler(method, handler, msg)`` — server-side timeout policy.
    - ``_envelope_extra()`` — dict merged into every response envelope.
    - ``_timeout_error(method)`` — message for ``asyncio.TimeoutError``.
    - ``_on_handler_error(method, exc)`` — error accounting.
    - ``_after_dispatch(method, req_id, duration_s, response)`` — metrics.
    """

    _methods: Dict[str, Callable[[Dict[str, Any]], Awaitable[Any]]]
    _conn_writers: set
    max_frame_bytes: int = 64 * 1024 * 1024

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conn_writers.add(writer)
        try:
            while True:
                try:
                    msg = await read_frame(
                        reader, max_frame=self.max_frame_bytes, timeout=None
                    )
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break  # client closed
                except FrameError as e:
                    await write_frame(writer, {"success": False,
                                               "error": f"bad frame: {e}"})
                    break
                response = await self._dispatch(msg)
                await write_frame(writer, response)
        finally:
            self._conn_writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _dispatch(self, msg: Any) -> Dict[str, Any]:
        t0 = time.perf_counter()
        if not isinstance(msg, dict) or "method" not in msg:
            return {"success": False,
                    "error": "message must be a dict with 'method'"}
        method = msg["method"]
        handler = self._methods.get(method)
        req_id = msg.get("id", "")
        extra = self._envelope_extra()
        if handler is None:
            return {"id": req_id, "success": False, **extra,
                    "error": f"unknown method {method!r}"}
        try:
            result = await self._run_handler(method, handler, msg)
            response = {"id": req_id, "success": True, **extra,
                        "result": result}
        except asyncio.TimeoutError:
            response = {"id": req_id, "success": False, **extra,
                        "error": self._timeout_error(method)}
        except Exception as e:  # fan any handler error back, keep serving
            self._on_handler_error(method, e)
            logger.warning("%s: %s failed: %s",
                           type(self).__name__, method, e)
            response = {"id": req_id, "success": False, **extra,
                        "error": str(e)}
        self._after_dispatch(method, req_id, time.perf_counter() - t0,
                             response)
        return response

    async def _run_handler(self, method: str, handler, msg) -> Any:
        return await handler(msg)

    def _envelope_extra(self) -> Dict[str, Any]:
        return {}

    def _timeout_error(self, method: str) -> str:
        return f"{method} timed out"

    def _on_handler_error(self, method: str, exc: Exception) -> None:
        pass

    def _after_dispatch(self, method: str, req_id: str,
                        duration_s: float, response: Dict[str, Any]) -> None:
        pass

    def _close_all_connections(self) -> None:
        for w in list(self._conn_writers):
            w.close()
