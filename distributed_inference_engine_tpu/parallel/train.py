"""Sharded training step — exercises the full mesh (dp/tp/sp axes) end to end.

Serving is the product, but a training step is the strictest validation of
the sharding layer: it touches every parameter's forward AND backward
collectives plus an optimizer update. ``make_train_step`` jits the whole
thing with explicit in/out shardings so GSPMD places: batch over dp×sp,
params over tp, gradients reduced over dp automatically.

Also the entry point the driver's multichip dry-run compiles
(``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..models.base import ModelSpec, Params, causal_lm_loss, init_params
from .sharding import ModelShardings


def make_train_step(
    spec: ModelSpec,
    shardings: ModelShardings,
    learning_rate: float = 1e-3,
):
    """Returns (init_state, train_step) where train_step is jit'd over the
    mesh: state is (params, opt_state); batch is (tokens [B, T], seq_lens [B])."""
    tx = optax.adamw(learning_rate)

    def init_state(key: jax.Array) -> Tuple[Params, Any]:
        params = init_params(spec, key)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, shardings.params
        )
        opt_state = tx.init(params)
        # mu/nu inherit the param shardings via zeros_like; scalar leaves
        # (adam's step count) land uncommitted on one device — replicate
        # them so the whole state lives on the mesh's device set
        from jax.sharding import NamedSharding

        opt_state = jax.tree.map(
            lambda x: x if isinstance(getattr(x, "sharding", None),
                                      NamedSharding)
            else jax.device_put(x, shardings.replicated), opt_state)
        return params, opt_state

    def step(state, tokens, seq_lens):
        params, opt_state = state
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(spec, p, tokens, seq_lens)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    # donation requires out shardings to MATCH the donated inputs exactly;
    # leaving the state's out_shardings unpinned lets GSPMD re-shard e.g. a
    # replicated norm scale over tp, and the aliasing check then fails with
    # a size mismatch. Pin both sides to the live state's own shardings
    # (mu/nu mirror the params: optax builds them with zeros_like, which
    # preserves sharding) — resolved lazily at the first call so init_state
    # stays the single owner of placement.
    cache: dict = {}

    def train_step(state, tokens, seq_lens):
        fn = cache.get("fn")
        if fn is None:
            state_sh = jax.tree.map(lambda x: x.sharding, state)
            fn = jax.jit(
                step,
                in_shardings=(state_sh, shardings.batch,
                              shardings.replicated),
                out_shardings=(state_sh, shardings.replicated),
                donate_argnums=(0,),
            )
            cache["fn"] = fn
        return fn(state, tokens, seq_lens)

    return init_state, train_step
