"""Serve a real HF checkpoint directory end-to-end: config.json →
``spec_from_hf_config``, safetensors → ``load_checkpoint`` (optionally
quantized), vocab.json+merges.txt → ``BPETokenizer`` (byte-level
fallback when tokenizer files are absent), prompts → continuous engine
→ detokenized text.

This is the path a user with real weights runs; the environment this
repo is benchmarked in is zero-egress with no checkpoint on disk
(README "Real-checkpoint status"), so CI drives it with a synthetic
checkpoint (tests/test_serve_checkpoint.py) and the perf tables use
random-init (byte/FLOP counts are weight-value-independent).

    python examples/serve_checkpoint.py /path/to/ckpt "prompt text" \
        [--quant 4|8] [--max-new 64]
"""

import argparse
import os
import pathlib
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_engine(path: str, quant: int = 0, max_slots: int = 4,
                 max_seq_len: int = 0):
    """(engine, tokenizer, eos_ids) serving the checkpoint at ``path``;
    ``eos_ids`` comes from config.json's eos_token_id (possibly several —
    wire [0] into ``GenerationRequest.eos_id`` and the rest into
    ``stop_ids``, as main() does)."""
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )
    from distributed_inference_engine_tpu.models.loader import (
        load_checkpoint,
        spec_from_hf_config,
    )
    from distributed_inference_engine_tpu.ops.quant import quantize_params
    from distributed_inference_engine_tpu.utils.tokenizer import (
        BPETokenizer,
        build_tokenizer,
    )

    import json

    p = pathlib.Path(path)
    t0 = time.perf_counter()
    hf_cfg = json.loads((p / "config.json").read_text())   # parsed ONCE:
    spec = spec_from_hf_config(str(p), cfg=hf_cfg)         # spec + eos
    if max_seq_len:
        spec = spec.replace(max_seq_len=min(spec.max_seq_len, max_seq_len))
    params = load_checkpoint(str(p), spec)
    if quant:
        params = quantize_params(spec, params, bits=quant)
    log(f"loaded {spec.n_layers}L/{spec.d_model}d checkpoint"
        f"{f' (int{quant})' if quant else ''}: "
        f"{time.perf_counter() - t0:.1f}s")

    tok = build_tokenizer(str(p))       # BPE from vocab.json+merges.txt or
    if isinstance(tok, BPETokenizer):   # tokenizer.json; else byte-level
        log(f"BPE tokenizer: {tok.vocab_size} tokens "
            f"(native merge core: {tok.native_enabled})")
    else:
        log("no tokenizer files — byte-level fallback")

    seq_cap = min(spec.max_seq_len, 4096)
    cfg = EngineConfig(
        max_slots=max_slots, max_seq_len=seq_cap,
        prefill_buckets=[min(128, seq_cap), min(512, seq_cap)],
        page_size=min(128, seq_cap),
        num_pages=max(64, max_slots * (-(-seq_cap // min(128, seq_cap)))
                      + 8),
    )
    # eos: config.json's eos_token_id is authoritative (a list for
    # multi-eos checkpoints like Llama-3 — the engine takes one id; the
    # rest ride GenerationRequest.stop_ids in main())
    eos = hf_cfg.get("eos_token_id")
    eos_ids = ([] if eos is None
               else [eos] if isinstance(eos, int) else list(eos))
    return ContinuousEngine(spec, params=params, config=cfg), tok, eos_ids


def main() -> None:
    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )

    ap = argparse.ArgumentParser()
    ap.add_argument("path", help="HF checkpoint dir (config.json + "
                                 "*.safetensors [+ vocab.json/merges.txt])")
    ap.add_argument("prompts", nargs="+")
    ap.add_argument("--quant", type=int, default=0, choices=(0, 4, 8))
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0)
    args = ap.parse_args()

    engine, tok, eos_ids = build_engine(args.path, quant=args.quant)
    reqs = [
        GenerationRequest(prompt=tok.encode(p),
                          max_new_tokens=args.max_new,
                          temperature=args.temperature,
                          eos_id=eos_ids[0] if eos_ids else -1,
                          stop_ids=eos_ids[1:],
                          request_id=f"p{i}")
        for i, p in enumerate(args.prompts)
    ]
    t0 = time.perf_counter()
    results = engine.generate(reqs)
    wall = time.perf_counter() - t0
    total = sum(len(r.tokens) for r in results)
    for p, r in zip(args.prompts, results):
        print(f"--- {r.request_id} ({r.finish_reason}, "
              f"{len(r.tokens)} tokens)")
        print(p + tok.decode(r.tokens))
    log(f"{total} tokens in {wall:.2f}s ({total / wall:.1f} tok/s)")


if __name__ == "__main__":
    main()
