from .norms import layer_norm, rms_norm  # noqa: F401
from .rope import apply_rope, rope_freqs  # noqa: F401
from .attention import causal_attention, cached_attention  # noqa: F401
from .sampling import sample_tokens, SamplingParams  # noqa: F401
