from .kv_cache import SlotKVCache  # noqa: F401
from .engine import Engine, GenerationRequest, GenerationResult  # noqa: F401
