"""Deterministic fault injection for the framed-RPC plane.

A ``FaultPlan`` is a seeded, shareable decision oracle: every potential
injection point (client call, server dispatch) asks it whether to
inject, identified by ``(scope, site, verb)`` — e.g.
``("worker-2", "server", "generate")``. Decisions are a pure function of
``(seed, spec index, scope, site, verb, call ordinal)``, where the
ordinal is a per-key counter: the Nth ``generate`` dispatched to
``worker-2`` gets the same verdict on every run with the same seed,
regardless of how the event loop interleaves unrelated traffic. That
per-key (rather than global-RNG) construction is what makes a chaos run
reproducible under async scheduling jitter.

Every injection is appended to ``plan.log`` so a test can assert the
exact fault sequence (compare sorted — interleaving may reorder entries
across keys, never within one).

The fault menu (``FaultSpec.kind``):

- client site: ``connect_refused`` (call fails before any bytes move),
  ``slow`` (delay before the request frame), ``stall`` (request frame
  written, then the connection is torn mid-exchange).
- server site: ``slow`` (delay before dispatch), ``drop`` (request
  consumed, no response, connection closed), ``garble`` (response
  replaced by bytes that fail frame-magic validation).

Hooks live in ``utils/rpc.py`` behind a ``fault_plan`` attribute that
defaults to ``None`` — the production path pays one attribute load.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

CLIENT = "client"
SERVER = "server"

CLIENT_KINDS = ("connect_refused", "slow", "stall")
SERVER_KINDS = ("slow", "drop", "garble")


@dataclass
class FaultSpec:
    """One line of the fault menu.

    ``rate`` is the per-call injection probability; ``verbs`` / ``scopes``
    restrict matching (empty = match all; scopes match by substring so a
    spec can target ``"worker-2"`` or a ``host:port``). ``site`` must be
    ``"client"`` or ``"server"``. ``max_injections`` caps how many times
    the spec fires in total (0 = unlimited).
    """

    kind: str
    rate: float
    site: str = SERVER
    delay_s: float = 0.05
    verbs: Tuple[str, ...] = ()
    scopes: Tuple[str, ...] = ()
    max_injections: int = 0


@dataclass
class InjectedFault:
    scope: str
    site: str
    verb: str
    ordinal: int
    kind: str

    def key(self) -> Tuple[str, str, str, int, str]:
        return (self.scope, self.site, self.verb, self.ordinal, self.kind)


def _unit(seed: int, spec_idx: int, scope: str, site: str, verb: str,
          ordinal: int) -> float:
    """Deterministic U[0,1) from the full decision coordinates (sha256,
    not Python's salted hash)."""
    h = hashlib.sha256(
        f"{seed}|{spec_idx}|{scope}|{site}|{verb}|{ordinal}".encode()
    ).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


class FaultPlan:
    """Seeded injection oracle shared by every hook in one chaos run."""

    def __init__(self, seed: int = 0,
                 specs: Sequence[FaultSpec] = ()) -> None:
        self.seed = int(seed)
        self.specs: List[FaultSpec] = list(specs)
        self.log: List[InjectedFault] = []
        self._ordinals: Dict[Tuple[str, str, str], int] = {}
        self._fired: List[int] = [0] * len(self.specs)
        self._listeners: List = []

    def subscribe(self, fn) -> None:
        """Register ``fn(InjectedFault)`` to fire on every injection —
        the flight recorder's hook (workers filter by their own scope).
        Listeners must be cheap and must not raise (guarded anyway)."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def unsubscribe(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def draw(self, scope: str, site: str, verb: str) -> Optional[FaultSpec]:
        """Decide whether the call identified by (scope, site, verb) at
        its current per-key ordinal should fault. First matching spec
        wins. Returns the spec to apply, or None."""
        key = (scope, site, verb)
        n = self._ordinals.get(key, 0)
        self._ordinals[key] = n + 1
        for i, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            if spec.verbs and verb not in spec.verbs:
                continue
            if spec.scopes and not any(s in scope for s in spec.scopes):
                continue
            if spec.max_injections and self._fired[i] >= spec.max_injections:
                continue
            if _unit(self.seed, i, scope, site, verb, n) < spec.rate:
                self._fired[i] += 1
                fault = InjectedFault(scope, site, verb, n, spec.kind)
                self.log.append(fault)
                for fn in self._listeners:
                    try:
                        fn(fault)
                    except Exception:  # telemetry must not break injection
                        pass
                return spec
        return None

    def injected_count(self, scope: str = "") -> int:
        """Total injections, optionally filtered to one scope (exact)."""
        if not scope:
            return len(self.log)
        return sum(1 for e in self.log if e.scope == scope)

    def sequence(self) -> List[Tuple[str, str, str, int, str]]:
        """Order-independent canonical fault sequence for reproducibility
        assertions (sorted: async interleaving may reorder the log across
        keys, never within one)."""
        return sorted(e.key() for e in self.log)


def default_menu(rate: float = 0.05, delay_s: float = 0.02,
                 verbs: Tuple[str, ...] = ()) -> List[FaultSpec]:
    """The full menu at a uniform rate — what the chaos harness runs."""
    out = [FaultSpec(kind=k, rate=rate, site=CLIENT, delay_s=delay_s,
                     verbs=verbs) for k in CLIENT_KINDS]
    out += [FaultSpec(kind=k, rate=rate, site=SERVER, delay_s=delay_s,
                      verbs=verbs) for k in SERVER_KINDS]
    return out
