"""Platform pinning helper.

This environment's ``sitecustomize`` registers an experimental TPU-tunnel
plugin at interpreter startup and force-updates ``jax_platforms``, clobbering
the ``JAX_PLATFORMS`` env var — a process asking for CPU can still dial the
(possibly unreachable) tunnel and hang at first backend init. Every non-test
entry point (demos, CLIs) calls ``pin_platform_from_env()`` before touching
jax; ``tests/conftest.py`` and ``__graft_entry__.py`` carry their own copies
because they must run before this package imports.
"""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    """If JAX_PLATFORMS requests cpu, re-pin jax's config to cpu before any
    backend initialization. No-op otherwise."""
    want = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in want.split(","):
        import jax

        jax.config.update("jax_platforms", "cpu")
