"""Per-worker multi-model residency: background staging + probe-gated swap.

One fleet so far meant one model: every worker holds exactly the engines
``deploy_model`` pushed at it, and switching models costs a cold
``load_model`` (checkpoint read + prepare + warmup — minutes at real model
scale, ``load_sleep_s`` on the fake). PRESERVE's observation (PAPERS.md) is
that a serving worker has idle host resources while the accelerator decodes:
the NEXT model's weights can be read and prepared in that shadow, so a model
switch costs a pointer swap, not a cold start. The r13 artifact layer is the
substrate — a staged load is an artifact restore (``prepare_params`` already
skipped), and the same golden-token probe that gates artifact cold-starts
gates every swap here, so a wrong-numerics model never serves.

``ModelManager`` owns one worker's resident set:

- ``engines``/``configs`` — the resident models (the worker aliases these
  dicts, so its RPC surface — ``_engine_for``, drain, metrics — reads the
  same state).
- ``stage(cfg)`` — build the next model's engine on a daemon side thread
  while the current pumps keep dispatching. Staging never runs on the
  worker's engine executor (that would serialize behind — and ahead of —
  generates) and never inside a pump step: it only competes for host I/O
  and CPU, which is exactly the bubble the accelerator leaves. The serving
  pumps' step counters are snapshotted around the stage so the overlap is
  *accounted*, not assumed (``stage_overlap_steps``).
- ``swap(name)`` — wait for the stage, golden-gate the engine (artifact
  manifest probe when it has one, else a caller-supplied expected token
  list), then admit it under the residency budget. A probe mismatch
  discards the staged engine and raises ``ModelProbeError`` — the models
  already resident keep serving.
- LRU eviction — admission over ``max_resident_models``/``resident_bytes``
  evicts the least-recently-*used* idle model (``touch`` on every routed
  request keeps the order honest). A model with in-flight work is never
  evicted (``busy_fn``), and neither is the model just admitted.

The manager is engine-agnostic and jax-free at import (artifact helpers are
imported lazily), so the fleet tests drive it with fake engines.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional

from ..config import ModelConfig
from ..utils.tracing import LatencyStats

logger = logging.getLogger(__name__)


class ModelProbeError(RuntimeError):
    """A staged engine failed its golden-token gate — it was discarded and
    must not serve. The previously resident models are untouched."""


class ModelStageError(RuntimeError):
    """Staging failed (factory raised) or the model was never staged."""


class _Staged:
    """One in-flight background stage."""

    __slots__ = ("cfg", "thread", "done", "engine", "error", "stage_s",
                 "steps_at_start", "overlap_steps")

    def __init__(self, cfg: ModelConfig) -> None:
        self.cfg = cfg
        self.thread: Optional[threading.Thread] = None
        self.done = threading.Event()
        self.engine: Any = None
        self.error: Optional[BaseException] = None
        self.stage_s = 0.0
        self.steps_at_start = 0
        self.overlap_steps = 0


def engine_size_bytes(cfg: ModelConfig, engine: Any) -> int:
    """Byte estimate for one resident engine: ``metadata.size_bytes`` when
    the deploy declares it (the fake path), else the parameter tree's bytes,
    else 0 (unaccounted — only the count budget applies)."""
    declared = cfg.metadata.get("size_bytes")
    if declared:
        return int(declared)
    params = getattr(engine, "params", None)
    if params is None:
        return 0
    try:
        import jax

        return int(sum(x.nbytes for x in jax.tree.leaves(params)
                       if hasattr(x, "nbytes")))
    # graftlint: ok[swallowed-transport-error] local size introspection, no peer involved; 0 just means the byte budget cannot see this engine
    except Exception:
        return 0


class ModelManager:
    """Resident-model policy for one worker (see module docstring)."""

    def __init__(
        self,
        build: Callable[[ModelConfig], Any],
        *,
        max_resident_models: int = 0,
        resident_bytes: int = 0,
        busy_fn: Optional[Callable[[str], bool]] = None,
        on_evict: Optional[Callable[[str, Any], None]] = None,
    ) -> None:
        self.build = build
        self.max_resident_models = int(max_resident_models)
        self.resident_bytes = int(resident_bytes)
        self.busy_fn = busy_fn
        self.on_evict = on_evict
        self.engines: Dict[str, Any] = {}
        self.configs: Dict[str, ModelConfig] = {}
        self._lru: "OrderedDict[str, None]" = OrderedDict()
        self._bytes: Dict[str, int] = {}
        self._staged: Dict[str, _Staged] = {}
        self._lock = threading.Lock()
        self._stages_started = 0
        self._stages_completed = 0
        self._stages_failed = 0
        self._swaps = 0
        self._evictions = 0
        self._probe_rejects = 0
        self._stage_overlap_steps = 0
        self.stage_stats = LatencyStats()
        self.swap_stats = LatencyStats()

    # -- residency ---------------------------------------------------------

    def touch(self, name: str) -> None:
        """Mark one resident model as just-used (LRU order source)."""
        if name in self._lru:
            self._lru.move_to_end(name)

    def admit(self, cfg: ModelConfig, engine: Any) -> List[str]:
        """Install an engine into the resident set; evict over-budget idle
        models (LRU-first). Returns the evicted names. The newly admitted
        model is never an eviction candidate."""
        name = cfg.name
        self.engines[name] = engine
        self.configs[name] = cfg
        self._bytes[name] = engine_size_bytes(cfg, engine)
        self._lru[name] = None
        self._lru.move_to_end(name)
        return self._evict_over_budget(protect=name)

    def remove(self, name: str) -> Optional[Any]:
        """Drop one model from the resident set (explicit unload — not an
        eviction). Returns the engine, or None if absent."""
        self.configs.pop(name, None)
        self._lru.pop(name, None)
        self._bytes.pop(name, None)
        return self.engines.pop(name, None)

    def resident_bytes_used(self) -> int:
        return sum(self._bytes.values())

    def _over_budget(self) -> bool:
        if self.max_resident_models and len(self.engines) > self.max_resident_models:
            return True
        if self.resident_bytes and self.resident_bytes_used() > self.resident_bytes:
            return True
        return False

    def _evict_over_budget(self, protect: str) -> List[str]:
        evicted: List[str] = []
        while self._over_budget():
            victim = None
            for name in self._lru:            # LRU-first
                if name == protect:
                    continue
                if self.busy_fn is not None and self.busy_fn(name):
                    continue                  # in-flight work pins residency
                victim = name
                break
            if victim is None:
                # everything else is busy or protected: serving correctness
                # beats the budget — stay over and let the next admit retry
                logger.warning(
                    "resident budget exceeded but every candidate is busy "
                    "(%d models, %d bytes)", len(self.engines),
                    self.resident_bytes_used())
                break
            engine = self.remove(victim)
            self._evictions += 1
            evicted.append(victim)
            logger.info("evicted idle model %s (LRU, resident budget)",
                        victim)
            if self.on_evict is not None and engine is not None:
                self.on_evict(victim, engine)
        return evicted

    # -- background staging ------------------------------------------------

    def staged_names(self) -> List[str]:
        return sorted(self._staged)

    def stage(self, cfg: ModelConfig,
              serving_steps: Optional[Callable[[], int]] = None) -> _Staged:
        """Begin building ``cfg``'s engine on a side thread; returns the
        stage record immediately (idempotent per name while in flight).
        ``serving_steps`` is sampled at start and finish so the overlap
        with live dispatch is measured, not assumed."""
        name = cfg.name
        with self._lock:
            rec = self._staged.get(name)
            if rec is not None:
                return rec
            rec = _Staged(cfg)
            self._staged[name] = rec
            self._stages_started += 1
        if serving_steps is not None:
            rec.steps_at_start = int(serving_steps())

        def _run() -> None:
            t0 = time.perf_counter()
            try:
                rec.engine = self.build(cfg)
            except BaseException as e:      # surfaced at swap time
                rec.error = e
            rec.stage_s = time.perf_counter() - t0
            if serving_steps is not None:
                try:
                    rec.overlap_steps = int(serving_steps()) - rec.steps_at_start
                # graftlint: ok[swallowed-transport-error] local stats sampling, no peer involved; overlap accounting is best-effort
                except Exception:
                    rec.overlap_steps = 0
            rec.done.set()

        rec.thread = threading.Thread(
            target=_run, daemon=True, name=f"stage-{name}")
        rec.thread.start()
        return rec

    def stage_wait(self, name: str,
                   timeout: Optional[float] = None) -> _Staged:
        """Block until ``name``'s stage finishes; pops and returns the
        record. Raises ``ModelStageError`` when never staged / timed out /
        the factory failed."""
        rec = self._staged.get(name)
        if rec is None:
            raise ModelStageError(
                f"model {name!r} is not staged (staged: {self.staged_names()})")
        if not rec.done.wait(timeout):
            raise ModelStageError(
                f"stage of {name!r} still running after {timeout}s")
        with self._lock:
            self._staged.pop(name, None)
        self._stage_overlap_steps += rec.overlap_steps
        if rec.error is not None:
            self._stages_failed += 1
            raise ModelStageError(
                f"stage of {name!r} failed: {type(rec.error).__name__}: "
                f"{rec.error}") from rec.error
        self._stages_completed += 1
        self.stage_stats.add(rec.stage_s)
        return rec

    # -- probe-gated swap --------------------------------------------------

    def _golden_gate(self, engine: Any,
                     probe_expected: Optional[List[int]]) -> None:
        """The same trust boundary as an artifact cold-start: an engine
        with a manifest replays its recorded golden generation; otherwise a
        caller-supplied expected token list is replayed over the fixed
        ``GOLDEN_PROMPT``. No gate available ⇒ admit (matching
        ``load_model``, which has no probe either)."""
        from ..engine.artifact import (
            GOLDEN_PROMPT,
            ArtifactCorruptError,
            run_probe,
            verify_golden,
        )

        manifest = getattr(engine, "artifact_manifest", None)
        if manifest is not None:
            try:
                verify_golden(engine, manifest)
                return
            except ArtifactCorruptError as e:
                raise ModelProbeError(str(e)) from e
        if probe_expected:
            want = [int(t) for t in probe_expected]
            got = run_probe(engine, list(GOLDEN_PROMPT), len(want))
            if got != want:
                raise ModelProbeError(
                    f"swap probe FAILED: expected {want}, got {got} — "
                    "staged engine numerics are wrong, refusing to swap")

    def swap(self, name: str,
             probe_expected: Optional[List[int]] = None,
             timeout: Optional[float] = None) -> Dict[str, Any]:
        """Activate a staged model: wait for its build, golden-gate it,
        admit it under the budget. Returns a receipt dict with the
        measured ``stage_s`` (background, overlapped) and ``swap_s`` (what
        the caller actually waited — the number that must beat a cold
        ``load_model`` by ~the artifact-restore ratio). On probe failure
        the staged engine is discarded and the resident set is untouched."""
        t0 = time.perf_counter()
        if name in self.engines and name not in self._staged:
            self.touch(name)
            return {"swapped": name, "already_resident": True,
                    "stage_s": 0.0, "swap_s": 0.0, "evicted": []}
        rec = self.stage_wait(name, timeout=timeout)
        try:
            self._golden_gate(rec.engine, probe_expected)
        except ModelProbeError:
            self._probe_rejects += 1
            raise
        evicted = self.admit(rec.cfg, rec.engine)
        swap_s = time.perf_counter() - t0
        self._swaps += 1
        self.swap_stats.add(swap_s)
        logger.info(
            "swapped in model %s: stage %.3fs (background, %d steps "
            "overlapped), swap wait %.3fs, evicted %s", name, rec.stage_s,
            rec.overlap_steps, swap_s, evicted or "none")
        return {"swapped": name, "already_resident": False,
                "stage_s": rec.stage_s, "swap_s": swap_s,
                "overlap_steps": rec.overlap_steps, "evicted": evicted}

    # -- introspection -----------------------------------------------------

    def get_stats(self) -> Dict[str, Any]:
        return {
            "resident_models": len(self.engines),
            "resident_bytes": self.resident_bytes_used(),
            "staged_models": len(self._staged),
            "stage_started": self._stages_started,
            "stage_completed": self._stages_completed,
            "stage_failed": self._stages_failed,
            "model_swaps": self._swaps,
            "model_evictions": self._evictions,
            "swap_probe_rejects": self._probe_rejects,
            "stage_overlap_steps": self._stage_overlap_steps,
            "model_stage": self.stage_stats.snapshot(),
            "model_swap": self.swap_stats.snapshot(),
        }
