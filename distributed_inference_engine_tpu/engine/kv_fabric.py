"""Fleet-wide KV page fabric: wire format + import/export helpers.

The r7 host tier (``HostKVOffload``) already holds exact KV pages in host
RAM keyed by prefix-chain hash — but only worker-locally: an affinity
rebind after drain/failover lands on a cold worker and mid-stream failover
replays the whole prefix. This module extends those entries into a
checksummed WIRE FORMAT that rides the framed RPC plane, so hot prefixes
MIGRATE between workers instead of being recomputed (PRESERVE /
async-KV-prefetch, PAPERS.md).

Wire format (msgpack-native: str keys, ints, bytes — no pickling)::

    {version: 1, kind: "paged",
     page_size: P, dtype: "float32", layout: [L, P, fused],
     pages: [{hash: <16B chain hash>, k: <raw bytes>, v: <raw bytes>,
              checksum: blake2b(hash+k+v)}, ...],
     manifest: blake2b(hash_0+checksum_0+...)}

Commit/checksum protocol (r13 artifact discipline): every per-page
checksum AND the manifest are verified BEFORE any page is stored —
import is all-or-nothing, and a rejected import inserts NOTHING, so the
importer falls back to normal prefill rather than ever serving wrong KV.
The typed failure is ``FabricRejected``.

Pages land in the importer's HOST tier (``offload.put``), never directly
in the device pool: restage host→device rides the existing
prefetch-on-admit path (``prefetch_chain`` → staged per-layer
``device_put`` → ``alloc_slot_prefix`` host-hit → ``sync_tiers``
scatter), so an import is bit-identical to a local offload/upload cycle
and the r7 CPU-exact parity guarantees carry over unchanged.

The fake engine speaks a parallel ``kind: "fake"`` wire (page-aligned
prefix tokens + checksum) so fleet tests exercise the same RPC plane,
validation, and fallback semantics without jax pools.

Import-light on purpose (hashlib + numpy): the worker control plane loads
this module for the typed error even when no jax engine is present;
anything touching ``PagedKVCache`` imports jax lazily.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

WIRE_VERSION = 1
KIND_PAGED = "paged"
KIND_FAKE = "fake"


class FabricRejected(ValueError):
    """Typed import rejection: wrong version/kind, layout or dtype
    mismatch, or a checksum failure. Guarantees NOTHING was stored — the
    caller counts a fallback and serves via normal prefill."""


# ----------------------------------------------------------- checksums

def _page_checksum(h: bytes, k: bytes, v: bytes) -> bytes:
    d = hashlib.blake2b(digest_size=16)
    d.update(h)
    d.update(k)
    d.update(v)
    return d.digest()


def _manifest_checksum(pages: Sequence[Dict[str, Any]]) -> bytes:
    d = hashlib.blake2b(digest_size=16)
    for pg in pages:
        d.update(pg.get("hash", b""))
        d.update(pg.get("checksum", b""))
    return d.digest()


def token_checksum(tokens: Sequence[int]) -> bytes:
    return hashlib.blake2b(
        np.asarray(list(tokens), np.int64).tobytes(), digest_size=16
    ).digest()


def wire_nbytes(wire: Optional[Dict[str, Any]]) -> int:
    """Payload size for accounting (page bytes, not framing overhead)."""
    if not wire:
        return 0
    if wire.get("kind") == KIND_PAGED:
        return sum(len(pg.get("k", b"")) + len(pg.get("v", b""))
                   for pg in wire.get("pages", ()))
    return 8 * len(wire.get("tokens", ()))


# ------------------------------------------------------------ builders

def build_paged_wire(page_size: int, dtype: str,
                     layout: Sequence[int],
                     pages: Sequence[Tuple[bytes, np.ndarray, np.ndarray]],
                     ) -> Dict[str, Any]:
    """Serialize (hash, k, v) host pages — ``[L, page_size, fused]``
    each — into the checksummed wire dict."""
    out: List[Dict[str, Any]] = []
    for h, k_arr, v_arr in pages:
        k_b = np.ascontiguousarray(k_arr).tobytes()
        v_b = np.ascontiguousarray(v_arr).tobytes()
        out.append({"hash": bytes(h), "k": k_b, "v": v_b,
                    "checksum": _page_checksum(bytes(h), k_b, v_b)})
    return {
        "version": WIRE_VERSION,
        "kind": KIND_PAGED,
        "page_size": int(page_size),
        "dtype": str(dtype),
        "layout": [int(x) for x in layout],
        "pages": out,
        "manifest": _manifest_checksum(out),
    }


def build_fake_wire(tokens: Sequence[int], page_size: int) -> Dict[str, Any]:
    toks = [int(t) for t in tokens]
    return {
        "version": WIRE_VERSION,
        "kind": KIND_FAKE,
        "page_size": int(page_size),
        "tokens": toks,
        "checksum": token_checksum(toks),
    }


# ---------------------------------------------------------- validation

def _require(cond: bool, why: str) -> None:
    if not cond:
        raise FabricRejected(why)


def check_paged_wire(wire: Any, *, page_size: int, dtype: str,
                     layout: Sequence[int]) -> List[Dict[str, Any]]:
    """Validate a paged wire against the local pool's geometry and verify
    EVERY checksum; returns the page list. Raises ``FabricRejected``
    without side effects on any mismatch."""
    _require(isinstance(wire, dict), "wire is not a mapping")
    _require(wire.get("version") == WIRE_VERSION,
             f"wire version {wire.get('version')!r} != {WIRE_VERSION}")
    _require(wire.get("kind") == KIND_PAGED,
             f"wire kind {wire.get('kind')!r} != {KIND_PAGED!r}")
    _require(int(wire.get("page_size", -1)) == int(page_size),
             f"page_size {wire.get('page_size')!r} != local {page_size}")
    _require(str(wire.get("dtype")) == str(dtype),
             f"dtype {wire.get('dtype')!r} != local {dtype!r}")
    got_layout = [int(x) for x in wire.get("layout", ())]
    _require(got_layout == [int(x) for x in layout],
             f"layout {got_layout} != local {[int(x) for x in layout]}")
    pages = wire.get("pages")
    _require(isinstance(pages, (list, tuple)) and len(pages) > 0,
             "wire carries no pages")
    for i, pg in enumerate(pages):
        _require(isinstance(pg, dict), f"page {i} is not a mapping")
        h, k_b, v_b = pg.get("hash"), pg.get("k"), pg.get("v")
        _require(isinstance(h, bytes) and isinstance(k_b, bytes)
                 and isinstance(v_b, bytes), f"page {i} fields not bytes")
        _require(pg.get("checksum") == _page_checksum(h, k_b, v_b),
                 f"page {i} checksum mismatch")
    _require(wire.get("manifest") == _manifest_checksum(pages),
             "manifest checksum mismatch")
    return list(pages)


def check_fake_wire(wire: Any, *, page_size: int) -> List[int]:
    _require(isinstance(wire, dict), "wire is not a mapping")
    _require(wire.get("version") == WIRE_VERSION,
             f"wire version {wire.get('version')!r} != {WIRE_VERSION}")
    _require(wire.get("kind") == KIND_FAKE,
             f"wire kind {wire.get('kind')!r} != {KIND_FAKE!r}")
    _require(int(wire.get("page_size", -1)) == int(page_size),
             f"page_size {wire.get('page_size')!r} != local {page_size}")
    toks = wire.get("tokens")
    _require(isinstance(toks, (list, tuple)) and len(toks) > 0,
             "wire carries no tokens")
    toks = [int(t) for t in toks]
    _require(len(toks) % int(page_size) == 0,
             f"token count {len(toks)} not page-aligned to {page_size}")
    _require(wire.get("checksum") == token_checksum(toks),
             "token checksum mismatch")
    return toks


# -------------------------------------------- paged engine export/import

def export_paged_kv(kv, tokens: Sequence[int],
                    max_pages: int = 0) -> Optional[Dict[str, Any]]:
    """Export the longest resident full-page prefix of ``tokens`` from a
    ``PagedKVCache`` (device index, pending uploads, or host tier) as a
    wire dict; None when nothing is resident."""
    from .paged_kv import page_chain_hashes  # lazy: pulls jax

    toks = [int(t) for t in tokens]
    n_full = len(toks) // kv.page_size
    if max_pages > 0:
        n_full = min(n_full, int(max_pages))
    if n_full < 1:
        return None
    hashes = page_chain_hashes(toks, n_full, kv.page_size)
    pages = kv.export_prefix_pages(hashes)
    if not pages:
        return None
    n_layers, _, p, fused = kv.k_pages.shape
    return build_paged_wire(kv.page_size, str(kv.dtype),
                            (n_layers, p, fused), pages)


def import_paged_kv(kv, wire: Any) -> int:
    """Validate ``wire`` against the local pool and land its pages in the
    HOST tier. Returns how many pages were newly stored (already-resident
    pages are skipped — the local copy is authoritative). All checksums
    verify before the first ``put``; any failure raises ``FabricRejected``
    with nothing stored."""
    _require(kv.offload is not None,
             "importer has no host KV tier (kv_offload_bytes=0)")
    n_layers, _, p, fused = kv.k_pages.shape
    pages = check_paged_wire(wire, page_size=kv.page_size,
                             dtype=str(kv.dtype),
                             layout=(n_layers, p, fused))
    expect = n_layers * p * fused * kv.dtype.itemsize
    for i, pg in enumerate(pages):
        _require(len(pg["k"]) == expect and len(pg["v"]) == expect,
                 f"page {i} payload is {len(pg['k'])}+{len(pg['v'])} bytes, "
                 f"layout implies {expect}")
    stored = 0
    for pg in pages:
        h = pg["hash"]
        if kv.holds_prefix_page(h):
            continue
        k_arr = np.frombuffer(pg["k"], dtype=kv.dtype).reshape(
            n_layers, p, fused)
        v_arr = np.frombuffer(pg["v"], dtype=kv.dtype).reshape(
            n_layers, p, fused)
        if kv.offload.put(h, k_arr, v_arr):
            stored += 1
    return stored
