"""Overload handling (VERDICT r2 item 2): bounded admission queue, deadline
shedding, and the typed error surfaced through the pump.

The reference's only notions of bounding are a per-batch size cap
(``/root/reference/src/batcher.py:140-147``) and the LB's healthy-set filter
(``src/load_balancer.py:150-153``); nothing sheds load. Here the continuous
engine refuses submits past ``max_waiting`` (hard backpressure) and sheds
queued requests older than ``queue_deadline_s`` (the client has likely
timed out anyway), both as machine-readable ``overloaded`` outcomes.
"""

import asyncio
import time

import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.types import (
    EngineOverloadedError,
    GenerationRequest,
)
from distributed_inference_engine_tpu.models.base import ModelSpec
from distributed_inference_engine_tpu.serving.pump import EnginePump

SPEC = ModelSpec(
    vocab_size=256, d_model=256, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype="float32",
)


def _engine(**kw):
    base = dict(
        max_slots=2, max_seq_len=64, prefill_buckets=[16],
        page_size=16, num_pages=16, decode_steps_per_call=4,
        kv_dtype="float32",
    )
    base.update(kw)
    return ContinuousEngine(SPEC, config=EngineConfig(**base), seed=0)


def _req(i, max_new=8):
    return GenerationRequest(prompt=[1 + i, 2, 3], max_new_tokens=max_new,
                             request_id=f"o{i}")


def test_submit_raises_typed_error_at_queue_cap():
    eng = _engine(max_waiting=3)
    for i in range(3):
        eng.submit(_req(i))
    with pytest.raises(EngineOverloadedError) as ei:
        eng.submit(_req(99))
    assert ei.value.reason == "queue_full"
    assert getattr(ei.value, "rpc_error_kind") == "overloaded"
    m = eng.get_metrics()
    assert m["rejected_queue_full"] == 1
    # the queued three still complete: shedding refuses NEW work, it never
    # drops admitted work
    results = eng.run_until_idle()
    assert len(results) == 3
    assert all(r.finish_reason == "length" for r in results)


def test_deadline_shed_resolves_with_overloaded_outcome():
    eng = _engine(max_slots=1, queue_deadline_s=0.05)
    # slot-occupying long generation + two queued victims
    eng.submit(_req(0, max_new=16))
    eng.step()                               # admit into the only slot
    eng.submit(_req(1))
    eng.submit(_req(2))
    time.sleep(0.08)                         # both exceed the deadline
    eng.step()
    shed = [r for r in eng.drain_finished()
            if r.finish_reason == "overloaded"]
    assert {r.request_id for r in shed} == {"o1", "o2"}
    assert all(r.tokens == [] for r in shed)
    assert all(r.ttft_s >= 0.05 for r in shed)
    assert eng.get_metrics()["shed_deadline"] == 2
    # the running request is untouched
    rest = eng.run_until_idle()
    assert any(r.request_id == "o0" and len(r.tokens) == 16 for r in rest)


def test_no_shedding_by_default():
    eng = _engine()                          # caps off
    for i in range(8):
        eng.submit(_req(i))
    results = eng.run_until_idle()
    assert len(results) == 8
    assert all(r.finish_reason == "length" for r in results)
    m = eng.get_metrics()
    assert m["rejected_queue_full"] == 0 and m["shed_deadline"] == 0


def test_pump_batch_keeps_siblings_on_shed():
    """A shed inside a batch is a PER-REQUEST outcome: siblings' results
    survive (an exception would discard their completed generations and
    push callers into whole-batch retries that duplicate work)."""
    eng = _engine(max_slots=1, max_waiting=2)
    pump = EnginePump(eng, idle_wait_s=0.01)

    async def run():
        res = await pump.generate([_req(i, max_new=6) for i in range(6)])
        await pump.stop()
        return res

    results = asyncio.run(run())
    assert len(results) == 6
    by_reason = {}
    for r in results:
        by_reason.setdefault(r.finish_reason, []).append(r)
    assert by_reason.get("length"), "siblings must complete"
    shed = by_reason.get("overloaded", [])
    assert shed, "burst past cap must shed someone"
    assert all(r.tokens == [] for r in shed)
    assert all(r.metadata["overload_reason"] == "queue_full" for r in shed)
    # request ids are preserved on shed results (callers map outcomes back)
    assert all(r.request_id.startswith("o") for r in results)


def test_pump_streaming_raises_typed_error():
    """Single-request surface: generate_streaming converts the overloaded
    outcome into the typed error (no siblings to protect)."""
    eng = _engine(max_slots=1, max_waiting=1)
    pump = EnginePump(eng, idle_wait_s=0.01)

    async def run():
        outcomes = {}

        async def client(i):
            try:
                res = await pump.generate_streaming(_req(i, max_new=12),
                                                    lambda toks: None)
                outcomes[i] = res.finish_reason
            except EngineOverloadedError as e:
                outcomes[i] = f"overloaded:{e.reason}"

        await asyncio.gather(*(client(i) for i in range(5)))
        await pump.stop()
        return outcomes

    outcomes = asyncio.run(run())
    served = [k for k, v in outcomes.items() if v == "length"]
    rejected = [k for k, v in outcomes.items()
                if v == "overloaded:queue_full"]
    assert len(served) + len(rejected) == 5
    assert rejected, "burst past cap must reject someone"
    assert served, "shedding must not reject everyone"


def test_coordinator_overload_metric_exists():
    """The coordinator counts worker sheds apart from failures (an
    overloaded worker is not an unhealthy worker)."""
    from distributed_inference_engine_tpu.api.coordinator import (
        Coordinator,
        CoordinatorConfig,
    )

    coord = Coordinator(CoordinatorConfig())
    assert coord.get_stats()["overload_rejections"] == 0


def test_sync_generate_returns_per_request_shed_results():
    """The sync batch API never strands submitted requests: past-cap
    requests come back as overloaded results IN ORDER, the rest complete
    (r3 review finding: a mid-batch raise left the head of the batch
    queued with nobody collecting its results)."""
    eng = _engine(max_waiting=2)
    results = eng.generate([_req(i, max_new=4) for i in range(6)])
    assert len(results) == 6
    assert [r.request_id for r in results] == [f"o{i}" for i in range(6)]
    reasons = [r.finish_reason for r in results]
    assert reasons.count("length") >= 2
    assert reasons.count("overloaded") >= 1
    assert len(eng.run_until_idle()) == 0      # nothing stranded
