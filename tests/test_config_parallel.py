"""Config-driven parallel serving: ``ModelConfig.metadata`` tp/sp/dp builds
the mesh + shardings inside ``engine_from_config``, so tensor- and
sequence-parallel placement deploys through the same CLI / coordinator /
config-file path as everything else (the reference's registry records
placement but its engine can't act on it — SURVEY.md §2.3)."""

import jax
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import ModelConfig
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import engine_from_config


def _cfg(**meta):
    return ModelConfig(name="m", architecture="llama-tiny", dtype="float32",
                       max_batch_size=2, max_seq_len=128, metadata=meta)


def test_tp_metadata_builds_sharded_continuous_engine():
    eng = engine_from_config(_cfg(continuous=1, page_size=16, tp=4))
    wq = eng.params["blocks"]["wq"]
    assert "tp" in str(wq.sharding.spec)
    # page pools sharded too (per-chip KV HBM drops with tp)
    assert "tp" in str(eng.kv.k_pages.sharding.spec)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3, 4],
                                          max_new_tokens=6)])[0]
    assert len(out.tokens) == 6
    # parity with an unsharded engine on the same params is covered by
    # tests/test_parallel.py; here the contract is the CONFIG path works


def test_sp_metadata_builds_sp_prefill_static_engine():
    plain = engine_from_config(_cfg(prefill_buckets=[64]))
    sp = engine_from_config(_cfg(sp=4, dp=2, prefill_buckets=[64]))
    # same seed => same random init => token-identical greedy output
    req = lambda: GenerationRequest(prompt=list(range(1, 50)),
                                    max_new_tokens=8)
    assert plain.generate([req()])[0].tokens == sp.generate([req()])[0].tokens


def test_sp_prefill_pool_from_config():
    eng = engine_from_config(_cfg(role="prefill", sp=4,
                                  prefill_buckets=[64]))
    h = eng.prefill([GenerationRequest(prompt=list(range(1, 40)),
                                       max_new_tokens=4,
                                       request_id="r1")])[0]
    assert h.prompt_len == 39 and h.k.shape[1] == 39


def test_continuous_sp_prefill_matches_unsharded():
    """sp composes with the continuous engine (the last round-1 rejection
    closed): admission prefill runs sequence-parallel ring attention, the
    paged decode is unchanged — token parity with the unsharded engine."""
    plain = engine_from_config(_cfg(continuous=1, page_size=16,
                                    prefill_buckets=[64]))
    sp = engine_from_config(_cfg(continuous=1, page_size=16, sp=4, dp=2,
                                 prefill_buckets=[64]))
    req = lambda: GenerationRequest(prompt=list(range(1, 50)),
                                    max_new_tokens=8)
    assert sp.generate([req()])[0].tokens == plain.generate([req()])[0].tokens


def test_continuous_sp_plus_chunking_rejected():
    """prefill_chunk and sp both bound the admission stall; the suffix
    chunk programs are not sequence-parallel — explicit error, not silent
    wrong sharding."""
    with pytest.raises(ValueError, match="pick one"):
        engine_from_config(_cfg(continuous=1, sp=4, prefill_chunk=32,
                                prefill_buckets=[64]))


def _qcfg(**meta):
    cfg = _cfg(**meta)
    cfg.quantized = True
    return cfg


def test_quantized_tp_composes_and_matches_unsharded():
    """int8 composes with tp (VERDICT r1 item 3): the QuantizedTensor's int8
    payload shards exactly like the bf16 weight and the per-channel scale
    follows its output axes, so quantized tp=2 serving must be
    token-identical to quantized unsharded (same seed ⇒ same init ⇒ same
    quantization grid)."""
    from distributed_inference_engine_tpu.ops.quant import QuantizedTensor

    plain = engine_from_config(_qcfg(continuous=1, page_size=16))
    tp = engine_from_config(_qcfg(continuous=1, page_size=16, tp=2))
    wq = tp.params["blocks"]["wq"]
    assert isinstance(wq, QuantizedTensor)
    assert "tp" in str(wq.q.sharding.spec)
    # column-parallel scale keeps the output-channel split chip-local
    assert "tp" in str(wq.s.sharding.spec)
    wo = tp.params["blocks"]["wo"]
    # row-parallel wo contracts over its sharded dim: the scale is size-1
    # there and must drop the axis (replicate), not fail placement
    assert "tp" not in str(wo.s.sharding.spec)
    req = lambda: GenerationRequest(prompt=[1, 2, 3, 4], max_new_tokens=8)
    assert tp.generate([req()])[0].tokens == plain.generate([req()])[0].tokens


def test_quantized_sp_prefill_matches_unsharded():
    """int8 + sequence-parallel prefill: QuantizedTensor params flow through
    the GSPMD ring-attention prefill unchanged (they are pytrees in the
    blocks scan), so sp=4 must match unsharded greedy output."""
    plain = engine_from_config(_qcfg(prefill_buckets=[64]))
    sp = engine_from_config(_qcfg(sp=4, dp=2, prefill_buckets=[64]))
    req = lambda: GenerationRequest(prompt=list(range(1, 50)),
                                    max_new_tokens=8)
    assert plain.generate([req()])[0].tokens == sp.generate([req()])[0].tokens


def test_speculative_tp_composes_and_matches_unsharded():
    """Speculative composes with tp (VERDICT r1 missing #3): target params
    + dense KV shard over tp, the draft replicates. Greedy speculative
    output is the target's greedy chain, so tp=2 must match unsharded."""
    mk = lambda **extra: _cfg(speculative=2, draft_size="llama-tiny",
                              **extra)
    plain = engine_from_config(mk())
    tp = engine_from_config(mk(tp=2))
    assert "tp" in str(tp.params["blocks"]["wq"].sharding.spec)
    req = lambda: GenerationRequest(prompt=[1, 2, 3, 4], max_new_tokens=8)
    assert tp.generate([req()])[0].tokens == plain.generate([req()])[0].tokens


def test_speculative_sp_rejected():
    with pytest.raises(ValueError, match="tp only"):
        engine_from_config(_cfg(sp=4, speculative=2,
                                draft_size="llama-tiny"))


def test_too_many_devices_requested():
    with pytest.raises(ValueError, match="devices"):
        engine_from_config(_cfg(tp=64))


def test_dp_without_sp_rejected():
    """dp shards nothing in the tp-only serving path — accepting it would
    silently waste half the slice."""
    with pytest.raises(ValueError, match="load balancer"):
        engine_from_config(_cfg(continuous=1, dp=2, tp=4))


def test_native_checkpoint_restores_directly_into_mesh_layout(tmp_path):
    """With tp metadata, a native checkpoint restores straight into the
    sharded layout (loading the whole tree onto one device first would
    peak at full-model bytes on a single chip)."""
    import jax

    from distributed_inference_engine_tpu.models.base import init_params
    from distributed_inference_engine_tpu.models.llama import llama_spec
    from distributed_inference_engine_tpu.utils.checkpoint import save_params

    spec = llama_spec("llama-tiny", max_seq_len=128).replace(dtype="float32")
    params = init_params(spec, jax.random.key(7))
    save_params(str(tmp_path / "ck"), spec, params)

    # the RESTORE itself must place shards on the mesh (item= without
    # restore_args silently materialises everything on one device, and the
    # engine's later shard_fn would mask that regression)
    from distributed_inference_engine_tpu.config import MeshConfig
    from distributed_inference_engine_tpu.parallel.mesh import make_mesh
    from distributed_inference_engine_tpu.parallel.sharding import (
        ModelShardings,
    )
    from distributed_inference_engine_tpu.utils.checkpoint import load_params

    mesh = make_mesh(MeshConfig(tp=4), devices=jax.devices()[:4])
    shardings = ModelShardings.build(spec, mesh)
    abstract = jax.eval_shape(lambda: init_params(spec, jax.random.key(0)))
    template = jax.tree.map(
        lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
        abstract, shardings.params)
    restored = load_params(str(tmp_path / "ck"), template=template)
    assert "tp" in str(restored["blocks"]["wq"].sharding.spec), \
        "restore must honor template shardings, not re-place afterwards"

    cfg = ModelConfig(name="m", architecture="llama-tiny", dtype="float32",
                      path=str(tmp_path / "ck"), max_batch_size=2,
                      max_seq_len=128,
                      metadata={"continuous": 1, "page_size": 16, "tp": 4})
    eng = engine_from_config(cfg)
    wq = eng.params["blocks"]["wq"]
    assert "tp" in str(wq.sharding.spec)
    np.testing.assert_allclose(np.asarray(wq), np.asarray(params["blocks"]["wq"]),
                               rtol=1e-6)
    # and parity: same checkpoint without mesh generates identical greedy
    plain = engine_from_config(ModelConfig(
        name="p", architecture="llama-tiny", dtype="float32",
        path=str(tmp_path / "ck"), max_batch_size=2, max_seq_len=128,
        metadata={"continuous": 1, "page_size": 16}))
    req = lambda: GenerationRequest(prompt=[1, 2, 3, 4], max_new_tokens=8)
    assert eng.generate([req()])[0].tokens == plain.generate([req()])[0].tokens
