"""Coordinator front-end: framed-RPC server + user-facing client.

The network face of the coordinator — what the reference's README calls "the
central API server" (``README.md:56-60``) and its ``examples/example_client.py``
(declared at ``README.md:40``, never written) would have talked to. Speaks the
same length-prefixed frame protocol as the workers (``utils/framing.py``), so
one wire format covers client→coordinator and coordinator→worker hops.

Methods: ``generate`` (token-space; batching/caching/routing applied),
``deploy_model``, ``add_worker`` / ``remove_worker``, ``stats``, ``models``,
``ping``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ..config import ModelConfig, ServerConfig
from ..obs.registry import OPENMETRICS_CONTENT_TYPE
from ..utils.rpc import FramedRPCClient, FramedServerMixin, relay_stream
from .coordinator import Coordinator

logger = logging.getLogger(__name__)


class CoordinatorServer(FramedServerMixin):
    """Serves a ``Coordinator`` over framed RPC (connection loop + dispatch
    envelope shared with ``WorkerServer`` via ``FramedServerMixin``)."""

    def __init__(self, coordinator: Coordinator,
                 config: Optional[ServerConfig] = None) -> None:
        self.coordinator = coordinator
        self.config = config or ServerConfig(worker_id="coordinator")
        self._server: Optional[asyncio.base_events.Server] = None
        self._conn_writers: set = set()
        self._methods: Dict[str, Callable[[Dict[str, Any]], Awaitable[Any]]] = {
            "ping": self._rpc_ping,
            "generate": self._rpc_generate,
            "deploy_model": self._rpc_deploy_model,
            "add_worker": self._rpc_add_worker,
            "remove_worker": self._rpc_remove_worker,
            "stats": self._rpc_stats,
            "models": self._rpc_models,
            "metrics_text": self._rpc_metrics_text,
            "trace": self._rpc_trace,
        }
        self._stream_methods = {
            "generate_stream": self._rpc_generate_stream,
        }

    @property
    def address(self) -> Tuple[str, int]:
        if self._server is None:
            raise RuntimeError("server not started")
        host, port = self._server.sockets[0].getsockname()[:2]
        return host, port

    async def start(self) -> Tuple[str, int]:
        await self.coordinator.start()
        self._server = await asyncio.start_server(
            self._handle_connection, self.config.host, self.config.port
        )
        host, port = self.address
        logger.info("coordinator listening on %s:%d", host, port)
        return host, port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            self._close_all_connections()  # see WorkerServer.stop
            await self._server.wait_closed()
            self._server = None
        await self.coordinator.stop()

    @property
    def max_frame_bytes(self) -> int:
        return self.config.max_frame_bytes

    # -- methods ------------------------------------------------------------

    async def _rpc_ping(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"time": time.time(), "role": "coordinator"}

    async def _rpc_generate(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return await self.coordinator.submit(
            model=msg["model"],
            prompt=msg.get("prompt"),
            text=msg.get("text"),
            version=msg.get("version", "1.0"),
            max_new_tokens=int(msg.get("max_new_tokens", 16)),
            temperature=float(msg.get("temperature", 0.0)),
            top_k=int(msg.get("top_k", 0)),
            top_p=float(msg.get("top_p", 1.0)),
            min_p=float(msg.get("min_p", 0.0)),
            eos_id=int(msg.get("eos_id", -1)),
            stop_ids=msg.get("stop_ids"),
            stop_sequences=msg.get("stop_sequences"),
            key=msg.get("key"),
            request_id=msg.get("request_id"),
            no_cache=bool(msg.get("no_cache", False)),
        )

    async def _rpc_generate_stream(self, msg: Dict[str, Any], send
                                   ) -> Dict[str, Any]:
        """End-to-end streaming: worker token chunks relay through the
        coordinator to the client connection."""
        queue: asyncio.Queue = asyncio.Queue()
        fut = asyncio.ensure_future(self.coordinator.submit_stream(
            model=msg["model"],
            prompt=msg.get("prompt"),
            text=msg.get("text"),
            on_tokens=queue.put_nowait,
            version=msg.get("version", "1.0"),
            max_new_tokens=int(msg.get("max_new_tokens", 16)),
            temperature=float(msg.get("temperature", 0.0)),
            top_k=int(msg.get("top_k", 0)),
            top_p=float(msg.get("top_p", 1.0)),
            min_p=float(msg.get("min_p", 0.0)),
            eos_id=int(msg.get("eos_id", -1)),
            stop_ids=msg.get("stop_ids"),
            stop_sequences=msg.get("stop_sequences"),
            key=msg.get("key"),
            request_id=msg.get("request_id"),
        ))
        return await relay_stream(fut, queue, send)

    async def _rpc_deploy_model(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        cfg = ModelConfig.from_dict(msg["config"])
        n = await self.coordinator.deploy_model(
            cfg, worker_ids=msg.get("workers") or None
        )
        return {"model": cfg.name, "shards": n}

    async def _rpc_add_worker(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self.coordinator.add_worker(msg["worker_id"], msg["host"],
                                    int(msg["port"]))
        return {"added": msg["worker_id"]}

    async def _rpc_remove_worker(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"removed": self.coordinator.remove_worker(msg["worker_id"])}

    async def _rpc_stats(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return self.coordinator.get_stats()

    async def _rpc_models(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        reg = self.coordinator.registry
        return {"models": {name: reg.list_versions(name)
                           for name in reg.list_models()}}

    async def _rpc_metrics_text(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        text = await self.coordinator.metrics_text(
            refresh_workers=bool(msg.get("refresh_workers", True)))
        return {"content_type": OPENMETRICS_CONTENT_TYPE, "text": text}

    async def _rpc_trace(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        return {"trace": self.coordinator.get_trace(str(msg["request_id"]))}

    async def _http_get(self, path: str):
        """Plain-HTTP escape hatch on the RPC port (utils/rpc.py protocol
        sniff): ``GET /metrics`` serves the fleet-wide OpenMetrics text so
        a stock Prometheus can scrape the coordinator directly."""
        if path == "/metrics":
            text = await self.coordinator.metrics_text()
            return (OPENMETRICS_CONTENT_TYPE, text.encode("utf-8"))
        return None


class CoordinatorClient(FramedRPCClient):
    """User-facing client (the README's promised ``example_client``,
    ``README.md:40``) — persistent connection, one call per frame pair
    (shared plumbing in ``utils/rpc.py``)."""

    def __init__(self, host: str, port: int, timeout: float = 120.0) -> None:
        super().__init__(host, port, timeout=timeout)

    async def generate(self, model: str, prompt: Optional[List[int]] = None,
                       **kwargs: Any) -> Dict[str, Any]:
        """Token-space (``prompt=[ids]``) or text-space (``text="..."``,
        coordinator tokenizes and the result carries ``"text"``)."""
        return await self.call(
            "generate", model=model,
            prompt=list(prompt) if prompt is not None else None, **kwargs)

    async def generate_stream(self, model: str, on_tokens,
                              prompt: Optional[List[int]] = None,
                              **kwargs: Any) -> Dict[str, Any]:
        """Streaming generate: ``on_tokens(tokens)`` fires per decoded
        chunk end-to-end (worker → coordinator → here); returns the final
        result dict."""
        return await self.call_stream(
            "generate_stream",
            lambda frame: on_tokens(list(frame.get("tokens", []))),
            model=model,
            prompt=list(prompt) if prompt is not None else None, **kwargs)

    async def deploy_model(self, cfg: ModelConfig,
                           workers: Optional[List[str]] = None,
                           timeout: float = 600.0) -> Dict[str, Any]:
        return await self.call("deploy_model", config=cfg.to_dict(),
                               workers=workers, timeout=timeout)

    async def add_worker(self, worker_id: str, host: str, port: int) -> None:
        await self.call("add_worker", worker_id=worker_id, host=host, port=port)

    async def stats(self) -> Dict[str, Any]:
        return await self.call("stats")

    async def metrics_text(self, refresh_workers: bool = True) -> str:
        """The coordinator's fleet-wide OpenMetrics exposition text."""
        result = await self.call("metrics_text",
                                 refresh_workers=refresh_workers)
        return str(result["text"])

    async def get_trace(self, request_id: str) -> Optional[Dict[str, Any]]:
        """Per-phase trace of a recent request (coordinator + worker spans),
        or ``None`` if the coordinator has aged it out."""
        result = await self.call("trace", request_id=request_id)
        return result.get("trace")

    async def ping(self) -> Dict[str, Any]:
        return await self.call("ping", timeout=5.0)
