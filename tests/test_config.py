"""Config tree + file loader tests (the config file the reference README
promised at ``README.md:39`` but never shipped)."""

import json

from distributed_inference_engine_tpu.config import (
    Config,
    MeshConfig,
    ModelConfig,
    config_from_dict,
    load_config,
)


def test_model_config_round_trip():
    mc = ModelConfig(name="llama3-8b", architecture="llama", max_seq_len=8192)
    d = mc.to_dict()
    mc2 = ModelConfig.from_dict(d)
    assert mc2 == mc


def test_from_dict_ignores_unknown_fields():
    mc = ModelConfig.from_dict({"name": "m", "totally_new_field": 1})
    assert mc.name == "m"


def test_mesh_config():
    m = MeshConfig(dp=2, tp=4)
    assert m.n_devices == 8
    assert m.axis_sizes() == {"dp": 2, "pp": 1, "sp": 1, "tp": 4, "ep": 1}


def test_config_from_dict_sections():
    cfg = config_from_dict(
        {
            "models": [{"name": "m", "architecture": "gpt2"}],
            "mesh": {"tp": 8},
            "batcher": {"max_batch_size": 16},
            "cache": {"policy": "lfu", "max_size": 99},
            "health": {"max_consecutive_failures": 5},
            "server": {"port": 9999},
        }
    )
    assert cfg.models[0].architecture == "gpt2"
    assert cfg.mesh.tp == 8 and cfg.mesh.dp == 1
    assert cfg.batcher.max_batch_size == 16
    assert cfg.cache.policy == "lfu"
    assert cfg.health.max_consecutive_failures == 5
    assert cfg.server.port == 9999


def test_load_json_and_yaml_and_toml(tmp_path):
    data = {"mesh": {"tp": 2, "dp": 4}, "models": [{"name": "x"}]}
    jp = tmp_path / "c.json"
    jp.write_text(json.dumps(data))
    cfg = load_config(str(jp))
    assert cfg.mesh.tp == 2 and cfg.mesh.n_devices == 8
    assert cfg.models[0].name == "x"

    yp = tmp_path / "c.yaml"
    yp.write_text("mesh:\n  tp: 4\nengine:\n  max_slots: 32\n")
    cfg = load_config(str(yp))
    assert cfg.mesh.tp == 4 and cfg.engine.max_slots == 32

    tp = tmp_path / "c.toml"
    tp.write_text("[mesh]\ntp = 8\n\n[batcher]\nmax_latency_ms = 5.0\n")
    cfg = load_config(str(tp))
    assert cfg.mesh.tp == 8 and cfg.batcher.max_latency_ms == 5.0


def test_default_config_is_valid():
    cfg = Config()
    d = cfg.to_dict()
    assert "engine" in d and "mesh" in d


def test_multihost_config_section(tmp_path):
    """The multihost section round-trips through the config-file loader
    (pod-slice deployments drive workers from files, not flags)."""
    import json

    from distributed_inference_engine_tpu.config import load_config

    p = tmp_path / "w.json"
    p.write_text(json.dumps({
        "server": {"worker_id": "h0", "port": 9000},
        "multihost": {"enabled": True,
                      "coordinator_address": "10.0.0.1:8476",
                      "num_processes": 4, "process_id": 2},
    }))
    cfg = load_config(str(p))
    assert cfg.multihost.enabled is True
    assert cfg.multihost.coordinator_address == "10.0.0.1:8476"
    assert cfg.multihost.num_processes == 4
    assert cfg.multihost.process_id == 2
    # defaults when absent
    p2 = tmp_path / "w2.json"
    p2.write_text(json.dumps({"server": {"worker_id": "h1"}}))
    assert load_config(str(p2)).multihost.enabled is False
