"""Disaggregated prefill/decode tests (engine/disagg.py; SURVEY.md §2.3 last
row — the reference *declared* disaggregated inference,
``/root/reference/README.md:15,96-98``, with no code behind it).

Correctness bar: a disaggregated pair must produce token-for-token the same
greedy output as a unified engine with the same weights — the handoff carries
exact KV state, not an approximation."""

import numpy as np
import pytest

from distributed_inference_engine_tpu.api import Coordinator, CoordinatorConfig
from distributed_inference_engine_tpu.config import (
    BatcherConfig,
    EngineConfig,
    HealthConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.cluster.worker import (
    DECODE_PEER_UNREACHABLE,
    WorkerClient,
    WorkerRPCError,
    WorkerServer,
)
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.disagg import (
    PrefillEngine,
    PrefillHandoff,
    handoff_from_wire,
    handoff_to_wire,
)
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=128)


def _cfg(**over):
    base = dict(max_slots=4, max_seq_len=128, page_size=16, num_pages=64,
                decode_steps_per_call=4, attention_impl="xla")
    base.update(over)
    return EngineConfig(**base)


def _reqs():
    return [
        GenerationRequest(prompt=[1, 2, 3, 4, 5], max_new_tokens=8,
                          temperature=0.0, request_id="a"),
        GenerationRequest(prompt=[7, 8, 9], max_new_tokens=6,
                          temperature=0.0, request_id="b"),
    ]


# ---------------------------------------------------------------- wire form


def test_handoff_wire_roundtrip_bf16():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    k = rng.randn(4, 5, 4, 64).astype("float32").astype(jnp.bfloat16)
    v = rng.randn(4, 5, 4, 64).astype("float32").astype(jnp.bfloat16)
    h = PrefillHandoff(request_id="r1", prompt_len=5, first_token=42,
                       k=k, v=v)
    wire = handoff_to_wire(h)
    assert isinstance(wire["k"], bytes)
    back = handoff_from_wire(wire)
    assert back.request_id == "r1" and back.first_token == 42
    assert back.k.dtype == k.dtype and back.k.shape == k.shape
    np.testing.assert_array_equal(np.asarray(back.k, dtype="float32"),
                                  np.asarray(k, dtype="float32"))
    np.testing.assert_array_equal(np.asarray(back.v, dtype="float32"),
                                  np.asarray(v, dtype="float32"))


# ------------------------------------------------------------- engine level


def test_disagg_matches_unified_greedy():
    import jax

    from distributed_inference_engine_tpu.models.base import init_params

    params = init_params(SPEC, jax.random.key(0))
    unified = ContinuousEngine(SPEC, params=params, config=_cfg())
    base = {r.request_id: r.tokens for r in unified.generate(_reqs())}

    pe = PrefillEngine(SPEC, params=params, config=_cfg())
    handoffs = pe.prefill(_reqs())
    # through the wire, as the RPC plane would carry it
    handoffs = [handoff_from_wire(handoff_to_wire(h)) for h in handoffs]
    de = ContinuousEngine(SPEC, params=params, config=_cfg())
    for r, h in zip(_reqs(), handoffs):
        de.submit_prefilled(r, h)
    out = {r.request_id: r.tokens for r in de.run_until_idle()}
    assert out == base
    assert pe.get_metrics()["total_handoff_bytes"] > 0


def test_submit_prefilled_validates_shapes():
    de = ContinuousEngine(SPEC, config=_cfg())
    bad = PrefillHandoff(request_id="x", prompt_len=3, first_token=1,
                         k=np.zeros((2, 3, 4, 64), "float32"),
                         v=np.zeros((2, 3, 4, 64), "float32"))
    with pytest.raises(ValueError):
        de.submit_prefilled(
            GenerationRequest(prompt=[1, 2, 3], max_new_tokens=2), bad)
    # prompt_len / T mismatch
    bad2 = PrefillHandoff(
        request_id="x", prompt_len=5, first_token=1,
        k=np.zeros((SPEC.n_layers, 3, SPEC.n_kv_heads, SPEC.head_dim),
                   "float32"),
        v=np.zeros((SPEC.n_layers, 3, SPEC.n_kv_heads, SPEC.head_dim),
                   "float32"))
    with pytest.raises(ValueError):
        de.submit_prefilled(
            GenerationRequest(prompt=[1, 2, 3], max_new_tokens=2), bad2)


# ---------------------------------------------------------------- RPC level


def _model_cfg(role=None, continuous=False, name="m"):
    meta = {"size": "llama-tiny", "page_size": 16, "num_pages": 64,
            "attention_impl": "xla", "kv_dtype": "float32",
            "decode_steps_per_call": 4}
    if role:
        meta["role"] = role
    if continuous:
        meta["continuous"] = 1
    return ModelConfig(name=name, architecture="llama", dtype="float32",
                       max_seq_len=64, max_batch_size=4, metadata=meta)


@pytest.mark.asyncio
async def test_worker_rpc_prefill_then_decode():
    """prefill on one worker, generate_prefilled on another — results match
    a unified continuous worker with the same (seed-0) weights."""
    wp = WorkerServer(ServerConfig(worker_id="wp", port=0))
    wd = WorkerServer(ServerConfig(worker_id="wd", port=0))
    wu = WorkerServer(ServerConfig(worker_id="wu", port=0))
    await wp.start()
    await wd.start()
    await wu.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        await wd.load_model_async(_model_cfg(continuous=True))
        await wu.load_model_async(_model_cfg(continuous=True))

        cp = WorkerClient(*wp.address, timeout=120.0)
        cd = WorkerClient(*wd.address, timeout=120.0)
        cu = WorkerClient(*wu.address, timeout=120.0)

        base = await cu.generate("m", _reqs())
        handoffs = await cp.prefill("m", _reqs())
        out = await cd.generate_prefilled("m", _reqs(), handoffs)
        assert {r.request_id: r.tokens for r in out} == \
            {r.request_id: r.tokens for r in base}

        # role errors are informative
        with pytest.raises(WorkerRPCError, match="does not support"):
            await cd.prefill("m", _reqs())
        with pytest.raises(WorkerRPCError, match="does not support"):
            await cp.generate("m", _reqs())
        await cp.close()
        await cd.close()
        await cu.close()
    finally:
        await wp.stop()
        await wd.stop()
        await wu.stop()


@pytest.mark.asyncio
async def test_worker_rpc_prefill_generate_relay():
    """The single-KV-hop path: coordinator-side caller talks only to the
    prefill worker; KV goes prefill → decode peer directly."""
    wp = WorkerServer(ServerConfig(worker_id="wp", port=0))
    wd = WorkerServer(ServerConfig(worker_id="wd", port=0))
    await wp.start()
    await wd.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        await wd.load_model_async(_model_cfg(continuous=True))
        cp = WorkerClient(*wp.address, timeout=120.0)
        dhost, dport = wd.address
        out = await cp.prefill_generate("m", _reqs(), dhost, dport,
                                        timeout=120.0)
        assert sorted(r.request_id for r in out) == ["a", "b"]
        for r in out:
            assert len(r.tokens) >= 1
        # decode-side engine actually did the decoding
        dm = wd.get_metrics()["models"]["m"]
        assert dm["total_requests"] == 2
        assert dm["total_generated_tokens"] > 0
        # prefill-side engine never decoded
        pm = wp.get_metrics()["models"]["m"]
        assert pm["role"] == "prefill"
        await cp.close()
    finally:
        await wp.stop()
        await wd.stop()


# ------------------------------------------------------------- coordinator


@pytest.mark.asyncio
async def test_coordinator_disaggregated_end_to_end():
    coord = Coordinator(CoordinatorConfig(
        batcher=BatcherConfig(max_batch_size=4, max_latency_ms=10.0),
        health=HealthConfig(check_interval=0.2, check_timeout=1.0,
                            max_consecutive_failures=2),
    ))
    await coord.start()
    workers = []
    try:
        for i in range(4):
            w = WorkerServer(ServerConfig(worker_id=f"w{i}", port=0))
            host, port = await w.start()
            workers.append(w)
            coord.add_worker(f"w{i}", host, port)
        np_, nd = await coord.deploy_model_disaggregated(
            _model_cfg(), ["w0", "w1"], ["w2", "w3"])
        assert (np_, nd) == (2, 2)

        outs = [await coord.submit("m", prompt=[1, 2, 3, 4 + i],
                                   max_new_tokens=5, key=f"k{i}")
                for i in range(4)]
        for out in outs:
            assert len(out["tokens"]) == 5
            assert out["metadata"]["prefill_worker"] in ("w0", "w1")
            assert out["metadata"]["decode_worker"] in ("w2", "w3")
        # both prefill workers rotated
        used_prefill = {o["metadata"]["prefill_worker"] for o in outs}
        assert used_prefill == {"w0", "w1"}
        stats = coord.get_stats()
        assert stats["disaggregated"]["m"]["decode"] == ["w2", "w3"]

        # pool validation
        with pytest.raises(ValueError, match="both pools"):
            await coord.deploy_model_disaggregated(_model_cfg(name="x"),
                                                   [], ["w2"])
        with pytest.raises(ValueError, match="both pools"):
            await coord.deploy_model_disaggregated(_model_cfg(name="x"),
                                                   ["w0"], [])
        with pytest.raises(ValueError, match="overlap|both pools|in both"):
            await coord.deploy_model_disaggregated(_model_cfg(name="x"),
                                                   ["w0"], ["w0"])
    finally:
        await coord.stop()
        for w in workers:
            await w.stop()


@pytest.mark.asyncio
async def test_relay_packs_handoffs_across_frames():
    """Handoffs bigger than one frame must split into several
    generate_prefilled calls, not die on the frame limit (review finding:
    a long prompt's oversize frame was misread as a dead decode peer)."""
    # budget = max(max_frame - 1MiB, max_frame/2); the two llama-tiny
    # handoffs are ~24KB and ~16KB, so a 30KB budget (60KB frames) forces
    # one call per request
    wp = WorkerServer(ServerConfig(worker_id="wp", port=0,
                                   max_frame_bytes=60_000))
    wd = WorkerServer(ServerConfig(worker_id="wd", port=0))
    wu = WorkerServer(ServerConfig(worker_id="wu", port=0))
    await wp.start()
    await wd.start()
    await wu.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        await wd.load_model_async(_model_cfg(continuous=True))
        await wu.load_model_async(_model_cfg(continuous=True))
        cp = WorkerClient(*wp.address, timeout=120.0)
        cu = WorkerClient(*wu.address, timeout=120.0)
        base = await cu.generate("m", _reqs())
        out = await cp.prefill_generate("m", _reqs(), *wd.address,
                                        timeout=120.0)
        assert {r.request_id: r.tokens for r in out} == \
            {r.request_id: r.tokens for r in base}
        # one relay arrived as TWO generate_prefilled calls on the peer
        assert wd._request_count == 2
        await cp.close()
        await cu.close()
    finally:
        await wp.stop()
        await wd.stop()
        await wu.stop()


@pytest.mark.asyncio
async def test_relay_oversize_single_handoff_is_config_error():
    """A single handoff that can't fit any frame is an application error
    naming the knob — NOT a decode-peer failure that dents health."""
    wp = WorkerServer(ServerConfig(worker_id="wp", port=0,
                                   max_frame_bytes=20_000))
    wd = WorkerServer(ServerConfig(worker_id="wd", port=0))
    await wp.start()
    await wd.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        await wd.load_model_async(_model_cfg(continuous=True))
        cp = WorkerClient(*wp.address, timeout=120.0)
        with pytest.raises(WorkerRPCError, match="max_frame_bytes") as ei:
            await cp.prefill_generate("m", _reqs(), *wd.address,
                                      timeout=60.0)
        assert ei.value.kind != DECODE_PEER_UNREACHABLE
        await cp.close()
    finally:
        await wp.stop()
        await wd.stop()


@pytest.mark.asyncio
async def test_load_model_feature_superset_is_directional():
    """A continuous preload accepts a plain (static) deploy — superset —
    but a static preload rejects a continuous (decode-pool) deploy."""
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg(continuous=True))
        # plain deploy needs only {generate}: idempotent accept
        await w.load_model_async(_model_cfg(continuous=False))
        assert "m" in w.engines
    finally:
        await w.stop()

    w2 = WorkerServer(ServerConfig(worker_id="w2", port=0))
    await w2.start()
    try:
        await w2.load_model_async(_model_cfg(continuous=False))
        with pytest.raises(ValueError, match="unload it first"):
            await w2.load_model_async(_model_cfg(continuous=True))
    finally:
        await w2.stop()


@pytest.mark.asyncio
async def test_decode_peer_down_reports_error_kind():
    """A dead decode peer must surface as a machine-readable error kind,
    not an anonymous app error (review finding: the coordinator could not
    distinguish decode-peer-down from a bad request)."""
    wp = WorkerServer(ServerConfig(worker_id="wp", port=0))
    await wp.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        cp = WorkerClient(*wp.address, timeout=60.0)
        with pytest.raises(WorkerRPCError) as ei:
            await cp.prefill_generate("m", _reqs(), "127.0.0.1", 1,
                                      timeout=30.0)
        assert ei.value.kind == DECODE_PEER_UNREACHABLE
        await cp.close()
    finally:
        await wp.stop()


@pytest.mark.asyncio
async def test_load_model_role_mismatch_rejected():
    """Same model identity but a different capability (prefill vs generate)
    must error, not pass the idempotency check (review finding: a
    wrong-role preload blackholed the pool)."""
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg(role="prefill"))
        with pytest.raises(ValueError, match="unload it first"):
            await w.load_model_async(_model_cfg(continuous=True))
    finally:
        await w.stop()


@pytest.mark.asyncio
async def test_coordinator_disagg_decode_failover():
    """Killing a decode worker mid-deployment: the relay reports the peer
    down, the coordinator marks the DECODE worker and retries on the
    surviving decode shard."""
    coord = Coordinator(CoordinatorConfig(
        batcher=BatcherConfig(max_batch_size=2, max_latency_ms=5.0),
        health=HealthConfig(check_interval=30.0, check_timeout=0.5,
                            max_consecutive_failures=1),
    ))
    await coord.start()
    workers = []
    try:
        for i in range(3):
            w = WorkerServer(ServerConfig(worker_id=f"w{i}", port=0))
            host, port = await w.start()
            workers.append(w)
            coord.add_worker(f"w{i}", host, port)
        await coord.deploy_model_disaggregated(
            _model_cfg(), ["w0"], ["w1", "w2"])
        await workers[1].stop()   # kill decode worker w1

        # every request completes on the surviving decode shard, whatever
        # shard its key hashes to (health.check_interval is long: only the
        # error-kind path can mask the dead worker this fast)
        for i in range(4):
            out = await coord.submit("m", prompt=[1, 2, 3 + i],
                                     max_new_tokens=3, key=f"k{i}",
                                     no_cache=True)
            assert len(out["tokens"]) == 3
            assert out["metadata"]["decode_worker"] == "w2"
    finally:
        await coord.stop()
        for w in (workers[0], workers[2]):
            await w.stop()


@pytest.mark.asyncio
async def test_coordinator_disagg_prefill_failover():
    """Killing one prefill worker reroutes new requests to the survivor
    (prefill is stateless — SURVEY.md §7 hard-part #5 doesn't bite here)."""
    coord = Coordinator(CoordinatorConfig(
        batcher=BatcherConfig(max_batch_size=2, max_latency_ms=5.0),
        health=HealthConfig(check_interval=0.2, check_timeout=0.5,
                            max_consecutive_failures=1),
    ))
    await coord.start()
    workers = []
    try:
        for i in range(3):
            w = WorkerServer(ServerConfig(worker_id=f"w{i}", port=0))
            host, port = await w.start()
            workers.append(w)
            coord.add_worker(f"w{i}", host, port)
        await coord.deploy_model_disaggregated(
            _model_cfg(), ["w0", "w1"], ["w2"])
        out = await coord.submit("m", prompt=[1, 2, 3], max_new_tokens=3,
                                 key="warm")
        assert len(out["tokens"]) == 3

        await workers[0].stop()   # kill prefill worker w0
        # the retry path masks the dead worker immediately; every request
        # still completes
        for i in range(3):
            out = await coord.submit("m", prompt=[2, 3, 4 + i],
                                     max_new_tokens=3, key=f"f{i}",
                                     no_cache=True)
            assert len(out["tokens"]) == 3
            assert out["metadata"]["prefill_worker"] == "w1"
    finally:
        await coord.stop()
        for w in workers[1:]:
            await w.stop()


# ----------------------------------------------------- prefix-aware handoff


def test_probe_and_trim_handoff_roundtrip():
    """probe_prefix counts indexed leading pages; trim_handoff drops the
    cached head and the wire form round-trips kv_start."""
    from distributed_inference_engine_tpu.engine.disagg import trim_handoff
    from distributed_inference_engine_tpu.engine.paged_kv import (
        page_chain_hashes,
    )

    rng = np.random.RandomState(1)
    k = rng.randn(2, 40, 4, 64).astype("float32")
    v = rng.randn(2, 40, 4, 64).astype("float32")
    h = PrefillHandoff(request_id="t", prompt_len=40, first_token=5,
                       k=k, v=v)
    t = trim_handoff(h, 32)                 # 2 cached pages of 16
    assert t.kv_start == 32 and t.k.shape[1] == 8
    back = handoff_from_wire(handoff_to_wire(t))
    assert back.kv_start == 32 and back.k.shape[1] == 8
    np.testing.assert_array_equal(back.k, k[:, 32:])
    with pytest.raises(ValueError):
        trim_handoff(h, 40)                 # must leave >= 1 position
    with pytest.raises(ValueError):
        trim_handoff(t, 8)                  # already trimmed
    # hash helper parity with the in-cache hashing
    de = ContinuousEngine(SPEC, config=_cfg())
    toks = list(range(1, 40))
    hs = page_chain_hashes(toks, 2, de.kv.page_size)
    assert de.kv.probe_prefix(hs) == 0      # nothing registered yet


def test_delta_handoff_reuses_cached_prefix_and_matches_full():
    """Second handoff of a shared-prefix prompt ships only the tail: the
    decode engine reuses its registered prefix pages, and greedy output
    is identical to the full-handoff path."""
    import jax

    from distributed_inference_engine_tpu.engine.disagg import trim_handoff
    from distributed_inference_engine_tpu.models.base import init_params

    params = init_params(SPEC, jax.random.key(0))
    # shared 32-token head (2 pages of 16), distinct tails
    head = list(range(1, 33))
    r1 = GenerationRequest(prompt=head + [40, 41, 42], max_new_tokens=6,
                          temperature=0.0, request_id="full")
    r2 = GenerationRequest(prompt=head + [50, 51], max_new_tokens=6,
                          temperature=0.0, request_id="delta")
    pe = PrefillEngine(SPEC, params=params, config=_cfg())
    de = ContinuousEngine(SPEC, params=params, config=_cfg())
    ref = ContinuousEngine(SPEC, params=params, config=_cfg())

    h1, h2 = pe.prefill([r1, r2])
    de.submit_prefilled(r1, h1)             # full handoff registers prefix
    de.run_until_idle()
    cached = de.kv.probe_prefix(
        de.kv._page_hashes(r2.prompt, 2))
    assert cached == 2                      # both head pages indexed
    h2_delta = handoff_from_wire(handoff_to_wire(trim_handoff(h2, 32)))
    de.submit_prefilled(
        GenerationRequest(prompt=r2.prompt, max_new_tokens=6,
                          temperature=0.0, request_id="delta"), h2_delta)
    out = {r.request_id: r.tokens for r in de.run_until_idle()}
    base = {r.request_id: r.tokens
            for r in ref.generate([
                GenerationRequest(prompt=r2.prompt, max_new_tokens=6,
                                  temperature=0.0, request_id="delta")])}
    assert out["delta"] == base["delta"]
    assert de.get_metrics()["kv"]["prefix_hit_tokens"] >= 32


def test_stale_delta_handoff_resolves_typed_outcome():
    """A delta handoff against an engine whose cache lacks the prefix
    resolves as finish_reason=stale_prefix (sender re-ships full KV)."""
    import jax

    from distributed_inference_engine_tpu.engine.disagg import trim_handoff
    from distributed_inference_engine_tpu.models.base import init_params

    params = init_params(SPEC, jax.random.key(0))
    req = GenerationRequest(prompt=list(range(1, 40)), max_new_tokens=4,
                            temperature=0.0, request_id="s")
    pe = PrefillEngine(SPEC, params=params, config=_cfg())
    de = ContinuousEngine(SPEC, params=params, config=_cfg())
    (h,) = pe.prefill([req])
    de.submit_prefilled(req, trim_handoff(h, 16))
    (res,) = de.run_until_idle()
    assert res.finish_reason == "stale_prefix"
    assert res.tokens == [] and res.metadata["kv_start"] == 16
    # full re-ship then succeeds
    de.submit_prefilled(
        GenerationRequest(prompt=req.prompt, max_new_tokens=4,
                          temperature=0.0, request_id="s2"), h)
    (res2,) = de.run_until_idle()
    assert res2.finish_reason in ("length", "stop") and len(res2.tokens) == 4


@pytest.mark.asyncio
async def test_relay_ships_delta_on_repeat_and_recovers_from_stale():
    """End-to-end over the RPC plane: the relay probes the decode pool,
    ships delta handoffs for repeated prompts, and the decode engine's
    prefix-hit counters tick; trimmed-vs-full results stay identical."""
    wp = WorkerServer(ServerConfig(worker_id="wp2", port=0))
    wd = WorkerServer(ServerConfig(worker_id="wd2", port=0))
    await wp.start()
    await wd.start()
    try:
        await wp.load_model_async(_model_cfg(role="prefill"))
        await wd.load_model_async(_model_cfg(continuous=True))
        cp = WorkerClient(*wp.address, timeout=120.0)
        dh, dp = wd.address

        first = await cp.prefill_generate("m", _reqs(), decode_host=dh,
                                          decode_port=dp)
        again = await cp.prefill_generate("m", _reqs(), decode_host=dh,
                                          decode_port=dp)
        assert {r.request_id: r.tokens for r in first} == \
            {r.request_id: r.tokens for r in again}
        m = wd.engines["m"].get_metrics()
        # prompts are 5 and 3 tokens with page_size 16 — no full page, so
        # force a page-crossing prompt for the hit
        long_req = [GenerationRequest(prompt=list(range(1, 40)),
                                      max_new_tokens=4, temperature=0.0,
                                      request_id="lp")]
        b0 = wp.get_metrics()["handoff_bytes_shipped"]
        await cp.prefill_generate("m", long_req, decode_host=dh,
                                  decode_port=dp)
        b1 = wp.get_metrics()["handoff_bytes_shipped"]
        r2 = await cp.prefill_generate(
            "m", [GenerationRequest(prompt=list(range(1, 40)),
                                    max_new_tokens=4, temperature=0.0,
                                    request_id="lp2")],
            decode_host=dh, decode_port=dp)
        b2 = wp.get_metrics()["handoff_bytes_shipped"]
        assert len(r2) == 1 and len(r2[0].tokens) == 4
        m = wd.engines["m"].get_metrics()
        assert m["kv"]["prefix_hit_tokens"] >= 32
        # the repeat must ship a DELTA on the wire, not just hit the
        # decode-side prefix counters at admission: 39-token prompt with
        # 2 full cached pages of 16 → tail of 7 tokens ≈ 7/39 the bytes
        # (catches the probe silently disabling itself — r4 review)
        assert 0 < b2 - b1 < (b1 - b0) / 2, (
            f"repeat shipped {b2 - b1} bytes vs first {b1 - b0} — "
            "delta handoff did not engage")
        await cp.close()
    finally:
        await wp.stop()
        await wd.stop()
