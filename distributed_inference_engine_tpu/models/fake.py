"""Fake engine: the real ``Engine`` interface with injectable latency/errors.

Capability heir of the reference's test strategy (SURVEY.md §4): ``FakeModel``
(configurable latency, metric tracking — ``src/mock_models/fake_model.py:11-83``)
and ``mock_batch_inference`` (injectable ``error_rate``/``latency_ms`` —
``src/mock_models/mock_inference.py:31-53``). Every orchestration layer
(worker, batcher, router, coordinator) is tested on CPU against this class, so
their tests never need a TPU or a multi-second jit compile.

Semantics: "generation" echoes the prompt reversed, token by token, up to
``max_new_tokens`` — deterministic, order-sensitive, and cheap, so tests can
assert exact outputs AND detect batch-order mix-ups (an echo that ignored
order couldn't).
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict, List, Optional

from ..engine.types import GenerationRequest, GenerationResult
from ..utils.tracing import LatencyStats


class FakeEngine:
    """Drop-in for ``engine.Engine`` with simulated latency and failures."""

    def __init__(
        self,
        latency_s: float = 0.0,
        per_token_latency_s: float = 0.0,
        error_rate: float = 0.0,
        seed: int = 0,
    ) -> None:
        self.latency_s = latency_s
        self.per_token_latency_s = per_token_latency_s
        self.error_rate = error_rate
        self._rand = random.Random(seed)
        self.prefill_stats = LatencyStats()
        self.decode_stats = LatencyStats()
        self._total_requests = 0
        self._total_generated_tokens = 0
        self._total_errors = 0

    def generate(self, requests: List[GenerationRequest]) -> List[GenerationResult]:
        self._total_requests += len(requests)
        t0 = time.perf_counter()
        if self.error_rate and self._rand.random() < self.error_rate:
            self._total_errors += 1
            raise RuntimeError("injected fake-engine failure")
        n_tokens = sum(min(len(r.prompt), r.max_new_tokens) for r in requests)
        delay = self.latency_s + self.per_token_latency_s * n_tokens
        if delay:
            time.sleep(delay)
        results = []
        for i, r in enumerate(requests):
            toks = list(reversed(r.prompt))[: r.max_new_tokens]
            self._total_generated_tokens += len(toks)
            results.append(
                GenerationResult(
                    request_id=r.request_id or f"fake-{self._total_requests}-{i}",
                    tokens=toks,
                    finish_reason="length",
                    prompt_tokens=len(r.prompt),
                    ttft_s=delay,
                    decode_s=0.0,
                    metadata={"fake": True},
                )
            )
        self.prefill_stats.add(time.perf_counter() - t0)
        return results

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "total_requests": self._total_requests,
            "total_prompt_tokens": 0,
            "total_generated_tokens": self._total_generated_tokens,
            "total_errors": self._total_errors,
            "prefill": self.prefill_stats.snapshot(),
            "decode": self.decode_stats.snapshot(),
            "spec": {"fake": True},
        }
