"""Mosaic (Pallas-TPU) matmul with in-register int4 unpack.

Closes the one SURVEY §2.2 "Pallas where XLA is insufficient" obligation
left open in round 3: packed-int4 weights through XLA's einsum decode at
1,584 tok/s vs int8's 3,661 at the 8B bs64 rung, because XLA materializes
the unpacked int8 operand in HBM — the decode step then streams the 2-byte
traffic AND the packed read. This kernel keeps the weight packed in HBM
and VMEM and unpacks nibbles in registers on the way into the MXU feed, so
HBM sees only the 0.5-byte/weight stream. (The reference has no analogue:
its "model" is an asyncio sleep, ``src/mock_models/fake_model.py:47``.)

Layout contract (``ops.quant.quantize_weight``): a ``[K, N]`` weight packs
SPLIT-HALF along the contraction axis into ``[K/2, N]`` int8 — source row
``k < K/2`` in the low nibble of byte row ``k``, row ``K/2 + k`` in the
high nibble. The matmul then decomposes into two contiguous-slice dots,

    y = x[:, :K/2] @ lo(P) + x[:, K/2:] @ hi(P),    P = packed bytes

with no stride-2 gather anywhere (an interleaved layout would need one on
either the activations or the unpacked weight — both Mosaic-hostile).

Grid: ``(M/bm, N/bn, K2/bk)``, k innermost ("arbitrary"), accumulating in
a VMEM f32 scratch; weight blocks stream exactly once per (m, n) tile, so
a bs64 decode step streams each weight byte exactly once. Nibble unpack is
3 VPU int32 ops + 2 converts per byte, overlapped with the MXU by Mosaic's
usual software pipeline.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# kernel dispatch mode (read at TRACE time):
#   auto      — use the kernel on a single-device TPU process (the bench /
#               single-chip serving deploys); XLA einsum path elsewhere.
#               Multi-device processes keep the XLA path because a
#               pallas_call is an opaque unit to GSPMD — tp-sharded int4
#               weights would force a gather.
#   on        — always (interpreted off-TPU: CPU tests of the kernel math)
#   off       — never
_MODE = os.environ.get("INT4_MATMUL_KERNEL", "auto")


def set_kernel_mode(mode: str) -> None:
    """"auto" | "on" | "off" — see module docstring."""
    global _MODE
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"bad int4 kernel mode {mode!r}")
    _MODE = mode


def _block_of(size: int, candidates: Tuple[int, ...]) -> Optional[int]:
    for b in candidates:
        if size % b == 0:
            return b
    return None


def kernel_wants(pattern: str, x, w) -> bool:
    """True when the Mosaic kernel should take this einsum: mode allows
    it, the weight is an unstacked ``[K/2, N]`` payload contracted on its
    packed axis, and the shapes tile cleanly (K/2 and N divisible by the
    block candidates). Everything else falls back to the XLA path."""
    if _MODE == "off":
        return False
    if _MODE == "auto" and not (jax.default_backend() == "tpu"
                                and len(jax.devices()) == 1):
        return False
    if w.q.ndim != 2 or w.pack_axis % w.q.ndim != 0:
        return False                    # payload must be packed on axis 0
    lhs, out = pattern.split("->")
    xs, ws = lhs.split(",")
    if len(ws) != 2 or not xs.endswith(ws[0]) or ws[0] in out \
            or ws[1] not in out:
        return False     # contraction must be x's LAST axis and w's axis 0
    if not out.endswith(ws[1]) or xs.replace(ws[0], "") + ws[1] != out:
        return False                    # out = x batch dims + N
    k2, n = w.q.shape
    return (_block_of(k2, _K_BLOCKS) is not None
            and _block_of(n, _N_BLOCKS) is not None)


# preference order measured on v5e at the 8B decode shape ([64,4096] @
# [4096,14336]): bk1024/bn2048 runs 24.9 us/iter vs 82.5 at bk512/bn512 —
# bigger blocks amortize the per-block VPU unpack + loop overhead; the
# unpack STYLE (int32 shifts vs xor-bias) measured within noise of itself.
# int8-typed shifts don't compile on this Mosaic — keep the int32 widen.
_K_BLOCKS = (1024, 512, 256, 128)
_N_BLOCKS = (2048, 1024, 512, 256, 128)


def _kernel(xlo_ref, xhi_ref, p_ref, s_ref, o_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # sign-extend both nibbles in int32 registers; int4 values are exact
    # in bf16, so the MXU sees ordinary bf16 operands
    p = p_ref[...].astype(jnp.int32)
    lo = jax.lax.shift_right_arithmetic(jax.lax.shift_left(p, 28), 28)
    hi = jax.lax.shift_right_arithmetic(p, 4)
    dt = xlo_ref.dtype
    acc_ref[...] += (
        jnp.dot(xlo_ref[...], lo.astype(dt),
                preferred_element_type=jnp.float32)
        + jnp.dot(xhi_ref[...], hi.astype(dt),
                  preferred_element_type=jnp.float32))

    @pl.when(k == pl.num_programs(2) - 1)
    def _emit():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _int4_matmul_2d(x, packed, scale, *, interpret: bool = False):
    """``[M, K] @ unpack([K/2, N]) * scale -> [M, N]`` (dtype of x)."""
    m, kdim = x.shape
    k2, n = packed.shape
    if kdim != 2 * k2:
        raise ValueError(f"x K={kdim} vs packed K/2={k2}")
    bk = _block_of(k2, _K_BLOCKS)
    bn = _block_of(n, _N_BLOCKS)
    if bk is None or bn is None:
        raise ValueError(f"untileable shapes K/2={k2} N={n}")
    # activations tile at (16, 128) for bf16 — pad M up, slice back after.
    # bm tops out at 128 to keep the f32 accumulator block ≤1 MB alongside
    # the 2 MB double-buffered weight blocks (VMEM is ~16 MB)
    bm = _block_of(m, (128, 64, 32, 16))
    if bm is None:
        bm = min(-(-m // 16) * 16, 128)
        x = jnp.pad(x, ((0, -m % bm), (0, 0)))
    mp = x.shape[0]

    grid = (mp // bm, n // bn, k2 // bk)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),      # x low half
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),      # x high half
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),      # packed W
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),       # out scale
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            # the int32 nibble-widening temporaries ([bk, bn] lo+hi) top
            # 16 MB at the prefill tile (bm=128, bn=2048) — past the
            # default scoped-vmem limit but well inside v5e's 128 MB
            # physical VMEM (measured: compiles + runs at 64 MB)
            vmem_limit_bytes=64 * 1024 * 1024),
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * n * kdim,
            bytes_accessed=(k2 * n) + 2 * mp * kdim * (n // bn)
                           + mp * n * x.dtype.itemsize,
            transcendentals=0),
        interpret=interpret,
    )(x[:, :k2], x[:, k2:], packed, scale.reshape(1, n))
    return out[:m] if mp != m else out


def int4_einsum_kernel(pattern: str, x, w):
    """``matmul_any``'s kernel path: flatten x's batch dims to M, run the
    2-D kernel, restore. ``kernel_wants(pattern, x, w)`` must hold."""
    k2, n = w.q.shape
    lead = x.shape[:-1]
    xm = x.reshape(-1, x.shape[-1])
    y = _int4_matmul_2d(xm, w.q, w.s.astype(jnp.float32),
                        interpret=jax.default_backend() != "tpu")
    return y.reshape(lead + (n,))
