"""Shared framed-RPC plumbing: client class + server connection loop.

One implementation of connect/reconnect/locking/call for every framed-RPC
peer (worker client, coordinator client) — the reference had no client class
at all, and two hand-rolled copies would drift (they briefly did: one copy
lost the malformed-response guard; later the two hand-rolled *server* loops
drifted the same way, hence ``FramedServerMixin``).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from .framing import (
    HEADER_SIZE,
    FrameError,
    read_frame,
    read_frame_after_header,
    write_frame,
)

logger = logging.getLogger(__name__)

# a framed message starts with magic 0xD17E — never printable ASCII — so a
# connection whose first four bytes spell an HTTP verb is unambiguously a
# plain HTTP client (curl/Prometheus hitting GET /metrics on the RPC port)
_HTTP_VERB_PREFIXES = (b"GET ", b"HEAD", b"POST", b"PUT ", b"DELE",
                       b"OPTI", b"PATC")


class RPCError(RuntimeError):
    """Peer-reported request failure (distinct from transport failure).

    ``kind`` carries the peer's machine-readable error class (the
    envelope's ``error_kind``, from the handler exception's
    ``rpc_error_kind`` attribute) so callers can react to specific
    failures — e.g. a relay's unreachable decode peer — without sniffing
    error text. ``detail`` is the optional machine-readable sub-reason
    (envelope ``error_detail``, from ``rpc_error_detail``) — e.g. an
    overloaded worker's "queue_full" vs "deadline".
    """

    def __init__(self, message: str, kind: str = "",
                 detail: str = "") -> None:
        super().__init__(message)
        self.kind = kind
        self.detail = detail


class FramedRPCClient:
    """Pooled framed-RPC client: concurrent calls each ride their own
    connection (bounded by ``max_connections``), with transparent reconnect
    after a drop and poisoned-connection teardown.

    One frame in flight per connection keeps request/response matching
    trivial (the server answers in frame order per stream); concurrency
    comes from the pool, so N coordinator dispatch groups to one worker —
    or N relays holding a decode peer for a whole generation — overlap
    instead of serializing behind a single socket lock.
    """

    # optional chaos injection oracle (utils/faults.FaultPlan); None in
    # production — the hot path pays one attribute load
    fault_plan = None

    def __init__(self, host: str, port: int,
                 timeout: float = 30.0,
                 max_frame: int = 64 * 1024 * 1024,
                 max_connections: int = 8) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        self.max_frame = max_frame
        self.max_connections = max(1, max_connections)
        # idle connections ready for reuse; _total counts idle + in-use
        self._free: list = []   # [(reader, writer)]
        self._total = 0
        self._inuse: set = set()  # (reader, writer) with a call in flight
        self._cond = asyncio.Condition()
        self._seq = 0
        self._closed = False
        # asyncio keeps only weak refs to tasks: retain notify tasks here
        # or they can be garbage-collected before the waiter is woken
        self._bg_tasks: set = set()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def _acquire(
        self, timeout: float
    ) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
        async def _get() -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
            async with self._cond:
                while True:
                    while self._free:
                        reader, writer = self._free.pop()
                        if writer.is_closing():    # died while idle
                            self._total -= 1
                            continue
                        return reader, writer
                    if self._total < self.max_connections:
                        self._total += 1  # reserve before the await below
                        break
                    await self._cond.wait()
            try:
                return await asyncio.open_connection(self.host, self.port)
            except BaseException:
                async with self._cond:
                    self._total -= 1
                    self._cond.notify()
                raise

        # the timeout must bound the connect/wait too — a blackholed host
        # otherwise hangs the OS TCP connect (~2 min)
        return await asyncio.wait_for(_get(), timeout=timeout)

    def _release_nowait(self, conn) -> None:
        """Synchronous re-pool: no ``await`` means no suspension point at
        which a cancelled caller could leak the slot (the same discipline
        as ``_discard_nowait``). List mutation is loop-thread-atomic;
        waiters are notified by a detached task."""
        self._inuse.discard(conn)
        if self._closed:
            # close() ran while this call was in flight — don't re-pool a
            # socket nobody will ever close again
            self._discard_nowait(conn)
            return
        self._free.append(conn)
        self._notify_detached()

    def _discard_nowait(self, conn) -> None:
        """Synchronous discard: safe to run from a CancelledError handler
        (any further ``await`` there could be interrupted again, leaking
        the slot)."""
        self._inuse.discard(conn)
        _reader, writer = conn
        writer.close()
        self._total -= 1
        self._notify_detached()

    def _notify_detached(self) -> None:
        """Wake one _acquire waiter from a task that can't be cancelled
        with the caller (Condition.notify needs the lock, which needs an
        await)."""

        async def _notify() -> None:
            async with self._cond:
                self._cond.notify()

        try:
            task = asyncio.get_running_loop().create_task(_notify())
            self._bg_tasks.add(task)
            task.add_done_callback(self._bg_tasks.discard)
        except RuntimeError:      # no running loop (teardown) — no waiters
            pass

    def abort_inflight(self) -> int:
        """Force-close every connection with a call in flight: the pending
        reads fail immediately as transport errors instead of waiting out
        the full dispatch timeout against a peer that is being removed —
        the caller's retry policy then requeues the work on an alternate.
        Slot accounting stays with the in-flight caller (its discard path
        runs when the read fails); this only tears the sockets."""
        n = 0
        for _reader, writer in list(self._inuse):
            writer.close()
            n += 1
        return n

    async def close(self) -> None:
        """Close idle connections and mark the pool closed: in-flight calls
        discard their connection when they finish instead of re-pooling it,
        so the count drains to zero. A later ``call`` reopens the pool
        (reconnect semantics, matching the pre-pool client)."""
        self._closed = True
        async with self._cond:
            free, self._free = self._free, []
            self._total -= len(free)
            self._cond.notify_all()
        for _reader, writer in free:
            writer.close()
            try:
                await writer.wait_closed()
            # graftlint: ok[swallowed-transport-error] pool teardown of an already-closing socket — there is no call left to fail
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def call(self, method: str, *, timeout: Optional[float] = None,
                   **params: Any) -> Any:
        """Send one request frame, await one response frame.

        Raises ``RPCError`` when the peer reports failure; transport trouble
        (``OSError``/``asyncio.TimeoutError``/...) propagates for callers —
        router/LB — to turn into health signals.
        """
        return await self._roundtrip(method, None, timeout, params)

    async def call_stream(self, method: str, on_chunk: Callable[[Dict], None],
                          *, timeout: Optional[float] = None,
                          **params: Any) -> Any:
        """Send one request, consume a stream of chunk frames, return the
        final result.

        The server interleaves ``{"stream": true, ...}`` frames (each passed
        to ``on_chunk``) before the usual success/error envelope. ``timeout``
        bounds each individual frame read — a live stream keeps resetting
        it — not the total call.
        """
        return await self._roundtrip(method, on_chunk, timeout, params)

    async def _roundtrip(self, method: str,
                         on_chunk: Optional[Callable[[Dict], None]],
                         timeout: Optional[float],
                         params: Dict[str, Any]) -> Any:
        """One shared request/response cycle for ``call`` and
        ``call_stream`` — a single copy of the acquire/discard discipline
        and envelope validation (two copies drifted once before; see the
        module docstring)."""
        self._seq += 1
        msg = {"method": method, "id": f"{id(self):x}-{self._seq}", **params}
        effective = timeout if timeout is not None else self.timeout
        fault = None
        if self.fault_plan is not None:
            fault = self.fault_plan.draw(self.address, "client", method)
            if fault is not None and fault.kind == "connect_refused":
                raise ConnectionRefusedError(
                    f"chaos: injected connection refusal to {self.address}")
            if fault is not None and fault.kind == "slow":
                await asyncio.sleep(fault.delay_s)
        self._closed = False          # calling a closed client reopens it
        conn = await self._acquire(effective)
        self._inuse.add(conn)
        try:
            await write_frame(conn[1], msg)
            if fault is not None and fault.kind == "stall":
                # the request frame is on the wire; tear the connection
                # before the response — the worst spot in the exchange
                raise ConnectionResetError(
                    f"chaos: injected mid-frame stall to {self.address}")
            while True:
                frame = await read_frame(
                    conn[0], max_frame=self.max_frame, timeout=effective,
                )
                if isinstance(frame, dict) and frame.get("stream"):
                    if on_chunk is None:
                        raise RPCError(
                            f"unexpected stream frame from {method!r} — "
                            "use call_stream for streaming methods")
                    on_chunk(frame)
                    continue
                response = frame
                break
        except BaseException:
            # BaseException: a cancelled caller must still return its slot
            # (a response may be in flight on the socket — discard it), or
            # the pool leaks towards zero capacity
            self._discard_nowait(conn)
            raise
        else:
            self._release_nowait(conn)
        if not isinstance(response, dict):
            raise RPCError(f"malformed response: {response!r}")
        if not response.get("success"):
            raise RPCError(response.get("error", "unknown peer error"),
                           kind=str(response.get("error_kind", "")),
                           detail=str(response.get("error_detail", "")))
        return response.get("result")


class ClientGone(Exception):
    """The streaming client hung up mid-stream — not a handler failure."""


async def relay_stream(fut: "asyncio.Future", queue: "asyncio.Queue",
                       send) -> Any:
    """Forward token chunks from ``queue`` to ``send`` until ``fut``
    resolves, drain the stragglers, return the result.

    The one copy of the getter/wait/drain/cancel relay both streaming
    servers use (worker and coordinator — the cancellation/ordering logic
    here is exactly the kind that drifts when duplicated). Safe because
    chunk callbacks and the future resolution ride the same
    ``call_soon_threadsafe`` FIFO: when ``fut`` is done, every chunk is
    already queued.
    """
    try:
        while True:
            getter = asyncio.ensure_future(queue.get())
            done, _ = await asyncio.wait(
                {getter, fut}, return_when=asyncio.FIRST_COMPLETED)
            if getter in done:
                await send({"tokens": getter.result()})
                continue
            getter.cancel()
            break
        while not queue.empty():
            await send({"tokens": queue.get_nowait()})
        return await fut
    except BaseException:
        fut.cancel()
        raise


class FramedServerMixin:
    """Framed-RPC server connection loop, shared by ``WorkerServer`` and
    ``CoordinatorServer``.

    Subclass contract: set ``self._methods`` (method name → async handler)
    and ``self._conn_writers`` (a set) before serving, expose
    ``self.max_frame_bytes``. Responses come back in frame order on one
    stream; concurrent clients use concurrent connections.

    Hooks (all optional overrides):
    - ``_run_handler(method, handler, msg)`` — server-side timeout policy.
    - ``_envelope_extra()`` — dict merged into every response envelope.
    - ``_timeout_error(method)`` — message for ``asyncio.TimeoutError``.
    - ``_on_handler_error(method, exc)`` — error accounting.
    - ``_after_dispatch(method, req_id, duration_s, response)`` — metrics.

    Streaming: methods in ``_stream_methods`` get ``handler(msg, send)``
    where ``await send(obj)`` writes a ``{"stream": true, "id": …}`` frame
    ahead of the final envelope; the client consumes them with
    ``FramedRPCClient.call_stream``.
    """

    _methods: Dict[str, Callable[[Dict[str, Any]], Awaitable[Any]]]
    _stream_methods: Dict[str, Callable[..., Awaitable[Any]]] = {}
    _conn_writers: set
    max_frame_bytes: int = 64 * 1024 * 1024
    # optional chaos injection oracle (utils/faults.FaultPlan); None in
    # production
    fault_plan = None

    def _fault_scope(self) -> str:
        """Identity this server reports to the FaultPlan (workers override
        via their ``worker_id`` attribute)."""
        return getattr(self, "worker_id", "") or type(self).__name__

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conn_writers.add(writer)
        try:
            first = True
            while True:
                try:
                    if first:
                        # sniff the connection's first bytes: an HTTP verb
                        # means a plain-HTTP scraper (GET /metrics) — hand
                        # the connection to the HTTP hook; anything else
                        # must be a frame header (magic-validated below)
                        first = False
                        head = await reader.readexactly(HEADER_SIZE)
                        if head[:4] in _HTTP_VERB_PREFIXES:
                            await self._serve_http(head, reader, writer)
                            break
                        msg = await read_frame_after_header(
                            reader, head, max_frame=self.max_frame_bytes)
                    else:
                        msg = await read_frame(
                            reader, max_frame=self.max_frame_bytes,
                            timeout=None,
                        )
                # graftlint: ok[swallowed-transport-error] client hung up; leaving the serve loop (and closing the connection) IS the handling
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break  # client closed
                except FrameError as e:
                    await write_frame(writer, {"success": False,
                                               "error": f"bad frame: {e}"})
                    break
                if self.fault_plan is not None and isinstance(msg, dict):
                    spec = self.fault_plan.draw(
                        self._fault_scope(), "server",
                        str(msg.get("method", "")))
                    if spec is not None:
                        if spec.kind == "drop":
                            break   # request consumed, no response, close
                        if spec.kind == "garble":
                            # bytes that fail frame-magic validation: the
                            # client sees FrameError (transport class)
                            writer.write(b"\x00GARBLED\x00FRAME\x00")
                            try:
                                await writer.drain()
                            # graftlint: ok[swallowed-transport-error] injected garble fault: the CLIENT is meant to see the failure (FrameError); the server just tears the conn
                            except (ConnectionResetError, BrokenPipeError):
                                pass
                            break
                        if spec.kind == "slow":
                            await asyncio.sleep(spec.delay_s)
                if (isinstance(msg, dict)
                        and msg.get("method") in self._stream_methods):
                    response = await self._dispatch_stream(msg, writer)
                    if response is None:      # client hung up mid-stream
                        break
                else:
                    response = await self._dispatch(msg)
                try:
                    await write_frame(writer, response)
                # graftlint: ok[swallowed-transport-error] client gone mid-response — nobody left to tell; the conn closes below
                except (ConnectionResetError, BrokenPipeError):
                    break                     # client gone — nobody to tell
        finally:
            self._conn_writers.discard(writer)
            writer.close()
            try:
                await writer.wait_closed()
            # graftlint: ok[swallowed-transport-error] teardown of a socket that is already dead — nothing to mark at this layer
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _dispatch(self, msg: Any) -> Dict[str, Any]:
        t0 = time.perf_counter()
        if not isinstance(msg, dict) or "method" not in msg:
            return {"success": False,
                    "error": "message must be a dict with 'method'"}
        method = msg["method"]
        handler = self._methods.get(method)
        req_id = msg.get("id", "")
        extra = self._envelope_extra()
        if handler is None:
            return {"id": req_id, "success": False, **extra,
                    "error": f"unknown method {method!r}"}
        try:
            result = await self._run_handler(method, handler, msg)
            response = {"id": req_id, "success": True, **extra,
                        "result": result}
        # graftlint: ok[swallowed-transport-error] the timeout becomes an error response frame — the client sees and counts it
        except asyncio.TimeoutError:
            response = {"id": req_id, "success": False, **extra,
                        "error": self._timeout_error(method)}
        except Exception as e:  # fan any handler error back, keep serving
            self._on_handler_error(method, e)
            logger.warning("%s: %s failed: %s",
                           type(self).__name__, method, e)
            response = {"id": req_id, "success": False, **extra,
                        "error": str(e)}
            kind = getattr(e, "rpc_error_kind", "") or getattr(e, "kind", "")
            if kind:
                response["error_kind"] = kind
            detail = (getattr(e, "rpc_error_detail", "")
                      or getattr(e, "detail", ""))
            if detail:
                response["error_detail"] = detail
        self._after_dispatch(method, req_id, time.perf_counter() - t0,
                             response)
        return response

    async def _dispatch_stream(
        self, msg: Dict[str, Any], writer: asyncio.StreamWriter
    ) -> Optional[Dict[str, Any]]:
        """Run a streaming handler: chunk frames on the wire as the
        handler emits them, then the normal envelope. Returns None when
        the CLIENT hung up mid-stream (routine for aborted generations —
        not a handler failure, and there is nobody left to send an
        envelope to); a downstream ConnectionError from the handler itself
        still produces an error envelope."""
        t0 = time.perf_counter()
        method = msg["method"]
        handler = self._stream_methods[method]
        req_id = msg.get("id", "")
        extra = self._envelope_extra()

        async def send(obj: Dict[str, Any]) -> None:
            try:
                await write_frame(writer,
                                  {"stream": True, "id": req_id, **obj})
            except (ConnectionResetError, BrokenPipeError, OSError) as e:
                raise ClientGone() from e

        try:
            result = await handler(msg, send)
            response = {"id": req_id, "success": True, **extra,
                        "result": result}
        except ClientGone:
            logger.info("%s: client disconnected mid-stream (%s)",
                        type(self).__name__, method)
            return None
        except Exception as e:
            self._on_handler_error(method, e)
            logger.warning("%s: %s failed: %s",
                           type(self).__name__, method, e)
            response = {"id": req_id, "success": False, **extra,
                        "error": str(e)}
            kind = getattr(e, "rpc_error_kind", "") or getattr(e, "kind", "")
            if kind:
                response["error_kind"] = kind
            detail = (getattr(e, "rpc_error_detail", "")
                      or getattr(e, "detail", ""))
            if detail:
                response["error_detail"] = detail
        self._after_dispatch(method, req_id, time.perf_counter() - t0,
                             response)
        return response

    # -- plain-HTTP side door (GET /metrics on the RPC port) ---------------

    async def _serve_http(self, head: bytes, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        """Answer ONE plain-HTTP request on the framed port, then let the
        caller close the connection. Only GET/HEAD reach ``_http_get``;
        everything else (and unknown paths) gets a 404. Deliberately
        minimal — this exists so ``curl``/Prometheus can scrape
        ``/metrics`` without speaking the frame protocol, not to be a web
        server."""
        try:
            raw = head
            if b"\r\n\r\n" not in raw:
                raw += await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=5.0)
        # graftlint: ok[swallowed-transport-error] best-effort HTTP side-door: a scraper that hangs up mid-request just loses its scrape
        except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, ConnectionResetError):
            return
        parts = raw.split(b"\r\n", 1)[0].decode("latin-1").split()
        method = parts[0].upper() if parts else ""
        path = (parts[1] if len(parts) > 1 else "/").split("?", 1)[0]
        status, ctype, body = "404 Not Found", "text/plain; charset=utf-8", \
            b"not found\n"
        if method in ("GET", "HEAD"):
            try:
                got = await self._http_get(path)
            except Exception as e:
                logger.warning("%s: HTTP %s %s failed: %s",
                               type(self).__name__, method, path, e)
                got = None
                status, body = ("500 Internal Server Error",
                                f"{e}\n".encode("utf-8", "replace"))
            if got is not None:
                ctype, body = got[0], got[1]
                status = "200 OK"
        payload = b"" if method == "HEAD" else body
        try:
            writer.write(
                f"HTTP/1.1 {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                "Connection: close\r\n\r\n".encode("latin-1") + payload)
            await writer.drain()
        # graftlint: ok[swallowed-transport-error] scraper disconnected before the HTTP response; the connection closes right after
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass

    async def _http_get(self, path: str) -> Optional[Tuple[str, bytes]]:
        """Override hook: return ``(content_type, body)`` or None for 404."""
        return None

    async def _run_handler(self, method: str, handler, msg) -> Any:
        return await handler(msg)

    def _envelope_extra(self) -> Dict[str, Any]:
        return {}

    def _timeout_error(self, method: str) -> str:
        return f"{method} timed out"

    def _on_handler_error(self, method: str, exc: Exception) -> None:
        pass

    def _after_dispatch(self, method: str, req_id: str,
                        duration_s: float, response: Dict[str, Any]) -> None:
        pass

    def _close_all_connections(self) -> None:
        for w in list(self._conn_writers):
            w.close()
