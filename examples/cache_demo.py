"""Interactive cache CLI — heir of the reference's
``examples/kvstore_demo.py`` (get/set/delete/stats REPL over the cache).

    set <key> <value> [ttl_s]
    get <key>
    del <key>
    stats | clear | keys | quit

Non-interactive: --script "set a 1; get a; stats"
Policy via --policy {lru,lfu,fifo}, capacity via --max-size.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_inference_engine_tpu.serving.cache import ResponseCache  # noqa: E402


def handle(cache: ResponseCache, line: str) -> bool:
    parts = line.split()
    if not parts:
        return True
    cmd, args = parts[0], parts[1:]
    try:
        if cmd in ("quit", "exit"):
            return False
        elif cmd == "set":
            ttl = float(args[2]) if len(args) > 2 else None
            cache.set(args[0], args[1], ttl=ttl)
            print(f"OK ({len(cache)} entries)")
        elif cmd == "get":
            t0 = time.perf_counter()
            val = cache.get(args[0])
            us = (time.perf_counter() - t0) * 1e6
            print(f"{val!r} ({us:.0f}us)" if val is not None else "(miss)")
        elif cmd == "del":
            print("deleted" if cache.delete(args[0]) else "(no such key)")
        elif cmd == "keys":
            print(cache.keys())
        elif cmd == "clear":
            print(f"cleared {cache.clear()} entries")
        elif cmd == "stats":
            print(json.dumps(cache.get_stats(), indent=2))
        else:
            print(f"unknown command {cmd!r} (set/get/del/keys/clear/stats/quit)")
    except Exception as e:
        print(f"error: {type(e).__name__}: {e}")
    return True


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--script", default="", help="semicolon-separated commands")
    ap.add_argument("--policy", default="lru", choices=["lru", "lfu", "fifo"])
    ap.add_argument("--max-size", type=int, default=1024)
    ap.add_argument("--default-ttl", type=float, default=0.0,
                    help="0 = no expiry")
    args = ap.parse_args()
    with ResponseCache(max_size=args.max_size, policy=args.policy,
                       default_ttl=args.default_ttl or None) as cache:
        print(f"cache: policy={args.policy} max_size={args.max_size}")
        from _repl import run_repl_sync

        run_repl_sync(lambda line: handle(cache, line), "cache> ", args.script)


if __name__ == "__main__":
    main()
