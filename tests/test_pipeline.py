"""Pipeline parallelism (parallel/pipeline.py): GPipe-microbatched stages
over the ``pp`` mesh axis — the strategy SURVEY.md §2.3 reserves for the
stacked-layer layout. Validated on the virtual 8-device CPU mesh like every
other sharding feature (SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import MeshConfig
from distributed_inference_engine_tpu.models.base import (
    forward_train,
    init_params,
)
from distributed_inference_engine_tpu.models.llama import llama_spec

from distributed_inference_engine_tpu.parallel.mesh import make_mesh
from distributed_inference_engine_tpu.parallel.pipeline import (
    make_pp_train_step,
    pipeline_forward_train,
    pp_param_pspecs,
)

SPEC = llama_spec("llama-tiny", max_seq_len=64).replace(dtype="float32")


def _batch(b=8, t=24):
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(1, 1000, (b, t)), jnp.int32)
    lens = jnp.asarray(rs.randint(4, t + 1, (b,)), jnp.int32)
    return tokens, lens


@pytest.mark.parametrize("pp,dp,n_micro", [(4, 2, 4), (2, 1, 2), (2, 2, 4)])
def test_pipeline_matches_dense_forward(pp, dp, n_micro):
    mesh = make_mesh(MeshConfig(dp=dp, pp=pp),
                     devices=jax.devices()[: dp * pp])
    params = init_params(SPEC, jax.random.key(0))
    tokens, lens = _batch()
    ref = forward_train(SPEC, params, tokens, lens)
    out = pipeline_forward_train(SPEC, params, tokens, lens, mesh, n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_pipeline_handles_gpt2_variant_blocks():
    """Stage splitting must survive the layernorm/bias/learned-pos block
    tree, not just Llama's."""
    from distributed_inference_engine_tpu.models.base import ModelSpec

    spec = ModelSpec(
        vocab_size=512, d_model=128, n_layers=4, n_heads=4, n_kv_heads=4,
        d_ff=256, max_seq_len=64, pos_emb="learned", norm="layernorm",
        mlp="gelu", use_bias=True, tie_embeddings=True, dtype="float32",
    )
    mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
    params = init_params(spec, jax.random.key(1))
    tokens, lens = _batch()
    ref = forward_train(spec, params, tokens, lens)
    out = pipeline_forward_train(spec, params, tokens, lens, mesh, n_micro=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_pp_train_step_loss_decreases_and_params_stage_sharded():
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    init_state, step = make_pp_train_step(SPEC, mesh, n_micro=4,
                                          learning_rate=1e-2)
    state = init_state(jax.random.key(2))
    params = state[0]
    # block tensors are stage-sharded over pp on the leading (layer) axis
    wq_sharding = params["blocks"]["wq"].sharding
    assert "pp" in (wq_sharding.spec[0] if isinstance(wq_sharding.spec[0],
                                                      tuple)
                    else (wq_sharding.spec[0],))
    tokens, lens = _batch()
    losses = []
    for _ in range(6):
        state, loss = step(state, tokens, lens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_pp_gradients_match_dense():
    """The pipelined backward (grad through ppermute/scan schedule) must
    produce the same gradients as the dense model."""
    from distributed_inference_engine_tpu.models.base import causal_lm_loss
    from distributed_inference_engine_tpu.parallel.pipeline import (
        pipeline_lm_loss,
    )

    mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
    params = init_params(SPEC, jax.random.key(3))
    tokens, lens = _batch(b=4)
    g_ref = jax.grad(lambda p: causal_lm_loss(SPEC, p, tokens, lens))(params)
    g_pp = jax.grad(lambda p: pipeline_lm_loss(SPEC, p, tokens, lens, mesh,
                                               n_micro=2))(params)
    # jax.tree.leaves_with_path is missing on older jax; the tree_util
    # spelling exists on every version in support
    from jax.tree_util import tree_leaves_with_path
    flat_ref = tree_leaves_with_path(g_ref)
    flat_pp = {str(k): v for k, v in tree_leaves_with_path(g_pp)}
    for k, v in flat_ref:
        np.testing.assert_allclose(
            np.asarray(flat_pp[str(k)]), np.asarray(v),
            rtol=2e-3, atol=2e-4, err_msg=str(k))


def test_pp_pspecs_cover_all_block_tensors():
    pspecs = pp_param_pspecs(SPEC)
    for k, p in pspecs["blocks"].items():
        assert tuple(p)[0] == "pp", f"{k} not stage-sharded"


def test_bad_microbatch_count_raises():
    mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
    params = init_params(SPEC, jax.random.key(0))
    tokens, lens = _batch(b=8)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward_train(SPEC, params, tokens, lens, mesh, n_micro=3)


def test_layer_count_must_divide_stages():
    mesh = make_mesh(MeshConfig(pp=8))
    params = init_params(SPEC, jax.random.key(0))     # 4 layers, 8 stages
    tokens, lens = _batch(b=8)
    with pytest.raises(ValueError, match="pp stages"):
        pipeline_forward_train(SPEC, params, tokens, lens, mesh, n_micro=4)


def test_moe_spec_rejected_with_clear_error():
    from distributed_inference_engine_tpu.models.llama import mixtral_spec

    spec = mixtral_spec("mixtral-tiny").replace(dtype="float32")
    mesh = make_mesh(MeshConfig(pp=4), devices=jax.devices()[:4])
    params = init_params(spec, jax.random.key(0))
    tokens, lens = _batch(b=4)
    with pytest.raises(ValueError, match="MoE"):
        pipeline_forward_train(spec, params, tokens, lens, mesh, n_micro=2)
