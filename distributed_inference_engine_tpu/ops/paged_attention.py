"""Paged decode attention: Pallas TPU kernel + XLA reference implementation.

This is the TPU-native answer to SURVEY.md §7 hard-part #2 (paged KV cache in
HBM) and the north-star reinterpretation of the reference's ``src/kvstore.py``
cache: attention state lives in a pool of fixed-size HBM pages instead of one
contiguous row per sequence, so long and short sequences share HBM without
fragmentation and page recycling replaces whole-row eviction.

Layout (per layer):

- ``k_pages`` / ``v_pages``: ``[num_pages, page_size, n_kv * head_dim]`` —
  the trailing dim is fused so every VMEM block is lane-aligned (the kernel
  requires ``n_kv * head_dim`` to be a multiple of 128, the TPU lane count).
- ``page_table``: ``[batch, max_pages_per_seq]`` int32 — logical page ``p`` of
  slot ``b`` lives in physical page ``page_table[b, p]``. Unused entries must
  hold a valid page id (0): the kernel still DMAs them (static grid) and masks
  the scores, so the id only has to be safe to read.
- ``lengths``: ``[batch]`` int32 — live tokens per slot, *including* the
  token at the current decode position.

Kernel design (flash-style online softmax over pages):

- Grid ``(batch, max_pages_per_seq)``; the page table and lengths ride
  ``PrefetchScalarGridSpec`` so the index map can translate logical→physical
  page ids before the block DMA is issued — the gather lives in the DMA
  engine, not in compute.
- Per grid step one K page and one V page are DMA'd to VMEM (double-buffered
  by the Pallas pipeline across the sequential page axis); VMEM scratch
  carries the running (max, sum, acc) across pages of the same row.
- All in-kernel tensors stay RANK-2 with the fused head·dim axis on lanes:
  Mosaic rejects the "natural" batched-per-head ``dot_general`` and 3-D
  reshapes for these shapes (found the hard way on hardware — interpret
  mode happily accepts both). Per-head segment sums and broadcasts are
  expressed as matmuls against constant 0/1 matrices, which lower cleanly
  to the MXU; GQA expands K/V to query heads the same way.
- Decode attention is HBM-bandwidth-bound; the kernel's job is DMAing only
  live pages, not MXU utilisation. Precision is bf16-grade (Mosaic's fp32
  matmul rounds operands through bf16 passes), matching bf16 serving.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _upcast_fp8

NEG_INF = -1e30


# ----------------------------------------------------------------- XLA path


def paged_attention_xla(
    q: jnp.ndarray,            # [B, H, Dh]
    k_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    v_pages: jnp.ndarray,      # [N, P, Hkv * Dh]
    page_table: jnp.ndarray,   # [B, MP] int32
    lengths: jnp.ndarray,      # [B] int32
    *,
    n_kv_heads: int,
    window: int = 0,           # sliding-window size (0 = full attention)
    with_stats: bool = False,
):
    """Reference implementation via gather; correct everywhere (CPU tests,
    interpret-mode cross-check), but reads the whole gathered cache through
    XLA's generic scatter/gather path. Returns [B, H, Dh] in q.dtype — or
    (out, m, l) flash stats ([B, H] fp32 each) with ``with_stats`` for
    ``ops.attention.merge_attention`` (a zero-valid row carries l = 0)."""
    b, h, dh = q.shape
    n, p, fused = k_pages.shape
    mp = page_table.shape[1]
    g = h // n_kv_heads

    # gather FIRST, upcast the gathered pages only: upcasting the whole
    # pool would materialize a full wide copy per decode call — the HBM
    # traffic the fp8 cache exists to avoid
    k, v = _upcast_fp8(k_pages[page_table], v_pages[page_table], q.dtype)
    k = k.reshape(b, mp * p, n_kv_heads, dh)      # [B, S, Hkv, Dh]
    v = v.reshape(b, mp * p, n_kv_heads, dh)

    qg = q.reshape(b, n_kv_heads, g, dh)
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k).astype(jnp.float32) * scale
    valid = jnp.arange(mp * p)[None, :] < lengths[:, None]        # [B, S]
    if window:
        valid &= jnp.arange(mp * p)[None, :] >= (lengths[:, None] - window)
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    m = scores.max(axis=-1)                                       # [B,Hkv,G]
    probs = jnp.exp(scores - m[..., None])
    # zero-valid rows: m == NEG_INF turns every exp into 1 — zero them so
    # l is a true softmax denominator (merge weight 0, not S)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    l = probs.sum(axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs.astype(v.dtype), v)
    out = out.astype(jnp.float32) / jnp.maximum(l[..., None], 1e-30)
    out = out.reshape(b, h, dh).astype(q.dtype)
    if with_stats:
        return out, m.reshape(b, h), l.reshape(b, h)
    return out


# -------------------------------------------------------------- Pallas path


def _paged_attn_kernel(
    # scalar prefetch
    page_table_ref,            # [B, MP] SMEM
    lengths_ref,               # [B] SMEM
    layer_ref,                 # [1] SMEM: layer offset into a stacked pool
                               # (0 when the caller passes one layer's pool)
    # blocks — q/out carry a singleton sublane axis: Mosaic requires the
    # last two block dims to divide (8, 128) or EQUAL the array dims, and
    # a (1, H·Dh) block over a (B, H·Dh) array satisfies neither (the
    # interpret-mode tests can't catch this; only a real TPU lowers it)
    q_ref,                     # [1, 1, H * Dh] VMEM
    k_ref,                     # [1, P, Hkv * Dh] VMEM (one physical page)
    v_ref,                     # [1, P, Hkv * Dh] VMEM
    out_ref,                   # [1, 1, H * Dh] VMEM
    m_ref,                     # [1, 1, H] VMEM: final row max (flash stats)
    l_ref,                     # [1, 1, H] VMEM: final denominator
    # scratch
    m_scr,                     # [1, H] f32 running max per head
    l_scr,                     # [1, H] f32 running denominator
    acc_scr,                   # [1, H * Dh] f32 running numerator
    *,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
    n_heads: int,
    window: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    length = lengths_ref[b]
    dh = head_dim
    H = n_heads
    g = H // n_kv_heads

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # pages past the live prefix contribute nothing; skip their FLOPs —
    # and with a sliding window, so do pages wholly before the window
    live = p * page_size < length
    if window:
        live &= (p + 1) * page_size > length - window

    # constant 0/1 map, folded into the compiled kernel:
    # S [H*Dh, H] segment-sums each head's Dh lanes; S.T broadcasts back
    lane_head = lax.broadcasted_iota(jnp.int32, (H * dh, H), 0) // dh
    head_idx = lax.broadcasted_iota(jnp.int32, (H * dh, H), 1)
    seg = (lane_head == head_idx).astype(jnp.float32)

    @pl.when(live)
    def _page():
        qf = q_ref[0, 0, :].astype(jnp.float32)[None, :]       # [1, H*Dh]
        kf = k_ref[0].astype(jnp.float32)                      # [P, Hkv*Dh]
        vf = v_ref[0].astype(jnp.float32)
        if g > 1:
            # GQA: replicate each kv head's Dh lanes across its query
            # group with STATIC lane-slice concats (a dense 0/1 expander
            # matmul would cost O(P·HkvDh·HDh) MACs and a VMEM constant
            # that blows up at real GQA shapes, e.g. 16 MiB for 8B-class)
            kf = jnp.concatenate(
                [kf[:, (h // g) * dh:(h // g + 1) * dh] for h in range(H)],
                axis=1)
            vf = jnp.concatenate(
                [vf[:, (h // g) * dh:(h // g + 1) * dh] for h in range(H)],
                axis=1)
        prod = kf * qf                                         # [P, H*Dh]
        scores = jnp.dot(prod, seg,                            # [P, H]
                         preferred_element_type=jnp.float32,
                         precision=lax.Precision.HIGHEST)
        scores = scores * (1.0 / (dh ** 0.5))
        tok = p * page_size + lax.broadcasted_iota(
            jnp.int32, (page_size, H), 0)
        in_range = tok < length
        if window:
            in_range &= tok >= length - window
        scores = jnp.where(in_range, scores, NEG_INF)

        m_prev = m_scr[:]                                      # [1, H]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, scores.max(axis=0, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                        # [1, H]
        probs = jnp.exp(scores - m_new[0][None, :])            # [P, H]
        l_new = l_prev * alpha + probs.sum(axis=0, keepdims=True)

        pe = jnp.dot(probs, seg.T,                             # [P, H*Dh]
                     preferred_element_type=jnp.float32,
                     precision=lax.Precision.HIGHEST)
        pv = (pe * vf).sum(axis=0, keepdims=True)              # [1, H*Dh]
        alpha_e = jnp.dot(alpha, seg.T,
                          preferred_element_type=jnp.float32,
                          precision=lax.Precision.HIGHEST)
        acc_scr[:] = acc_scr[:] * alpha_e + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(p == n_pages - 1)
    def _finish():
        l = jnp.maximum(l_scr[:], 1e-30)                       # [1, H]
        le = jnp.dot(l, seg.T, preferred_element_type=jnp.float32,
                     precision=lax.Precision.HIGHEST)
        out = (acc_scr[:] / le).reshape(1, 1, H * dh)
        out_ref[:] = out.astype(out_ref.dtype)
        # flash stats for cross-source merging (zero-valid rows keep the
        # RAW l = 0, so their merge weight vanishes)
        m_ref[:] = m_scr[:].reshape(1, 1, H)
        l_ref[:] = l_scr[:].reshape(1, 1, H)


def paged_attention_pallas(
    q: jnp.ndarray,            # [B, H, Dh]
    k_pages: jnp.ndarray,      # [N, P, Hkv*Dh] — or [L*N, P, Hkv*Dh] stacked
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,   # [B, MP] int32
    lengths: jnp.ndarray,      # [B] int32
    *,
    n_kv_heads: int,
    window: int = 0,
    interpret: bool = False,
    with_stats: bool = False,
    layer=None,                # int32 scalar: layer offset into stacked pools
    n_pages_per_layer: int = 0,
):
    """One compiled program serves both pool layouts: per-layer pools
    (``layer=None``) and the STACKED [L·N, P, fused] layout, where the
    physical page id becomes ``layer·N + table[i, p]``. The stacked form
    lets the decode scan hand the whole pool to the kernel — slicing one
    layer out per step materializes a pool-sized copy per layer·step
    (custom-call operands can't fuse a dynamic slice)."""
    b, h, dh = q.shape
    n, page_size, fused = k_pages.shape
    mp = page_table.shape[1]
    if fused != n_kv_heads * dh:
        raise ValueError(f"fused dim {fused} != n_kv_heads*head_dim {n_kv_heads * dh}")
    if fused % 128:
        raise ValueError(
            f"n_kv_heads*head_dim = {fused} must be a multiple of 128 (TPU lanes)"
        )
    n_per = n_pages_per_layer or n
    if layer is None:
        layer = jnp.zeros((1,), jnp.int32)
    else:
        layer = jnp.asarray(layer, jnp.int32).reshape(1)

    page_idx = lambda i, p, pt, ln, ly: (ly[0] * n_per + pt[i, p], 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, mp),
        in_specs=[
            # q/out: (1, 1, H·Dh) blocks over a (B, 1, H·Dh) array — the
            # trailing two block dims EQUAL the array dims, satisfying the
            # Mosaic tiling rule for any batch size
            pl.BlockSpec((1, 1, h * dh), lambda i, p, pt, ln, ly: (i, 0, 0)),
            pl.BlockSpec((1, page_size, fused), page_idx),
            pl.BlockSpec((1, page_size, fused), page_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, h * dh), lambda i, p, pt, ln, ly: (i, 0, 0)),
            pl.BlockSpec((1, 1, h), lambda i, p, pt, ln, ly: (i, 0, 0)),
            pl.BlockSpec((1, 1, h), lambda i, p, pt, ln, ly: (i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h * dh), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_attn_kernel,
        n_kv_heads=n_kv_heads,
        head_dim=dh,
        page_size=page_size,
        n_heads=h,
        window=window,
    )
    out, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((b, 1, h * dh), q.dtype),
                   jax.ShapeDtypeStruct((b, 1, h), jnp.float32),
                   jax.ShapeDtypeStruct((b, 1, h), jnp.float32)],
        interpret=interpret,
    )(page_table, lengths, layer, q.reshape(b, 1, h * dh), k_pages, v_pages)
    out = out.reshape(b, h, dh)
    if with_stats:
        return out, m.reshape(b, h), l.reshape(b, h)
    return out


# ------------------------------------------------------------- dispatcher


def paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    n_kv_heads: int,
    impl: str = "auto",
    window: int = 0,
    with_stats: bool = False,
    layer=None,
    n_pages_per_layer: int = 0,
):
    """impl: "auto" | "xla" | "pallas" | "pallas_interpret" (kernel
    correctness tests on CPU). ``with_stats`` additionally returns the
    flash (m, l) stats for cross-source merging; ``layer``/
    ``n_pages_per_layer`` select a layer inside STACKED [L·N, P, fused]
    pools (pallas path; the XLA path's callers slice the layer out — a
    plain gather XLA fuses fine).

    "auto" resolves to the XLA path on every backend — a measured, now
    settled decision (README "Pallas status"): on a real v5e at 8B
    serving shapes the kernel's (slot, page) grid pays ~13 µs of
    unhidden DMA latency per step (1,380 vs 3,623 tok/s end-to-end,
    round 3), and the dense-ctx chunk scheme (engine/continuous.py)
    removed the per-step paged read it was built to accelerate — decode
    now touches pages once per chunk, which stock XLA gathers at full
    bandwidth. The kernel is RETIRED to a reference/testing role: it
    stays correct (interpret-mode cross-checks on CPU, explicit
    ``attention_impl="pallas"``) and is the starting point should a
    future shape — very long contexts where live-bucket padding waste
    overtakes DMA latency — reopen the question."""
    if impl == "auto":
        impl = "xla"
    if impl == "xla":
        if layer is not None:
            raise ValueError(
                "stacked-pool layer indexing is a pallas-path feature; "
                "slice the layer before the xla path")
        return paged_attention_xla(
            q, k_pages, v_pages, page_table, lengths, n_kv_heads=n_kv_heads,
            window=window, with_stats=with_stats,
        )
    if impl in ("pallas", "pallas_interpret"):
        return paged_attention_pallas(
            q, k_pages, v_pages, page_table, lengths,
            n_kv_heads=n_kv_heads, window=window,
            interpret=impl == "pallas_interpret",
            with_stats=with_stats, layer=layer,
            n_pages_per_layer=n_pages_per_layer,
        )
    raise ValueError(f"unknown paged-attention impl {impl!r}")
