"""Paged KV cache + paged attention: allocator accounting, XLA/Pallas kernel
equivalence (interpret mode on CPU — SURVEY.md §4's multi-device-without-
hardware strategy applied to kernels), and paged-vs-contiguous decode parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.engine.paged_kv import PagedKVCache
from distributed_inference_engine_tpu.models.base import (
    ModelSpec,
    forward_decode,
    forward_decode_paged,
    forward_prefill,
    init_params,
    write_prefill_pages,
)
from distributed_inference_engine_tpu.ops.paged_attention import (
    paged_attention_pallas,
    paged_attention_xla,
)

# fused kv dim must be a multiple of 128: 2 heads * 64 = 128
SPEC = ModelSpec(
    vocab_size=256, d_model=256, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=256, max_seq_len=256, dtype="float32",
)


# ------------------------------------------------------------- allocator


def test_alloc_slot_and_pages():
    kv = PagedKVCache(SPEC, max_slots=4, page_size=16, num_pages=8, max_seq_len=128)
    s0 = kv.alloc_slot(20)          # 2 pages
    s1 = kv.alloc_slot(5)           # 1 page
    assert s0 is not None and s1 is not None and s0 != s1
    assert kv.n_free_pages == 5
    assert kv.slot_capacity(s0) == 32
    kv.free_slot(s0)
    assert kv.n_free_pages == 7
    assert kv.n_free_slots == 3


def test_alloc_exhaustion_returns_none():
    kv = PagedKVCache(SPEC, max_slots=8, page_size=16, num_pages=2, max_seq_len=128)
    assert kv.alloc_slot(32) is not None      # takes both pages
    assert kv.alloc_slot(1) is None           # no pages left
    stats = kv.get_stats()
    assert stats["pages_free"] == 0 and stats["utilization"] == 1.0


def test_reserve_grows_across_page_boundary():
    kv = PagedKVCache(SPEC, max_slots=2, page_size=16, num_pages=4, max_seq_len=128)
    s = kv.alloc_slot(15)
    assert kv.slot_capacity(s) == 16
    assert kv.reserve(s, 8) == 8              # 15+8=23 -> 2 pages
    assert kv.slot_capacity(s) == 32
    assert kv.reserve(s, 1000) == 0           # would need more than the pool
    kv.free_slot(s)
    assert kv.n_free_pages == 4


def test_reserve_truncated_by_max_seq_len():
    """A grant clipped by max_seq_len reports the partial amount, and a slot
    already at max_seq_len gets 0 — the decode chunk must stop, not index
    past the page table (code-review finding: silent True here corrupted
    the slot's last page)."""
    kv = PagedKVCache(SPEC, max_slots=1, page_size=16, num_pages=8, max_seq_len=64)
    s = kv.alloc_slot(60)
    assert kv.reserve(s, 16) == 4             # clipped at 64
    assert kv.reserve(s, 16) == 0             # already at cap
    assert kv.slot_capacity(s) == 64


def test_page_table_device_mirror_updates():
    kv = PagedKVCache(SPEC, max_slots=2, page_size=16, num_pages=4, max_seq_len=64)
    t0 = kv.page_table
    assert t0.shape == (2, 4)
    s = kv.alloc_slot(30)
    t1 = kv.page_table
    assert not np.array_equal(np.asarray(t0), np.asarray(t1))
    # no accounting change -> same device array object (no re-upload)
    assert kv.page_table is t1
    kv.free_slot(s)


def test_misaligned_fused_dim_rejected():
    # a valid spec whose kv width is misaligned: 1 kv head * 16 dims = 16
    bad = ModelSpec(vocab_size=16, d_model=64, n_layers=1, n_heads=4,
                    n_kv_heads=1, d_ff=64)
    with pytest.raises(ValueError, match="multiple of 128"):
        PagedKVCache(bad, max_slots=1, page_size=8, num_pages=2)


# ----------------------------------------------------- kernel equivalence


def _random_paged_case(seed, b=3, h=4, n_kv=2, dh=64, page_size=16,
                       num_pages=16, max_pages=4, dtype=jnp.float32):
    rs = np.random.RandomState(seed)
    fused = n_kv * dh
    q = jnp.asarray(rs.randn(b, h, dh), dtype=dtype)
    k_pages = jnp.asarray(rs.randn(num_pages, page_size, fused), dtype=dtype)
    v_pages = jnp.asarray(rs.randn(num_pages, page_size, fused), dtype=dtype)
    # distinct physical pages per slot (as the allocator guarantees)
    perm = rs.permutation(num_pages)[: b * max_pages].reshape(b, max_pages)
    table = jnp.asarray(perm, dtype=jnp.int32)
    lengths = jnp.asarray(rs.randint(1, page_size * max_pages + 1, size=b),
                          dtype=jnp.int32)
    return q, k_pages, v_pages, table, lengths


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pallas_kernel_matches_xla(seed):
    q, kp, vp, table, lengths = _random_paged_case(seed)
    ref = paged_attention_xla(q, kp, vp, table, lengths, n_kv_heads=2)
    out = paged_attention_pallas(q, kp, vp, table, lengths, n_kv_heads=2,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pallas_kernel_partial_last_page():
    q, kp, vp, table, _ = _random_paged_case(7)
    lengths = jnp.asarray([1, 17, 64], dtype=jnp.int32)   # 1 tok / cross-page / full
    ref = paged_attention_xla(q, kp, vp, table, lengths, n_kv_heads=2)
    out = paged_attention_pallas(q, kp, vp, table, lengths, n_kv_heads=2,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_xla_path_masks_stale_pool_data():
    """Garbage in unused pages/positions must not leak into the output."""
    q, kp, vp, table, _ = _random_paged_case(3)
    lengths = jnp.asarray([5, 5, 5], dtype=jnp.int32)
    out1 = paged_attention_xla(q, kp, vp, table, lengths, n_kv_heads=2)
    # poison everything past position 5 in each slot's first page + all later pages
    kp2 = kp.at[:, 5:, :].set(1e4)
    out2 = paged_attention_xla(q, kp2, vp, table, lengths, n_kv_heads=2)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)


# ------------------------------------------------- end-to-end decode parity


def test_paged_decode_matches_contiguous():
    """forward_decode_paged == forward_decode given identical KV history."""
    spec = SPEC
    key = jax.random.key(0)
    params = init_params(spec, key)
    rs = np.random.RandomState(0)
    B, T = 2, 24
    prompts = jnp.asarray(rs.randint(0, spec.vocab_size, size=(B, T)), jnp.int32)
    seq_lens = jnp.asarray([24, 9], dtype=jnp.int32)

    _, ks, vs = forward_prefill(spec, params, prompts, seq_lens)

    # contiguous cache
    S = 64
    L, Hkv, Dh = spec.n_layers, spec.n_kv_heads, spec.head_dim
    ck = jnp.zeros((L, B, S, Hkv, Dh), jnp.float32).at[:, :, :T].set(ks)
    cv = jnp.zeros((L, B, S, Hkv, Dh), jnp.float32).at[:, :, :T].set(vs)

    # paged cache via the real allocator + prefill scatter
    kv = PagedKVCache(spec, max_slots=B, page_size=16, num_pages=12,
                      max_seq_len=S, dtype="float32")
    slots = [kv.alloc_slot(int(seq_lens[i]) + 8) for i in range(B)]
    assert slots == [0, 1]
    kp, vp = write_prefill_pages(
        kv.k_pages, kv.v_pages, ks, vs, kv.page_table, seq_lens
    )

    tok = jnp.asarray(rs.randint(0, spec.vocab_size, size=B), jnp.int32)
    h_ref, _, _ = forward_decode(spec, params, tok, seq_lens, ck, cv)
    h_paged, kp2, vp2 = forward_decode_paged(
        spec, params, tok, seq_lens, kp, vp, kv.page_table, attn_impl="xla"
    )
    np.testing.assert_allclose(np.asarray(h_paged), np.asarray(h_ref),
                               rtol=2e-4, atol=2e-4)

    # and one more step after the write (checks the scatter landed right):
    # the first decode call wrote fresh K/V into both cache forms
    tok2 = jnp.asarray(rs.randint(0, spec.vocab_size, size=B), jnp.int32)
    _, ck2, cv2 = forward_decode(spec, params, tok, seq_lens, ck, cv)
    h_ref2, _, _ = forward_decode(spec, params, tok2, seq_lens + 1, ck2, cv2)
    h_paged2, _, _ = forward_decode_paged(
        spec, params, tok2, seq_lens + 1, kp2, vp2, kv.page_table,
        attn_impl="xla",
    )
    np.testing.assert_allclose(np.asarray(h_paged2), np.asarray(h_ref2),
                               rtol=2e-4, atol=2e-4)


def test_prefill_page_scatter_roundtrip():
    """Tokens written by write_prefill_pages land at (table[b,pos//P], pos%P)."""
    spec = SPEC
    params = init_params(spec, jax.random.key(1))
    rs = np.random.RandomState(5)
    B, T = 2, 20
    prompts = jnp.asarray(rs.randint(0, spec.vocab_size, size=(B, T)), jnp.int32)
    seq_lens = jnp.asarray([20, 13], dtype=jnp.int32)
    _, ks, vs = forward_prefill(spec, params, prompts, seq_lens)

    kv = PagedKVCache(spec, max_slots=B, page_size=16, num_pages=8,
                      max_seq_len=64, dtype="float32")
    for i in range(B):
        kv.alloc_slot(int(seq_lens[i]))
    kp, vp = write_prefill_pages(
        kv.k_pages, kv.v_pages, ks, vs, kv.page_table, seq_lens
    )
    table = np.asarray(kv.page_table)
    kp_np = np.asarray(kp)
    ks_np = np.asarray(ks).reshape(spec.n_layers, B, T, -1)
    for b in range(B):
        for pos in [0, 7, int(seq_lens[b]) - 1]:
            page, off = table[b, pos // 16], pos % 16
            np.testing.assert_allclose(
                kp_np[:, page, off], ks_np[:, b, pos], rtol=1e-6
            )
    # padded tail of slot 1 (positions 13..19) must NOT have been written
    np.testing.assert_allclose(kp_np[:, table[1, 0], 14], 0.0, atol=0)


# -------------------------------------------- flash stats + stacked pools


def test_stats_merge_matches_single_softmax():
    """Splitting the key set into paged-prefix + side-window partials and
    merging their flash stats must equal one softmax over the union —
    the invariant the windowed decode chunk rests on."""
    from distributed_inference_engine_tpu.ops.attention import (
        merge_attention, window_decode_attention,
    )

    q, kp, vp, table, _ = _random_paged_case(5)
    lengths = jnp.asarray([30, 17, 64], jnp.int32)
    rs = np.random.RandomState(9)
    b, h = q.shape[0], q.shape[1]
    W, n_kv, dh = 8, 2, q.shape[2]
    ks = jnp.asarray(rs.randn(b, W, n_kv, dh), jnp.float32)
    vs = jnp.asarray(rs.randn(b, W, n_kv, dh), jnp.float32)
    n_side = jnp.asarray([3, 0, 8], jnp.int32)   # incl. a zero-valid row

    prefix = paged_attention_xla(q, kp, vp, table, lengths, n_kv_heads=2,
                                 with_stats=True)
    window = window_decode_attention(q, ks, vs, n_side)
    merged = merge_attention([prefix, window])

    # reference: one dense softmax over gathered prefix + valid side keys
    mp, p = table.shape[1], kp.shape[1]
    k_all = kp[table].reshape(b, mp * p, n_kv, dh)
    v_all = vp[table].reshape(b, mp * p, n_kv, dh)
    k_cat = jnp.concatenate([k_all, ks], axis=1)
    v_cat = jnp.concatenate([v_all, vs], axis=1)
    s_tot = mp * p + W
    valid = (jnp.arange(s_tot)[None, :] < lengths[:, None]) | (
        (jnp.arange(s_tot)[None, :] >= mp * p)
        & (jnp.arange(s_tot)[None, :] - mp * p < n_side[:, None]))
    qg = q.reshape(b, n_kv, h // n_kv, dh)
    scores = jnp.einsum("bkgd,bskd->bkgs", qg, k_cat) / np.sqrt(dh)
    scores = jnp.where(valid[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("bkgs,bskd->bkgd", probs, v_cat).reshape(b, h, dh)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pallas_stats_and_stacked_layer_match_xla():
    """Kernel feature parity in interpret mode: with_stats returns the
    same (m, l) the XLA path computes, and stacked-pool layer indexing
    reads layer l's pages exactly."""
    q, kp, vp, table, lengths = _random_paged_case(11)
    ref_out, ref_m, ref_l = paged_attention_xla(
        q, kp, vp, table, lengths, n_kv_heads=2, with_stats=True)
    out, m, l = paged_attention_pallas(
        q, kp, vp, table, lengths, n_kv_heads=2, interpret=True,
        with_stats=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(m), np.asarray(ref_m), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(l), np.asarray(ref_l), rtol=1e-5)

    # stacked pools: layer 1 of a 3-layer stack
    L, n = 3, kp.shape[0]
    rs = np.random.RandomState(2)
    big_k = jnp.asarray(rs.randn(L * n, *kp.shape[1:]), kp.dtype)
    big_v = jnp.asarray(rs.randn(L * n, *vp.shape[1:]), vp.dtype)
    ref2 = paged_attention_xla(q, big_k[n:2 * n], big_v[n:2 * n], table,
                               lengths, n_kv_heads=2)
    out2 = paged_attention_pallas(
        q, big_k, big_v, table, lengths, n_kv_heads=2, interpret=True,
        layer=jnp.asarray(1), n_pages_per_layer=n)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(ref2),
                               rtol=2e-5, atol=2e-5)


def test_forward_prefill_into_pages_matches_two_program_path():
    """The fused admission prefill (per-layer KV scattered into the
    pools inside the scan, r5) must produce byte-identical pools and
    hidden states to forward_prefill + write_prefill_pages — including
    a seq_len=0 pad row, whose positions must all drop."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_inference_engine_tpu.models.base import (
        ModelSpec,
        forward_prefill,
        forward_prefill_into_pages,
        init_params,
        write_prefill_pages,
    )

    spec = ModelSpec(vocab_size=128, d_model=256, n_layers=2, n_heads=4,
                     n_kv_heads=2, d_ff=128, max_seq_len=64,
                     dtype="float32")
    params = init_params(spec, jax.random.key(0))
    L, Hkv, Dh = 2, 2, 64
    n_pages, page_size = 8, 16
    fused = Hkv * Dh
    rs = np.random.RandomState(0)
    tokens = jnp.asarray(rs.randint(1, 128, size=(4, 32)), jnp.int32)
    seq_lens = jnp.asarray([32, 20, 5, 0], jnp.int32)   # incl. pad row
    table = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0],
                         [5, 0, 0, 0], [0, 0, 0, 0]], jnp.int32)
    kp0 = jnp.full((L, n_pages, page_size, fused), -7.0, jnp.float32)
    vp0 = jnp.full_like(kp0, -9.0)

    h_ref, ks, vs = forward_prefill(spec, params, tokens, seq_lens)
    kp_ref, vp_ref = write_prefill_pages(
        kp0, vp0, ks, vs, table, seq_lens)
    h_got, kp_got, vp_got = forward_prefill_into_pages(
        spec, params, tokens, seq_lens, kp0, vp0, table)
    np.testing.assert_array_equal(np.asarray(h_got), np.asarray(h_ref))
    np.testing.assert_array_equal(np.asarray(kp_got), np.asarray(kp_ref))
    np.testing.assert_array_equal(np.asarray(vp_got), np.asarray(vp_ref))
    # pad row's pages (incl. page 0, which its zeroed table row points
    # at) keep the sentinel fill where no valid token landed
    assert float(kp_got[:, 6:].min()) == -7.0


# --------------------------------------- eviction order under pin pressure


def _cache(num_pages, max_slots=4):
    return PagedKVCache(SPEC, max_slots=max_slots, page_size=16,
                        num_pages=num_pages, max_seq_len=256,
                        dtype="float32")


def _prompt(base, n_tokens=16):
    return list(range(base, base + n_tokens))


def test_pinned_prefix_pages_never_reclaimed():
    """A cached page re-pinned by a live slot must be invisible to
    _take_free, even when it is the ONLY reclaimable candidate left —
    allocation fails rather than stealing pinned KV (the hazard at
    alloc_slot_prefix's pin-before-source ordering)."""
    kv = _cache(num_pages=3)
    pa = _prompt(0)
    s1, _ = kv.alloc_slot_prefix(pa)            # 1 page
    kv.register_prefix(s1, pa)
    kv.free_slot(s1)
    assert list(kv._reclaimable)                # cached, ref 0

    s2, n2 = kv.alloc_slot_prefix(pa + _prompt(100, 32))   # re-pins pa's page
    assert s2 is not None and n2 == 16
    pinned = kv._slot_pages[s2][0]
    assert pinned not in kv._reclaimable and kv._page_ref[pinned] == 1

    # pool: 3 pages, all owned by s2 now → nothing reclaimable, nothing free
    assert kv.available_pages == 0
    assert kv._take_free(1) is None             # must NOT hand out the pin
    assert kv.alloc_slot(4) is None
    # s2's table is intact and alias-free
    pages = kv._slot_pages[s2]
    assert len(set(pages)) == len(pages) == 3


def test_reclaim_order_is_recency_not_registration():
    """Re-pinning a cached chain and releasing it moves it to MRU: the
    next reclaim under pressure takes the least-recently-USED chain, not
    the first-registered one."""
    kv = _cache(num_pages=3)
    chains = [_prompt(0), _prompt(1000), _prompt(2000)]
    for c in chains:                            # cache A, then B, then C
        s, _ = kv.alloc_slot_prefix(c)
        kv.register_prefix(s, c)
        kv.free_slot(s)
    assert kv.get_stats()["pages_cached"] == 3

    # touch A: re-admit + free → A becomes most-recently-used
    s, n = kv.alloc_slot_prefix(chains[0] + [7])
    assert n == 16
    kv.free_slot(s)

    ha, hb, hc = (kv._page_hashes(c, 1)[0] for c in chains)
    # one writable page under full-cache pressure must evict B (oldest)
    s2 = kv.alloc_slot(4)
    assert s2 is not None
    assert hb not in kv._prefix_index
    assert ha in kv._prefix_index and hc in kv._prefix_index


def test_pin_churn_stress_invariants():
    """Deterministic churn of shared-prefix admissions, growth, and frees
    against a tight pool: after every operation the allocator invariants
    hold — no page in two tables, no pinned page free/reclaimable, and
    free/reclaimable disjoint."""
    kv = _cache(num_pages=10, max_slots=4)
    rs = np.random.RandomState(7)
    prompts = [_prompt(b, 40) for b in (0, 500, 0, 9000)]  # 0 shared twice
    live = {}

    def check():
        owned = [p for pages in kv._slot_pages.values() for p in pages]
        for pages in kv._slot_pages.values():
            assert len(set(pages)) == len(pages), f"aliased table {pages}"
        free, recl = set(kv._free), set(kv._reclaimable)
        assert not free & recl
        assert not set(owned) & free and not set(owned) & recl
        for p, r in kv._page_ref.items():
            assert r >= 1
            assert p not in free and p not in recl
        # every reclaimable page is indexed; every indexed page exists
        for p in recl:
            assert p in kv._page_key
        for h, p in kv._prefix_index.items():
            assert kv._page_key.get(p) == h

    for it in range(60):
        op = rs.randint(3)
        if op == 0 and len(live) < 4:
            pi = rs.randint(len(prompts))
            got = kv.alloc_slot_prefix(prompts[pi])
            if got is not None:
                slot, _ = got
                kv.register_prefix(slot, prompts[pi])
                live[slot] = prompts[pi]
        elif op == 1 and live:
            slot = list(live)[rs.randint(len(live))]
            kv.ensure_capacity(slot, kv._slot_len[slot] + 16)
        elif live:
            slot = list(live)[rs.randint(len(live))]
            kv.free_slot(slot)
            del live[slot]
        check()
    for slot in list(live):
        kv.free_slot(slot)
    check()
    assert kv.available_pages == 10
