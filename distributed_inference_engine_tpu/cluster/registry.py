"""Model registry: metadata, versioning, shard placement, consistent hashing.

Capability heir of the reference's ``src/model_registry.py``: model
registration/versioning (``:86-114``), shard placement records (``:29-46``),
``get_shard_for_key`` consistent hashing — md5(key) mod n_shards — so a given
request key always lands on the same shard (``:149-161``), per-worker model
tracking (``:175-177``), metadata-hash change detection (``:179-190``), and
full dict round-trip serialization (``:192-249``).

TPU reinterpretation (BASELINE.json north star): a *shard* is no longer "a
worker holding a copy of the weights" — it is a **mesh placement record**: the
worker host plus the slice of the ``jax.sharding.Mesh`` (axis sizes, spec
name) the model partition occupies. ``get_shard_for_key`` then implements
session/prefix-cache affinity across TPU workers, while the tensor-level
partitioning inside one worker is carried by ``mesh_axes``.
"""

from __future__ import annotations

import enum
import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..config import ModelConfig


class ModelStatus(str, enum.Enum):
    """Reference ``src/model_registry.py:20-26``."""

    PENDING = "pending"
    LOADING = "loading"
    READY = "ready"
    FAILED = "failed"
    UNLOADING = "unloading"


@dataclass
class ModelShard:
    """One placement of (part of) a model version on a worker
    (reference ``src/model_registry.py:29-46``), extended with TPU mesh
    placement."""

    shard_id: int
    worker_id: str
    status: ModelStatus = ModelStatus.PENDING
    load: float = 0.0
    mesh_axes: Dict[str, int] = field(default_factory=dict)   # e.g. {"tp": 8}
    partition_spec: str = ""                                  # sharding recipe name
    metadata: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "shard_id": self.shard_id,
            "worker_id": self.worker_id,
            "status": self.status.value,
            "load": self.load,
            "mesh_axes": dict(self.mesh_axes),
            "partition_spec": self.partition_spec,
            "metadata": dict(self.metadata),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelShard":
        return cls(
            shard_id=d["shard_id"],
            worker_id=d["worker_id"],
            status=ModelStatus(d.get("status", "pending")),
            load=d.get("load", 0.0),
            mesh_axes=d.get("mesh_axes", {}),
            partition_spec=d.get("partition_spec", ""),
            metadata=d.get("metadata", {}),
        )


@dataclass
class ModelVersion:
    """Reference ``src/model_registry.py:49-74``."""

    name: str
    version: str
    config: ModelConfig
    status: ModelStatus = ModelStatus.PENDING
    quantized: bool = False
    shards: List[ModelShard] = field(default_factory=list)
    created_at: float = field(default_factory=time.time)
    metadata: Dict[str, Any] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.name}:{self.version}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "version": self.version,
            "config": self.config.to_dict(),
            "status": self.status.value,
            "quantized": self.quantized,
            "shards": [s.to_dict() for s in self.shards],
            "created_at": self.created_at,
            "metadata": dict(self.metadata),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelVersion":
        return cls(
            name=d["name"],
            version=d["version"],
            config=ModelConfig.from_dict(d.get("config", {"name": d["name"]})),
            status=ModelStatus(d.get("status", "pending")),
            quantized=d.get("quantized", False),
            shards=[ModelShard.from_dict(s) for s in d.get("shards", [])],
            created_at=d.get("created_at", time.time()),
            metadata=d.get("metadata", {}),
        )


def stable_key_hash(key: str) -> int:
    """md5-based stable hash — deterministic across processes and Python
    runs, unlike builtin ``hash`` (reference ``src/model_registry.py:149-161``
    chose md5 for the same reason)."""
    return int.from_bytes(hashlib.md5(key.encode("utf-8")).digest()[:8], "big")


class ModelRegistry:
    """Thread-safe registry of model versions and their shard placements."""

    def __init__(self) -> None:
        self._versions: Dict[str, ModelVersion] = {}     # "name:version" -> MV
        self._worker_models: Dict[str, List[str]] = {}   # worker_id -> [version keys]
        self._hashes: Dict[str, str] = {}                # "name:version" -> metadata hash
        self._lock = threading.RLock()

    # -------------------------------------------------------- registration

    def register_model(
        self,
        config: ModelConfig,
        version: Optional[str] = None,
        quantized: Optional[bool] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> ModelVersion:
        """Register (or update) a model version (reference ``:86-114``)."""
        with self._lock:
            ver = version or config.version
            existing = self._versions.get(f"{config.name}:{ver}")
            mv = ModelVersion(
                name=config.name,
                version=ver,
                config=config,
                quantized=config.quantized if quantized is None else quantized,
                metadata=metadata or {},
            )
            if existing is not None:
                # re-registration updates config/metadata but must not orphan
                # live shard placements (or strand their worker-index entries)
                mv.shards = existing.shards
                mv.status = existing.status
                mv.created_at = existing.created_at
            self._versions[mv.key] = mv
            self._update_hash(mv)
            return mv

    def add_shard(
        self,
        name: str,
        version: str,
        worker_id: str,
        shard_id: Optional[int] = None,
        mesh_axes: Optional[Dict[str, int]] = None,
        partition_spec: str = "",
        status: ModelStatus = ModelStatus.READY,
    ) -> ModelShard:
        """Attach a shard placement to a model version (reference ``:116-147``)."""
        with self._lock:
            mv = self._require(name, version)
            sid = shard_id if shard_id is not None else len(mv.shards)
            if any(s.shard_id == sid for s in mv.shards):
                raise ValueError(f"shard {sid} already exists for {mv.key}")
            shard = ModelShard(
                shard_id=sid,
                worker_id=worker_id,
                status=status,
                mesh_axes=mesh_axes or {},
                partition_spec=partition_spec,
            )
            mv.shards.append(shard)
            mv.shards.sort(key=lambda s: s.shard_id)
            self._worker_models.setdefault(worker_id, [])
            if mv.key not in self._worker_models[worker_id]:
                self._worker_models[worker_id].append(mv.key)
            if mv.status is ModelStatus.PENDING:
                mv.status = ModelStatus.READY
            self._update_hash(mv)
            return shard

    def remove_shard(self, name: str, version: str, shard_id: int) -> bool:
        with self._lock:
            mv = self._require(name, version)
            before = len(mv.shards)
            removed = [s for s in mv.shards if s.shard_id == shard_id]
            mv.shards = [s for s in mv.shards if s.shard_id != shard_id]
            for s in removed:
                # drop this version from the worker's index only if the worker
                # no longer serves any shard of *this version*
                still_this_version = any(
                    sh.worker_id == s.worker_id for sh in mv.shards
                )
                if not still_this_version and s.worker_id in self._worker_models:
                    self._worker_models[s.worker_id] = [
                        k for k in self._worker_models[s.worker_id] if k != mv.key
                    ]
            if len(mv.shards) != before:
                self._update_hash(mv)
                return True
            return False

    # ------------------------------------------------------------- lookup

    def get_shard_for_key(
        self, name: str, version: str, request_key: str
    ) -> Optional[ModelShard]:
        """Consistent-hash placement: same key ⇒ same shard, as long as the
        shard set is unchanged (reference ``:149-161``)."""
        with self._lock:
            mv = self._versions.get(f"{name}:{version}")
            if mv is None or not mv.shards:
                return None
            return mv.shards[stable_key_hash(request_key) % len(mv.shards)]

    def get_model_version(self, name: str, version: str) -> Optional[ModelVersion]:
        with self._lock:
            return self._versions.get(f"{name}:{version}")

    def list_models(self) -> List[str]:
        with self._lock:
            return sorted({mv.name for mv in self._versions.values()})

    def list_versions(self, name: str) -> List[str]:
        with self._lock:
            return sorted(
                mv.version for mv in self._versions.values() if mv.name == name
            )

    def get_worker_models(self, worker_id: str) -> List[str]:
        """Version keys served by a worker (reference ``:175-177``)."""
        with self._lock:
            return list(self._worker_models.get(worker_id, []))

    def all_shards(self, name: str, version: str) -> List[ModelShard]:
        with self._lock:
            mv = self._versions.get(f"{name}:{version}")
            return list(mv.shards) if mv else []

    # ------------------------------------------------------ change hashing

    def _update_hash(self, mv: ModelVersion) -> None:
        """md5 over the version's metadata *excluding shard state*, so the
        hash detects config changes, not load/health churn (reference
        ``:179-190``)."""
        d = mv.to_dict()
        d.pop("shards", None)
        d.pop("created_at", None)
        d.pop("status", None)   # placement/health churn must not look like a config change
        blob = json.dumps(d, sort_keys=True).encode("utf-8")
        self._hashes[mv.key] = hashlib.md5(blob).hexdigest()

    def get_model_hash(self, name: str, version: str) -> Optional[str]:
        with self._lock:
            return self._hashes.get(f"{name}:{version}")

    # -------------------------------------------------------- serialization

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "versions": {k: mv.to_dict() for k, mv in self._versions.items()},
                "worker_models": {k: list(v) for k, v in self._worker_models.items()},
            }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelRegistry":
        reg = cls()
        for key, mvd in d.get("versions", {}).items():
            mv = ModelVersion.from_dict(mvd)
            reg._versions[key] = mv
            reg._update_hash(mv)
        reg._worker_models = {k: list(v) for k, v in d.get("worker_models", {}).items()}
        return reg

    # --------------------------------------------------------------- misc

    def _require(self, name: str, version: str) -> ModelVersion:
        mv = self._versions.get(f"{name}:{version}")
        if mv is None:
            raise KeyError(f"model {name}:{version} is not registered")
        return mv

    def get_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "models": len({mv.name for mv in self._versions.values()}),
                "versions": len(self._versions),
                "shards": sum(len(mv.shards) for mv in self._versions.values()),
                "workers": len([w for w, ms in self._worker_models.items() if ms]),
            }
