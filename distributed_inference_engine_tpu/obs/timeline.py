"""StepTimeline: a ring-buffer recorder for engine dispatches, exported as
Chrome trace-event JSON (loadable in Perfetto / ``chrome://tracing``).

``jax.profiler`` captures the XLA/TPU device timeline; what it cannot show
is the ENGINE's view — which step was a mixed ragged dispatch vs a pure
decode chunk, how many prefill tokens rode along, what the KV pool and
host tier looked like at that moment, and which dispatches paid a first
-execution (compile) cost. This recorder captures exactly that, cheaply
(one small dict appended to a bounded deque per dispatch — against step
times in the tens of milliseconds), and brackets cleanly around the
worker's ``jax.profiler`` start/stop hooks so the two timelines cover the
same window.

Trace-event mapping: each step is a complete event (``"ph": "X"``) with
microsecond ``ts``/``dur`` relative to the timeline's epoch; markers are
instant events (``"ph": "i"``). Event ``args`` carry the per-step payload
(rows, prefill tokens, pool occupancy, ``compile``) and show up in the
Perfetto slice-details pane.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Dict, List, Optional


class StepTimeline:
    """Bounded per-engine step recorder with Chrome trace export."""

    def __init__(self, capacity: int = 4096, name: str = "engine") -> None:
        self.name = name
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=max(1, self.capacity))
        self._epoch = time.perf_counter()
        self._capture_from: Optional[float] = None
        self._dropped = 0

    def __len__(self) -> int:
        return len(self._events)

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, t_start: float, dur_s: float,
               **args: Any) -> None:
        """One complete dispatch: ``t_start`` is a ``time.perf_counter()``
        stamp, ``dur_s`` its wall duration."""
        if len(self._events) == self._events.maxlen:
            self._dropped += 1
        self._events.append({"name": kind, "t": float(t_start),
                             "dur": float(dur_s), "args": args})

    def instant(self, kind: str, **args: Any) -> None:
        if len(self._events) == self._events.maxlen:
            self._dropped += 1
        self._events.append({"name": kind, "t": time.perf_counter(),
                             "dur": None, "args": args})

    # -- capture window (brackets jax.profiler start/stop) -----------------

    def start_capture(self) -> None:
        self._capture_from = time.perf_counter()

    def stop_capture(self) -> List[Dict[str, Any]]:
        """Events recorded since ``start_capture()`` (all events if the
        window was never opened). Leaves the ring intact."""
        since, self._capture_from = self._capture_from, None
        return self.events(since=since)

    def events(self, since: Optional[float] = None) -> List[Dict[str, Any]]:
        evs = list(self._events)
        if since is not None:
            evs = [e for e in evs if e["t"] >= since]
        return evs

    # -- export ------------------------------------------------------------

    def to_chrome_trace(self, events: Optional[List[Dict[str, Any]]] = None,
                        pid: int = 0, tid: int = 0) -> Dict[str, Any]:
        """Chrome trace-event JSON object (the ``traceEvents`` container
        format Perfetto ingests directly)."""
        if events is None:
            events = self.events()
        out: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": self.name},
        }]
        for e in events:
            ts = (e["t"] - self._epoch) * 1e6
            if e["dur"] is None:
                out.append({"name": e["name"], "ph": "i", "s": "t",
                            "ts": ts, "pid": pid, "tid": tid,
                            "args": dict(e["args"])})
            else:
                out.append({"name": e["name"], "ph": "X", "ts": ts,
                            "dur": e["dur"] * 1e6, "pid": pid, "tid": tid,
                            "args": dict(e["args"])})
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "metadata": {"timeline": self.name,
                         "dropped_events": self._dropped},
        }

    def dump(self, path: str,
             events: Optional[List[Dict[str, Any]]] = None) -> str:
        # tmp+rename so a crash mid-dump never leaves Perfetto a half-JSON
        from ..utils.files import atomic_write

        trace = self.to_chrome_trace(events)
        return atomic_write(path, lambda f: json.dump(trace, f))


def busy_gap_split(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """Decompose a window of dispatch events into busy (inside a dispatch
    bracket) vs gap (host time BETWEEN consecutive brackets) seconds —
    the roofline split (ISSUE 5): ``hbm_util`` regressions attribute to
    the kernel side when busy grew, to the scheduler/host side when gap
    grew. Instant markers (``dur is None``) are skipped; overlapping
    brackets clamp the gap at zero rather than going negative.

    Returns busy_s, gap_s, bubble_frac = gap / (busy + gap), and the
    event count the split was computed over."""
    spans = sorted((e["t"], e["t"] + e["dur"]) for e in events
                   if e.get("dur") is not None)
    busy = 0.0
    gap = 0.0
    prev_end: Optional[float] = None
    for t0, t1 in spans:
        busy += t1 - t0
        if prev_end is not None and t0 > prev_end:
            gap += t0 - prev_end
        prev_end = max(prev_end, t1) if prev_end is not None else t1
    total = busy + gap
    return {
        "busy_s": busy,
        "gap_s": gap,
        "bubble_frac": (gap / total) if total > 0 else 0.0,
        "n_events": len(spans),
    }
