"""Prefix KV-cache tests (engine/paged_kv.py + forward_prefill_suffix):
shared prompt prefixes must reuse KV pages — the reference's response cache
(``src/kvstore.py``) taken to its north-star depth, where the unit of reuse
is an attention-state page rather than a finished response.

Correctness bar: prefix-cache hits must be token-for-token invisible — the
cached KV is exact state, so greedy outputs match a cache-off engine."""

import jax
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.paged_kv import PagedKVCache
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.base import init_params
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=128)
PAGE = 8
SYS = list(range(1, 25))          # 24 tokens = 3 full pages of shared prefix


def _cfg(prefix_cache=True, num_pages=64, **over):
    # kv_dtype matches the spec dtype so cache-on/cache-off comparisons are
    # exact (bf16 pages would round the prefix KV the cache-off path keeps
    # at full precision — a near-tie argmax could flip spuriously)
    base = dict(max_slots=4, max_seq_len=128, page_size=PAGE,
                num_pages=num_pages, decode_steps_per_call=4,
                attention_impl="xla", prefix_cache=prefix_cache,
                kv_dtype="float32")
    base.update(over)
    return EngineConfig(**base)


def _reqs():
    return [
        GenerationRequest(prompt=SYS + [30, 31], max_new_tokens=6,
                          temperature=0.0, request_id="a"),
        GenerationRequest(prompt=SYS + [40, 41, 42], max_new_tokens=6,
                          temperature=0.0, request_id="b"),
    ]


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.key(0))


def test_prefix_hits_match_cache_off_engine(params):
    off = ContinuousEngine(SPEC, params=params, config=_cfg(False))
    base = {r.request_id: r.tokens for r in off.generate(_reqs())}

    on = ContinuousEngine(SPEC, params=params, config=_cfg(True))
    out = {r.request_id: r.tokens for r in on.generate(_reqs())}
    assert out == base
    m = on.get_metrics()
    assert m["prefix_hit_admissions"] == 1          # b reused a's pages
    assert m["kv"]["prefix_hit_tokens"] == len(SYS)

    # freed slots keep their full pages warm: a fresh request with the same
    # system prefix hits again
    out2 = {r.request_id: r.tokens for r in on.generate(_reqs())}
    assert out2 == base
    assert on.get_metrics()["kv"]["prefix_hit_tokens"] >= 3 * len(SYS)


def test_prefix_cache_partial_match(params):
    """A prompt sharing only the first page reuses exactly that page."""
    on = ContinuousEngine(SPEC, params=params, config=_cfg(True))
    on.generate([GenerationRequest(prompt=SYS + [30], max_new_tokens=2,
                                   temperature=0.0)])
    half = SYS[:PAGE] + [90, 91, 92]               # shares one full page
    off = ContinuousEngine(SPEC, params=params, config=_cfg(False))
    want = off.generate([GenerationRequest(prompt=half, max_new_tokens=5,
                                           temperature=0.0)])[0].tokens
    got = on.generate([GenerationRequest(prompt=half, max_new_tokens=5,
                                         temperature=0.0)])[0].tokens
    assert got == want
    assert on.get_metrics()["kv"]["prefix_hit_pages"] == 1


def test_prefix_cache_never_caches_whole_prompt(params):
    """A prompt that IS a cached prefix still prefills ≥1 suffix token
    (the engine needs last-position logits)."""
    on = ContinuousEngine(SPEC, params=params, config=_cfg(True))
    p = SYS[:16]                                   # exactly 2 pages
    on.generate([GenerationRequest(prompt=p, max_new_tokens=2,
                                   temperature=0.0)])
    off = ContinuousEngine(SPEC, params=params, config=_cfg(False))
    want = off.generate([GenerationRequest(prompt=p, max_new_tokens=3,
                                           temperature=0.0)])[0].tokens
    got = on.generate([GenerationRequest(prompt=p, max_new_tokens=3,
                                         temperature=0.0)])[0].tokens
    assert got == want
    # matched at most (16-1)//8 = 1 page on the second pass
    assert on.get_metrics()["kv"]["prefix_hit_pages"] <= 1


def test_reclaim_evicts_cached_pages_when_pool_is_tight():
    """Cached pages are reclaimed LRU when the free list runs dry —
    allocation must not fail while reclaimable pages exist."""
    kv = PagedKVCache(SPEC, max_slots=4, page_size=PAGE, num_pages=6,
                      max_seq_len=128, dtype="float32")
    s1, n1 = kv.alloc_slot_prefix(list(range(100, 124)))   # 3 pages
    assert n1 == 0
    kv.register_prefix(s1, list(range(100, 124)))
    kv.free_slot(s1)
    st = kv.get_stats()
    assert st["pages_cached"] == 3 and st["pages_free"] == 3

    # a 5-page prompt needs more than the free list: reclaims 2 cached
    s2, n2 = kv.alloc_slot_prefix(list(range(200, 240)))
    assert s2 is not None and n2 == 0
    st = kv.get_stats()
    assert st["prefix_reclaimed"] == 2
    # the reclaimed pages left the index
    assert st["prefix_indexed"] == 1


def test_shared_pages_refcounted_not_double_freed():
    kv = PagedKVCache(SPEC, max_slots=4, page_size=PAGE, num_pages=16,
                      max_seq_len=128, dtype="float32")
    prompt = list(range(50, 75))                    # 25 tokens → 4 pages
    s1, _ = kv.alloc_slot_prefix(prompt)
    kv.register_prefix(s1, prompt)
    s2, n2 = kv.alloc_slot_prefix(prompt)
    assert n2 == 24                                 # 3 full pages shared
    shared = kv._slot_pages[s1][:3]
    assert kv._slot_pages[s2][:3] == shared
    kv.free_slot(s1)
    # shared pages still referenced by s2: not free, not reclaimable
    for p in shared:
        assert p not in kv._free
        assert p not in kv._reclaimable
    kv.free_slot(s2)
    for p in shared:
        assert p in kv._reclaimable                 # now cached, ref 0


def test_shared_pages_never_reclaimed_into_own_slot():
    """Regression (review finding): re-admitting a cached prompt under
    full pool pressure must NOT reclaim one of its own shared prefix pages
    as the writable suffix page — that aliases the page table and the
    suffix prefill would clobber cached prefix KV."""
    kv = PagedKVCache(SPEC, max_slots=4, page_size=PAGE, num_pages=4,
                      max_seq_len=128, dtype="float32")
    prompt = list(range(300, 332))                  # 32 tokens = 4 pages
    s1, n1 = kv.alloc_slot_prefix(prompt)
    assert n1 == 0
    kv.register_prefix(s1, prompt)
    kv.free_slot(s1)
    assert kv.get_stats()["pages_cached"] == 4 and not kv._free

    s2, n2 = kv.alloc_slot_prefix(prompt)
    assert s2 is not None
    pages = kv._slot_pages[s2]
    assert len(set(pages)) == len(pages), f"aliased page table: {pages}"
    # 3 shared pages matched; the 4th (writable) page must be the one
    # reclaimed from cache, not any of the shared three
    assert n2 == 24
    assert pages[3] not in pages[:3]


def test_alloc_prefix_rolls_back_pins_on_failure():
    """If fresh pages can't be sourced, the shared-page pins must be
    undone (no refcount leak)."""
    kv = PagedKVCache(SPEC, max_slots=4, page_size=PAGE, num_pages=3,
                      max_seq_len=128, dtype="float32")
    p1 = list(range(400, 424))                      # 3 pages, fills pool
    s1, _ = kv.alloc_slot_prefix(p1)
    kv.register_prefix(s1, p1)
    # pool exhausted (s1 holds everything): a long prompt sharing the
    # prefix cannot allocate its private pages
    long = p1 + list(range(900, 940))
    assert kv.alloc_slot_prefix(long) is None
    # the matched shared pages belong to s1 (ref 1), untouched by rollback
    assert all(kv._page_ref[p] == 1 for p in kv._slot_pages[s1])
    kv.free_slot(s1)
    assert kv.get_stats()["pages_cached"] == 3       # registered full pages


def test_prefix_disabled_via_config(params):
    eng = ContinuousEngine(SPEC, params=params, config=_cfg(False))
    eng.generate(_reqs())
    m = eng.get_metrics()
    assert m["prefix_hit_admissions"] == 0
    assert m["kv"]["prefix_queries"] == 0
