"""Checkpoint/resume: native weight checkpoints (Orbax) + a model-spec
sidecar so a served or trained param tree round-trips without the original
HF files.

SURVEY.md §5 "checkpoint/resume" row: the reference persists ONLY registry
metadata (``src/model_registry.py:192-249`` dict round-trip, no file IO and
no weights — there are no weights). This module supplies the real half:

- ``save_params`` / ``load_params``: Orbax PyTree checkpoints of a param
  tree (sharded-array aware on TPU; on restore the tree is materialised on
  the default device unless a template with shardings is given).
- The ``spec.json`` sidecar records the ``ModelSpec`` so a checkpoint dir
  is self-describing — ``models.engine_from_config`` can load one directly
  (``ModelConfig.path`` pointing at an Orbax dir works like an HF dir).

The control-plane half (registry + fleet snapshot) lives in
``api.coordinator.Coordinator.save_state/restore_state``.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Dict, Optional

from .files import atomic_write_json

SPEC_FILE = "spec.json"
PARAMS_DIR = "params"
_QUANT_MARKER = "__quantized_tensor__"


def _encode_tree(tree: Any) -> Any:
    """Replace QuantizedTensor nodes with sentinel dicts: Orbax restores
    custom pytree nodes as plain containers, which would silently lose the
    node type (the engine's matmuls dispatch on it)."""
    from ..ops.quant import QuantizedTensor

    def enc(node: Any) -> Any:
        if isinstance(node, QuantizedTensor):
            import numpy as np

            # bits/pack_axis persist as tiny arrays (orbax stores arrays):
            # an int4 checkpoint restored as default-int8 would be
            # silently mis-shaped
            out = {_QUANT_MARKER: np.int8(1), "q": node.q, "s": node.s,
                   "bits": np.int32(node.bits),
                   "pack_axis": np.int32(node.pack_axis)}
            if node.bits == 4:
                # layout version: split-half packing (r4). Old files
                # without it are even/odd interleaved and get repacked
                # on restore
                from ..ops.quant import INT4_LAYOUT_SPLIT_HALF

                out["layout"] = np.int32(INT4_LAYOUT_SPLIT_HALF)
            return out
        if isinstance(node, dict):
            return {k: enc(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(enc(v) for v in node)
        return node

    return enc(tree)


def _decode_tree(tree: Any) -> Any:
    from ..ops.quant import QuantizedTensor

    def dec(node: Any) -> Any:
        if isinstance(node, dict):
            if _QUANT_MARKER in node:
                # pre-int4 checkpoints carry no bits field -> int8
                qt = QuantizedTensor(
                    q=node["q"], s=node["s"],
                    bits=int(node.get("bits", 8)),
                    pack_axis=int(node.get("pack_axis", 0)))
                if qt.bits == 4 and "layout" not in node:
                    # pre-r4 int4 files are even/odd interleaved; the
                    # current code (XLA fallback AND the Mosaic kernel)
                    # reads split-half — repack once here
                    from ..ops.quant import repack_int4_interleaved_to_split

                    qt = repack_int4_interleaved_to_split(qt)
                return qt
            return {k: dec(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(dec(v) for v in node)
        return node

    return dec(tree)


def is_native_checkpoint(path: str) -> bool:
    """True when ``path`` is a directory written by ``save_params``."""
    p = pathlib.Path(path)
    return (p / SPEC_FILE).is_file() and (p / PARAMS_DIR).exists()


def save_params(path: str, spec, params: Any) -> str:
    """Write ``params`` (+ the spec sidecar) to ``path``; returns the path.

    Quantized trees (``ops.quant.QuantizedTensor`` nodes) serialize
    transparently — they are registered pytrees of arrays.
    """
    import orbax.checkpoint as ocp

    p = pathlib.Path(path).absolute()
    p.mkdir(parents=True, exist_ok=True)
    # atomic: a crash mid-save must not leave a torn spec sidecar that
    # poisons the next load_spec
    atomic_write_json(str(p / SPEC_FILE), spec.to_dict())
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(p / PARAMS_DIR, _encode_tree(params), force=True)
    ckptr.close()
    return str(p)


def load_spec(path: str):
    """Read the ModelSpec sidecar of a native checkpoint dir."""
    from ..models.base import ModelSpec

    d = json.loads((pathlib.Path(path) / SPEC_FILE).read_text())
    return ModelSpec.from_dict(d)


def load_params(path: str, template: Optional[Any] = None) -> Any:
    """Restore a param tree saved by ``save_params``.

    ``template`` (optional) is a like-structured tree of arrays or
    ShapeDtypeStructs — pass one with shardings to restore directly into a
    mesh layout; without it the tree materialises on the default device.
    """
    import orbax.checkpoint as ocp

    p = pathlib.Path(path).absolute() / PARAMS_DIR
    ckptr = ocp.PyTreeCheckpointer()
    try:
        if template is not None:
            return _decode_tree(_restore_with_template(ckptr, p, template))
        return _decode_tree(ckptr.restore(p))
    finally:
        ckptr.close()


def _restore_with_template(ckptr, p, template):
    """Restore honoring the template's shardings: ``item=`` alone does NOT
    set restore shardings (Orbax materialises every leaf on one device and
    warns 'Sharding info not provided') — explicit restore_args built from
    the template leaves are what place shards directly on the mesh."""
    import orbax.checkpoint as ocp

    enc = _encode_tree(template)
    restore_args = ocp.checkpoint_utils.construct_restore_args(enc)
    return ckptr.restore(p, item=enc, restore_args=restore_args)


def save_train_state(path: str, spec, state: Dict[str, Any]) -> str:
    """Checkpoint a training state tree (params + optimizer moments +
    step) the same way; resumable via ``load_train_state``."""
    import orbax.checkpoint as ocp

    p = pathlib.Path(path).absolute()
    p.mkdir(parents=True, exist_ok=True)
    atomic_write_json(str(p / SPEC_FILE), spec.to_dict())
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(p / "state", _encode_tree(state), force=True)
    ckptr.close()
    return str(p)


def load_train_state(path: str, template: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    p = pathlib.Path(path).absolute() / "state"
    ckptr = ocp.PyTreeCheckpointer()
    try:
        if template is not None:
            return _decode_tree(_restore_with_template(ckptr, p, template))
        return _decode_tree(ckptr.restore(p))
    finally:
        ckptr.close()
