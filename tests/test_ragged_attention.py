"""Ragged mixed-batch attention (ops/ragged_attention.py): parity of the
Pallas kernel (CPU interpret mode) against the XLA reference — decode rows
(q=1), prefill-chunk rows (q>1), and inert rows (q=0) in ONE dispatch —
across GQA groupings, fp8 pools, masked tails, page-boundary-straddling
chunks, and stacked-pool layer indexing; fresh-KV page writeback must be
bit-exact. Plus forward_mixed_step wiring, the config compose-validation
error, the compile-count guard (bucket audit), and engine-level greedy
equivalence of the mixed step vs the alternating split path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.ops.ragged_attention import (
    ragged_attention,
    ragged_attention_pallas,
    ragged_attention_xla,
)

IMPL = "pallas-ragged_interpret"

pytestmark = pytest.mark.kernels


def _inputs(key, *, r=4, qmax=8, h=4, hkv=2, dh=64, n=32, p=8, mp=4,
            layers=1, q_dtype=jnp.float32, kv_dtype=jnp.float32,
            ctx_lens=None, q_lens=None):
    """Random mixed batch. Rows own DISJOINT page sets (the engine
    invariant the kernel's writeback relies on); ctx+q stays within each
    row's mp pages."""
    assert r * mp <= n
    ks = jax.random.split(key, 6)
    q = jax.random.normal(ks[0], (r, qmax, h, dh), q_dtype)
    kp = jax.random.normal(ks[1], (layers * n, p, hkv * dh),
                           jnp.float32).astype(kv_dtype)
    vp = jax.random.normal(ks[2], (layers * n, p, hkv * dh),
                           jnp.float32).astype(kv_dtype)
    perm = jax.random.permutation(ks[3], n)[: r * mp]
    pt = perm.reshape(r, mp).astype(jnp.int32)
    fk = jax.random.normal(ks[4], (r, qmax, hkv, dh), jnp.float32)
    fv = jax.random.normal(ks[5], (r, qmax, hkv, dh), jnp.float32)
    if ctx_lens is None:
        # page-straddling, non-aligned contexts by construction
        ctx_lens = [(3 + 5 * i) % (mp * p - qmax) for i in range(r)]
    if q_lens is None:
        # the mixed shape: decode row, chunk rows, full row
        q_lens = [1 if i == 0 else min(qmax, 2 + 3 * i) for i in range(r)]
    return (q, kp, vp, pt, jnp.asarray(ctx_lens, jnp.int32),
            jnp.asarray(q_lens, jnp.int32), fk, fv)


def _assert_match(got, want, tol):
    out, kp, vp = got
    out_r, kp_r, vp_r = want
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(out_r, np.float32),
        rtol=tol, atol=tol)
    # writeback is the SAME cast bits to the SAME slots: bit-exact
    np.testing.assert_array_equal(np.asarray(kp).view(np.uint8),
                                  np.asarray(kp_r).view(np.uint8))
    np.testing.assert_array_equal(np.asarray(vp).view(np.uint8),
                                  np.asarray(vp_r).view(np.uint8))


# ------------------------------------------------------ kernel-level parity


@pytest.mark.parametrize("h,hkv,dh", [(4, 4, 64), (4, 2, 64), (8, 2, 64)])
def test_parity_gqa(h, hkv, dh):
    args = _inputs(jax.random.key(0), h=h, hkv=hkv, dh=dh)
    ref = ragged_attention_xla(*args, n_kv_heads=hkv)
    got = ragged_attention_pallas(*args, n_kv_heads=hkv, interpret=True)
    _assert_match(got, ref, 2e-5)


@pytest.mark.parametrize("kv_dtype,tol", [
    (jnp.bfloat16, 2e-2),
    (jnp.float8_e4m3fn, 8e-2),
])
def test_parity_low_precision_pools(kv_dtype, tol):
    args = _inputs(jax.random.key(1), kv_dtype=kv_dtype)
    ref = ragged_attention_xla(*args, n_kv_heads=2)
    got = ragged_attention_pallas(*args, n_kv_heads=2, interpret=True)
    _assert_match(got, ref, tol)


def test_parity_empty_and_masked_rows():
    """q_len=0 rows are inert (zero output, no writeback); q_len<qmax rows
    mask their tail queries and write only q_len fresh tokens."""
    args = _inputs(jax.random.key(2), r=4, qmax=8,
                   ctx_lens=[0, 5, 16, 23], q_lens=[0, 1, 8, 3])
    ref = ragged_attention_xla(*args, n_kv_heads=2)
    got = ragged_attention_pallas(*args, n_kv_heads=2, interpret=True)
    _assert_match(got, ref, 2e-5)
    # inert row's output really is zero
    np.testing.assert_array_equal(np.asarray(got[0][0]), 0.0)


def test_parity_page_straddling_chunks():
    """Fresh chunks whose [ctx, ctx+q) span crosses a page boundary land
    split across two physical pages."""
    # p=8: ctx=6 with q=8 straddles page 0->1; ctx=13 straddles 1->2
    args = _inputs(jax.random.key(3), r=3, qmax=8, p=8, mp=4,
                   ctx_lens=[6, 13, 21], q_lens=[8, 8, 8])
    ref = ragged_attention_xla(*args, n_kv_heads=2)
    got = ragged_attention_pallas(*args, n_kv_heads=2, interpret=True)
    _assert_match(got, ref, 2e-5)


def test_parity_decode_only_and_prefill_only():
    """The ragged kernel degenerates correctly at both ends of the mix."""
    for q_lens in ([1, 1, 1, 1], [8, 8, 8, 8]):
        args = _inputs(jax.random.key(4), q_lens=q_lens)
        ref = ragged_attention_xla(*args, n_kv_heads=2)
        got = ragged_attention_pallas(*args, n_kv_heads=2, interpret=True)
        _assert_match(got, ref, 2e-5)


def test_stacked_layer_pools():
    """layer=1 of 2: the kernel offsets into the stacked pool and leaves
    layer 0 untouched."""
    layers, n = 2, 32
    (q, kp, vp, pt, ctx, qlens, fk, fv) = _inputs(
        jax.random.key(5), n=n, layers=layers)
    kp0 = np.asarray(kp).copy()
    vp0 = np.asarray(vp).copy()
    ref = ragged_attention_xla(q, kp[n:], vp[n:], pt, ctx, qlens, fk, fv,
                               n_kv_heads=2)
    out, kp2, vp2 = ragged_attention_pallas(
        q, kp, vp, pt, ctx, qlens, fk, fv, n_kv_heads=2, interpret=True,
        layer=jnp.int32(1), n_pages_per_layer=n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref[0]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(kp2[n:]), np.asarray(ref[1]))
    np.testing.assert_array_equal(np.asarray(vp2[n:]), np.asarray(ref[2]))
    # layer 0 pools untouched
    np.testing.assert_array_equal(np.asarray(kp2[:n]), kp0[:n])
    np.testing.assert_array_equal(np.asarray(vp2[:n]), vp0[:n])


def test_dispatcher():
    args = _inputs(jax.random.key(6))
    ref = ragged_attention(*args, n_kv_heads=2, impl="xla")
    got = ragged_attention(*args, n_kv_heads=2, impl=IMPL)
    _assert_match(got, ref, 2e-5)
    with pytest.raises(ValueError, match="unknown ragged attention impl"):
        ragged_attention(*args, n_kv_heads=2, impl="nope")


# ------------------------------------------------------- model-level wiring


def _tiny_spec():
    from distributed_inference_engine_tpu.models.base import ModelSpec

    return ModelSpec(
        vocab_size=256, d_model=256, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq_len=128, dtype="float32",
    )


def test_forward_mixed_step_parity():
    from distributed_inference_engine_tpu.models.base import (
        forward_mixed_step,
        init_params,
    )

    spec = _tiny_spec()
    params = init_params(spec, jax.random.key(0))
    L, n, p, mp, r, qmax = spec.n_layers, 16, 8, 4, 3, 8
    fused = spec.n_kv_heads * spec.head_dim
    ks = jax.random.split(jax.random.key(7), 4)
    kp = jax.random.normal(ks[0], (L, n, p, fused), jnp.float32)
    vp = jax.random.normal(ks[1], (L, n, p, fused), jnp.float32)
    pt = jax.random.permutation(ks[2], n)[: r * mp].reshape(r, mp)
    pt = pt.astype(jnp.int32)
    tokens = jax.random.randint(ks[3], (r, qmax), 0, spec.vocab_size,
                                jnp.int32)
    ctx = jnp.asarray([5, 0, 17], jnp.int32)
    qlens = jnp.asarray([1, 8, 3], jnp.int32)
    h_ref, kp_ref, vp_ref = forward_mixed_step(
        spec, params, tokens, ctx, qlens, kp, vp, pt, attn_impl="xla")
    h_got, kp_got, vp_got = forward_mixed_step(
        spec, params, tokens, ctx, qlens, kp, vp, pt, attn_impl=IMPL)
    np.testing.assert_allclose(np.asarray(h_got), np.asarray(h_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(kp_got), np.asarray(kp_ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vp_got), np.asarray(vp_ref),
                               rtol=1e-6, atol=1e-6)


# ------------------------------------------------- config compose validation


def test_validate_prefill_compose():
    from distributed_inference_engine_tpu.config import (
        validate_prefill_compose,
    )

    validate_prefill_compose(0, sp=4)        # no chunking: any sp is fine
    validate_prefill_compose(512, sp=1)      # chunking without sp is fine
    with pytest.raises(ValueError, match="prefill_chunk"):
        validate_prefill_compose(512, sp=2)
    # the message must be actionable: name both escape hatches
    with pytest.raises(ValueError, match="prefill_chunk=0"):
        validate_prefill_compose(512, sp=2)
    with pytest.raises(ValueError, match="sp=1"):
        validate_prefill_compose(512, sp=2)


def test_metadata_loader_rejects_sp_plus_chunk():
    """The deploy-config path fails BEFORE the checkpoint load."""
    from distributed_inference_engine_tpu.config import ModelConfig
    from distributed_inference_engine_tpu.models import engine_from_config

    cfg = ModelConfig(
        name="m", architecture="gpt2", metadata={
            "sp": 2, "prefill_chunk": 512})
    with pytest.raises(ValueError, match="prefill_chunk"):
        engine_from_config(cfg)


def test_ragged_rejects_sliding_window_spec():
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )

    spec = _tiny_spec().replace(sliding_window=16)
    with pytest.raises(ValueError, match="sliding-window"):
        ContinuousEngine(spec, config=EngineConfig(
            attention_impl="pallas-ragged", max_slots=2, max_seq_len=64,
            prefill_buckets=[16], page_size=16, num_pages=16), seed=0)


# ------------------------------------------------------------- engine level


def _mk_engines(extra=None, both=True):
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )

    spec = _tiny_spec()
    base = dict(max_slots=2, max_seq_len=64, prefill_buckets=[16],
                page_size=16, num_pages=16, decode_steps_per_call=4,
                prefill_chunk=16)
    base.update(extra or {})
    xla = ContinuousEngine(spec, config=EngineConfig(
        attention_impl="xla", **base), seed=0)
    if not both:
        return xla, None
    rg = ContinuousEngine(spec, params=xla.params, config=EngineConfig(
        attention_impl=IMPL, **base), seed=0)
    return xla, rg


def _reqs():
    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )

    return [GenerationRequest(
        prompt=[(3 * i + j) % 250 + 1 for j in range(28)],
        max_new_tokens=8, temperature=0.0, request_id=f"long{i}")
        for i in range(2)] + [GenerationRequest(
            prompt=[5, 9, 13], max_new_tokens=8, temperature=0.0,
            request_id="short")]


@pytest.mark.slow
def test_engine_mixed_greedy_equivalence():
    """attn_impl="pallas-ragged_interpret" + chunked prefill: greedy
    output token-for-token identical to the split (alternating) xla
    path, and the mixed dispatch actually engaged."""
    xla, rg = _mk_engines()
    a = {r.request_id: r.tokens for r in xla.generate(_reqs())}
    b = {r.request_id: r.tokens for r in rg.generate(_reqs())}
    assert a == b
    m = rg.get_metrics()
    assert m["mixed_steps"] > 0
    assert m["mixed_prefill_tokens"] > 0
    assert xla.get_metrics()["mixed_steps"] == 0


@pytest.mark.slow
def test_engine_mixed_step_token_budget():
    """mixed_step_tokens throttles prefill rows per step (row-granular,
    always >= 1) without changing greedy output."""
    xla, rg = _mk_engines(extra=dict(mixed_step_tokens=12))
    a = {r.request_id: r.tokens for r in xla.generate(_reqs())}
    b = {r.request_id: r.tokens for r in rg.generate(_reqs())}
    assert a == b
    m = rg.get_metrics()
    # two 12-token tails at a 12-token budget: one row per step, so the
    # budget forces at least two mixed steps
    assert m["mixed_steps"] >= 2


@pytest.mark.slow
def test_engine_compile_count_guard():
    """Bucket audit: a mixed-workload run dispatches a BOUNDED set of
    (prefill-rows bucket, chunk bucket) programs — the jit cache cannot
    grow with the workload."""
    _, rg = _mk_engines()
    assert rg is not None
    rg.generate(_reqs())
    rg.generate(_reqs())               # second wave: no new buckets
    m = rg.get_metrics()
    row_buckets = rg.max_slots.bit_length() + 1   # pow2 row counts
    bound = row_buckets * len(rg._mixed_q_buckets)
    assert 0 < m["mixed_programs"] <= bound
    # and the audit set is the jit-program key set, not a step counter
    assert m["mixed_programs"] <= m["mixed_steps"]
