"""Host-side response/prefix cache with LRU/LFU/FIFO eviction and TTL.

Capability heir of the reference's ``src/kvstore.py:26-236`` (``KVCache``:
eviction policies ``:63-102``, set/get ``:104-164``, batch ops ``:166-176``,
stats ``:206-219``), with the fixes its own test suite demanded: the reference
tests call ``close()``, item access, and context-manager use that the shipped
class never implemented (``tests/test_kvstore.py:14,41,99-104`` — SURVEY.md §4),
and the class claims thread safety (``src/kvstore.py:35``) without any lock.
This implementation ships that full API and takes a real ``threading.RLock``.

This is the *host* cache (responses, prefixes, metadata). The attention-state
KV cache lives in HBM under ``engine/kv_cache.py`` — the north-star
reinterpretation of the same component (BASELINE.json).
"""

from __future__ import annotations

import enum
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, Hashable, Iterable, List, Optional, Tuple


class EvictionPolicy(str, enum.Enum):
    LRU = "lru"
    LFU = "lfu"
    FIFO = "fifo"


@dataclass
class CacheEntry:
    """One cached value (reference ``src/kvstore.py:17-24``)."""

    value: Any
    created_at: float = field(default_factory=time.monotonic)
    last_accessed: float = field(default_factory=time.monotonic)
    ttl: Optional[float] = None
    access_count: int = 0

    def is_expired(self, now: Optional[float] = None) -> bool:
        if self.ttl is None:
            return False
        return (now if now is not None else time.monotonic()) - self.created_at >= self.ttl


class ResponseCache:
    """In-memory cache with pluggable eviction, per-entry TTL, batch ops, and
    hit/miss/eviction stats. Thread-safe.

    Insertion order is tracked by the underlying ``OrderedDict`` (FIFO),
    recency by move-to-end on access (LRU), and frequency by per-entry access
    counts (LFU) — one structure, three policies.
    """

    def __init__(
        self,
        max_size: int = 1024,
        policy: str | EvictionPolicy = EvictionPolicy.LRU,
        default_ttl: Optional[float] = None,
    ) -> None:
        if max_size <= 0:
            raise ValueError("max_size must be positive")
        self.max_size = max_size
        self.policy = EvictionPolicy(policy)
        self.default_ttl = default_ttl
        self._entries: "OrderedDict[Hashable, CacheEntry]" = OrderedDict()
        self._lock = threading.RLock()
        self._closed = False
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._expirations = 0

    # ---------------------------------------------------------------- core

    def set(self, key: Hashable, value: Any, ttl: Optional[float] = None) -> None:
        with self._lock:
            self._check_open()
            if key in self._entries:
                del self._entries[key]
            self._evict_if_needed()
            self._entries[key] = CacheEntry(
                value=value, ttl=ttl if ttl is not None else self.default_ttl
            )

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            self._check_open()
            entry = self._entries.get(key)
            if entry is None:
                self._misses += 1
                return default
            if entry.is_expired():
                del self._entries[key]
                self._expirations += 1
                self._misses += 1
                return default
            entry.last_accessed = time.monotonic()
            entry.access_count += 1
            if self.policy is EvictionPolicy.LRU:
                self._entries.move_to_end(key)
            self._hits += 1
            return entry.value

    def batch_get(self, keys: Iterable[Hashable]) -> Dict[Hashable, Any]:
        """Reference ``src/kvstore.py:166-168`` — only present keys appear."""
        sentinel = object()
        out = {}
        for k in keys:
            v = self.get(k, sentinel)
            if v is not sentinel:
                out[k] = v
        return out

    def batch_set(
        self, items: Dict[Hashable, Any], ttl: Optional[float] = None
    ) -> None:
        for k, v in items.items():
            self.set(k, v, ttl)

    def delete(self, key: Hashable) -> bool:
        with self._lock:
            self._check_open()
            if key in self._entries:
                del self._entries[key]
                return True
            return False

    def clear(self) -> int:
        with self._lock:
            self._check_open()
            n = len(self._entries)
            self._entries.clear()
            return n

    # ------------------------------------------------------------ eviction

    def _evict_if_needed(self) -> None:
        # expired entries go first — evicting them is free capacity
        while len(self._entries) >= self.max_size:
            expired = self._pick_expired()
            victim = expired if expired is not None else self._pick_victim()
            if victim is None:
                return
            del self._entries[victim]
            if expired is not None:
                self._expirations += 1   # TTL churn, not capacity pressure
            else:
                self._evictions += 1

    def _pick_expired(self) -> Optional[Hashable]:
        now = time.monotonic()
        for k, e in self._entries.items():
            if e.is_expired(now):
                return k
        return None

    def _pick_victim(self) -> Optional[Hashable]:
        if not self._entries:
            return None
        if self.policy in (EvictionPolicy.LRU, EvictionPolicy.FIFO):
            # LRU: least-recently-used is at the front (move_to_end on access).
            # FIFO: insertion order is the front (never reordered).
            return next(iter(self._entries))
        # LFU: smallest access count; ties broken by age (iteration order)
        return min(self._entries.items(), key=lambda kv: kv[1].access_count)[0]

    # --------------------------------------------------------- persistence

    # reserved single-key dict forms the tagged encoding emits; a USER dict
    # that happens to be exactly one of these shapes is wrapped in __esc__
    # so it round-trips as a dict instead of silently decoding as a tag
    _TAGS = frozenset({"__tuple__", "__esc__"})

    @staticmethod
    def _enc(obj):
        """JSON-safe tagged encoding of keys/values: tuples become
        ``{"__tuple__": [...]}`` (cache keys are tuples of model/version/
        prompt-token tuples); everything else must already be JSON
        (dict-with-str-keys / list / str / numbers / bool / None)."""
        if isinstance(obj, tuple):
            return {"__tuple__": [ResponseCache._enc(x) for x in obj]}
        if isinstance(obj, list):
            return [ResponseCache._enc(x) for x in obj]
        if isinstance(obj, dict):
            if any(not isinstance(k, str) for k in obj):
                raise TypeError("dict keys must be str for a JSON snapshot")
            enc = {k: ResponseCache._enc(v) for k, v in obj.items()}
            if len(enc) == 1 and next(iter(enc)) in ResponseCache._TAGS:
                return {"__esc__": enc}          # collider dict, escaped
            return enc
        if obj is None or isinstance(obj, (str, int, float, bool)):
            return obj
        raise TypeError(
            f"{type(obj).__name__} is not JSON-snapshot-serializable; "
            "pass format='pickle' (trusted snapshot dirs only)")

    @staticmethod
    def _dec(obj):
        if isinstance(obj, dict):
            if set(obj) == {"__tuple__"}:
                return tuple(ResponseCache._dec(x) for x in obj["__tuple__"])
            if set(obj) == {"__esc__"}:
                # escaped collider: the inner dict's values were encoded
                # but the dict itself is data, not a tag
                return {k: ResponseCache._dec(v)
                        for k, v in obj["__esc__"].items()}
            return {k: ResponseCache._dec(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [ResponseCache._dec(x) for x in obj]
        return obj

    def save(self, path: str, format: str = "json") -> int:
        """Persist live entries to ``path`` (the "optional persistence" the
        reference README declares for its KV store but never implements —
        ``/root/reference/README.md:14,90``). Returns entries written.

        TTLs are stored as REMAINING seconds: ``created_at`` is
        ``time.monotonic()``, which is meaningless across processes, so an
        entry with 30 s left saves as 30 and its clock restarts on load.
        Expired entries are dropped at save. Written atomically so a crash
        mid-write can't corrupt a previous snapshot.

        ``format="json"`` (default) writes a non-executable snapshot —
        loading one can't run code, whatever wrote the file. Tuple keys
        round-trip via a tagged encoding; values must be JSON-shaped (the
        coordinator's response payloads are — they travel the framed JSON
        RPC). ``format="pickle"`` handles arbitrary payloads but executes
        arbitrary code at load: use it only when the snapshot path is
        writable by the operator alone, and pass ``allow_pickle=True`` to
        ``load`` to acknowledge that trust boundary (ADVICE r2)."""
        from ..utils.files import atomic_write

        with self._lock:
            self._check_open()
            now = time.monotonic()
            rows = []
            for k, e in self._entries.items():   # preserves eviction order
                if e.is_expired(now):
                    continue
                remaining = (None if e.ttl is None
                             else max(0.0, e.ttl - (now - e.created_at)))
                rows.append((k, e.value, remaining, e.access_count))
        payload = {"version": 1, "policy": self.policy.value, "rows": rows}
        if format == "json":
            import json

            payload["rows"] = [self._enc(list(r)) for r in rows]
            blob = json.dumps(payload).encode()
            atomic_write(path, lambda f: f.write(blob), binary=True)
        elif format == "pickle":
            import pickle

            atomic_write(path, lambda f: pickle.dump(payload, f),
                         binary=True)
        else:
            raise ValueError(f"unknown snapshot format {format!r}")
        return len(rows)

    def load(self, path: str, allow_pickle: bool = False) -> int:
        """Restore a ``save`` snapshot into this cache: loaded keys
        overwrite, other existing entries are kept, capacity eviction
        applies normally. Entries whose remaining TTL reached zero are
        skipped. Returns entries restored.

        The format is detected from the file. Pickle snapshots load only
        with ``allow_pickle=True``: unpickling executes code from the
        file, so the caller must vouch that the snapshot path is
        operator-only writable (see ``save``)."""
        with open(path, "rb") as f:
            head = f.read(1)
            blob = head + f.read()
        if head == b"{":
            import json

            payload = json.loads(blob)
            payload["rows"] = [self._dec(r) for r in payload["rows"]]
        else:
            if not allow_pickle:
                raise ValueError(
                    f"cache snapshot {path!r} is a pickle; loading one "
                    "executes code from the file. Pass allow_pickle=True "
                    "only if the snapshot dir is operator-only writable.")
            import pickle

            payload = pickle.loads(blob)
        version = payload.get("version")
        if version != 1:
            # the version field exists exactly so a format bump fails with
            # a clear message, not an unpack error deep in the row loop
            raise ValueError(
                f"cache snapshot {path!r} has format version {version!r}; "
                "this build reads version 1")
        n = 0
        with self._lock:
            self._check_open()
            for k, value, remaining, access_count in payload["rows"]:
                if remaining is not None and remaining <= 0:
                    continue
                if k in self._entries:
                    del self._entries[k]
                self._evict_if_needed()
                entry = CacheEntry(value=value, ttl=remaining)
                entry.access_count = access_count
                self._entries[k] = entry
                n += 1
        return n

    # --------------------------------------------------------------- stats

    def get_stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "size": len(self._entries),
                "max_size": self.max_size,
                "policy": self.policy.value,
                "hits": self._hits,
                "misses": self._misses,
                "hit_rate": (self._hits / total) if total else 0.0,
                "evictions": self._evictions,
                "expirations": self._expirations,
            }

    # ------------------------------------------------- dunder / lifecycle

    def _check_open(self) -> None:
        if self._closed:
            raise RuntimeError("cache is closed")

    def close(self) -> None:
        with self._lock:
            self._entries.clear()
            self._closed = True

    def __enter__(self) -> "ResponseCache":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def __getitem__(self, key: Hashable) -> Any:
        sentinel = object()
        v = self.get(key, sentinel)
        if v is sentinel:
            raise KeyError(key)
        return v

    def __setitem__(self, key: Hashable, value: Any) -> None:
        self.set(key, value)

    def __delitem__(self, key: Hashable) -> None:
        if not self.delete(key):
            raise KeyError(key)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            self._check_open()
            entry = self._entries.get(key)
            if entry is None:
                return False
            if entry.is_expired():
                del self._entries[key]
                self._expirations += 1
                return False
            return True

    def __len__(self) -> int:
        """Live entry count; sweeps expired entries first (reference
        ``src/kvstore.py:230-236`` semantics)."""
        with self._lock:
            self._check_open()
            now = time.monotonic()
            dead = [k for k, e in self._entries.items() if e.is_expired(now)]
            for k in dead:
                del self._entries[k]
                self._expirations += 1
            return len(self._entries)

    def keys(self) -> List[Hashable]:
        with self._lock:
            self._check_open()
            return list(self._entries.keys())


# Aliases matching the reference's public names (``src/kvstore.py:238-240``).
KVStore = ResponseCache
create_kv_store = ResponseCache
