"""Engine warmup: pre-compiling the bucketed serving programs at load time
(metadata warmup=1) so the first real request doesn't pay the 20-40 s XLA
compile the TPU charges for each new shape."""

import numpy as np

from distributed_inference_engine_tpu.config import EngineConfig, ModelConfig
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.disagg import PrefillEngine
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=256).replace(dtype="float32")


def test_static_engine_warmup_then_generate():
    eng = Engine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=256, prefill_buckets=[16, 64],
        decode_steps_per_call=4))
    # (batch buckets {1,2}) x (prefill buckets {16,64}) = 4 rounds
    assert eng.warmup() == 4
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=5)])[0]
    assert len(out.tokens) == 5


def test_continuous_warmup_returns_all_pages():
    eng = ContinuousEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=128, prefill_buckets=[16, 64],
        page_size=16, num_pages=24, decode_steps_per_call=4))
    rounds = eng.warmup()
    # (admission buckets {1,2}) x (prefill buckets {16,64,128}) = 6 rounds
    assert rounds == 6
    m = eng.get_metrics()
    # every round ran ONE batched admission: a repeated warmup prompt
    # would hit the prefix cache and leave the batched programs cold
    assert m["prefill_calls"] == rounds
    assert m["prefix_hit_admissions"] == 0
    stats = eng.kv.get_stats()
    assert stats["live_slots"] == 0
    assert eng.n_live == 0 and eng.n_waiting == 0
    out = eng.generate([GenerationRequest(prompt=[5, 6, 7],
                                          max_new_tokens=4)])[0]
    assert len(out.tokens) == 4


def test_prefill_engine_warmup():
    eng = PrefillEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=256, prefill_buckets=[16]))
    assert eng.warmup() >= 1
    h = eng.prefill([GenerationRequest(prompt=[1, 2, 3], max_new_tokens=2,
                                       request_id="r")])[0]
    assert h.prompt_len == 3


def test_worker_metadata_warmup(tmp_path):
    import asyncio

    from distributed_inference_engine_tpu.cluster.worker import WorkerServer
    from distributed_inference_engine_tpu.config import ServerConfig

    async def main():
        w = WorkerServer(ServerConfig(worker_id="w", port=0))
        await w.start()
        await w.load_model_async(ModelConfig(
            name="m", architecture="llama-tiny", dtype="float32",
            max_batch_size=2, max_seq_len=128,
            metadata={"continuous": 1, "page_size": 16,
                      "prefill_buckets": [16], "warmup": 1}))
        eng = w.engines["m"]
        # warmup traffic ran through the engine before any request
        assert eng.get_metrics()["total_requests"] >= 2
        await w.stop()

    asyncio.run(main())
