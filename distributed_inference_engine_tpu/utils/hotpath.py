"""Marker decorator for latency-critical dispatch entry points.

``@hot_path`` is a no-op at runtime. Its job is static: it seeds
graftlint's call-graph reachability walk (scripts/graftlint), so every
function transitively callable from a decorated entry point is checked
for device→host sync reads, per-request jit wrapping, and unbucketed
shapes. Decorate the OUTERMOST per-step/per-request dispatch method of
an engine — not internal helpers, which the walk discovers on its own.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)


def hot_path(fn: F) -> F:
    """Mark ``fn`` as a serving hot-path entry point (see module doc)."""
    fn.__graftlint_hot_path__ = True
    return fn
