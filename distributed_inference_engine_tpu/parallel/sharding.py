"""Sharding specs: how the model lays out over the mesh.

The TPU reinterpretation of the reference's hash-sharded placement
(``src/model_registry.py:149-161``, SURVEY.md §2.3): there a "shard" is a
worker holding a copy; here a shard is a slice of the tensor math itself,
and the registry's ``ModelShard.mesh_axes``/``partition_spec`` fields record
which recipe a placement uses.

Megatron-style tensor parallelism, expressed as ``PartitionSpec`` trees that
GSPMD propagates (per the scaling-book recipe: annotate params + a few
activation constraints, let XLA insert the collectives):

- attention: QKV projections column-sharded over ``tp`` (heads split),
  output projection row-sharded (psum inserted by XLA after ``wo``);
- MLP: up/gate column-sharded, down row-sharded (one psum per block);
- embeddings/LM head: vocab-sharded over ``tp`` (logits all-gather at the
  end — once per step, off the per-layer critical path);
- KV cache: ``n_kv_heads`` over ``tp``, slots over ``dp``, SEQUENCE over
  ``sp`` — each chip holds only its heads' share of its sequence shard, so
  per-chip KV HBM drops with tp·sp and decode runs context-parallel
  (GSPMD lowers the sharded-S softmax/contraction to all-reduces);
- norms: replicated (tiny).

``ep`` is reserved for MoE expert sharding; ``pp`` for stage-split layers
(the stacked ``[n_layers, ...]`` leading axis is exactly what pp will split).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.base import ModelSpec, Params

REPLICATED = P()


def param_pspecs(spec: ModelSpec) -> Dict[str, Any]:
    """PartitionSpec tree matching ``init_params``' structure.

    Leading block axis is the layer stack (pp's future split dim); attention
    and MLP projections shard their feature dims over ``tp``.
    """
    blocks: Dict[str, P] = {
        "ln1_scale": P(), "ln2_scale": P(),
        # column-parallel: output features over tp
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        # row-parallel: input features over tp (XLA psums the partial sums)
        "wo": P(None, "tp", None),
    }
    if spec.n_experts:
        # expert axis over ep (GSPMD lowers the dispatch einsum to the
        # all-to-all); inside each expert the FFN dims still shard over tp.
        blocks["w_router"] = P()
        blocks["w_up"] = P(None, "ep", None, "tp")
        blocks["w_down"] = P(None, "ep", "tp", None)
        if spec.mlp == "swiglu":
            blocks["w_gate"] = P(None, "ep", None, "tp")
    else:
        blocks["w_up"] = P(None, None, "tp")
        blocks["w_down"] = P(None, "tp", None)
        if spec.mlp == "swiglu":
            blocks["w_gate"] = P(None, None, "tp")
    if spec.norm == "layernorm":
        blocks["ln1_bias"] = P()
        blocks["ln2_bias"] = P()
    if spec.use_bias:
        blocks.update({
            "bq": P(None, "tp"), "bk": P(None, "tp"), "bv": P(None, "tp"),
            "bo": P(), "b_up": P(None, "tp"), "b_down": P(),
        })
    tree: Dict[str, Any] = {
        "tok_emb": P("tp", None),          # vocab-sharded
        "blocks": blocks,
        "lnf_scale": P(),
    }
    if spec.norm == "layernorm":
        tree["lnf_bias"] = P()
    if spec.pos_emb == "learned":
        tree["pos_emb"] = P()
    if not spec.tie_embeddings:
        tree["lm_head"] = P(None, "tp")    # vocab-sharded logits
    return tree


def kv_cache_pspec() -> P:
    """[L, B, S, Hkv, Dh]: slots over dp, SEQUENCE over sp, kv heads over
    tp. The sp split makes decode context-parallel: per-chip attention
    covers its sequence shard and GSPMD lowers the softmax max/sum and the
    probs·V contraction over S to local work + all-reduces — long-context
    decode HBM and reads scale 1/sp per chip with no hand-written
    collectives (the ring/blockwise alternative only pays off once the
    per-step all-reduce latency beats 1/sp of the cache read, i.e. far
    beyond single-host contexts)."""
    return P(None, "dp", "sp", "tp", None)


def paged_kv_pspec() -> P:
    """[L, num_pages, page_size, Hkv*Dh] page pools: the fused head·dim
    axis shards over tp (head boundaries align because tp must divide
    Hkv), so each chip's pool holds only its heads' pages — per-chip KV
    HBM drops linearly with tp, same as the contiguous layout."""
    return P(None, None, None, "tp")


def batch_pspec() -> P:
    """[B, T] token batches: batch over dp, sequence over sp."""
    return P("dp", "sp")


@dataclass
class ModelShardings:
    """Bundle of mesh + concrete NamedShardings for one model."""

    mesh: Mesh
    params: Any              # pytree of NamedSharding
    kv: NamedSharding
    paged_kv: NamedSharding
    batch: NamedSharding
    replicated: NamedSharding

    @classmethod
    def build(cls, spec: ModelSpec, mesh: Mesh) -> "ModelShardings":
        pspecs = param_pspecs(spec)
        named = jax.tree.map(
            lambda p: NamedSharding(mesh, p), pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )
        return cls(
            mesh=mesh,
            params=named,
            kv=NamedSharding(mesh, kv_cache_pspec()),
            paged_kv=NamedSharding(mesh, paged_kv_pspec()),
            batch=NamedSharding(mesh, batch_pspec()),
            replicated=NamedSharding(mesh, REPLICATED),
        )

    def shard_fn(self):
        """A ``params -> sharded params`` function for ``Engine(shard_fn=…)``."""
        return lambda params: shard_params(params, self)


def compatible_sharding(base: NamedSharding, shape) -> NamedSharding:
    """``base`` with every axis whose mesh size doesn't divide its dim
    DROPPED (replicated) — a per-axis fallback for runtime-shaped arrays.

    The engines size KV caches per batch (batch bucket, padded seq cap);
    a single-request batch (bb=1) can't shard over dp=2, but that must
    not cost the sequence split — the 1/sp per-chip HBM scaling is the
    point of context-parallel decode. All-or-nothing fallback would.
    """
    spec = list(base.spec) + [None] * (len(shape) - len(base.spec))
    new = []
    for dim, axes in zip(shape, spec):
        if axes is None:
            new.append(None)
            continue
        names = (axes,) if isinstance(axes, str) else axes
        # a user-built mesh may lack an axis make_mesh always names (e.g.
        # no "sp"): a missing axis is dropped from the spec — replicated —
        # instead of a KeyError at first generate (ADVICE r2)
        present = [nm for nm in names if nm in base.mesh.shape]
        size = 1
        for nm in present:
            size *= base.mesh.shape[nm]
        if present and size and dim % size == 0:
            new.append(present[0] if len(present) == 1 else tuple(present))
        else:
            new.append(None)
    return NamedSharding(base.mesh, P(*new))


def scale_sharding(scale_shape, weight_sharding: NamedSharding) -> NamedSharding:
    """Sharding for an int8 weight's per-channel scale: the weight's spec
    with every axis over a size-1 (contracted, keepdims) dim dropped.

    Output channels keep the weight's placement — e.g. a column-parallel
    ``wq`` [L, D, H·Dh]@P(∅,∅,tp) gives its scale [L, 1, H·Dh] the same
    tp split, so the fused ``y * scale`` in ``ops.quant.matmul_any`` is
    chip-local; a row-parallel ``wo``'s scale [L, 1, D] drops the tp axis
    (its contracted dim is the sharded one) and replicates.
    """
    spec = list(weight_sharding.spec) + [None] * (
        len(scale_shape) - len(weight_sharding.spec))
    new = [None if scale_shape[d] == 1 else spec[d]
           for d in range(len(scale_shape))]
    return NamedSharding(weight_sharding.mesh, P(*new))


def shard_params(params: Params, shardings: ModelShardings) -> Params:
    """Place a param tree onto the mesh per the spec tree.

    ``QuantizedTensor`` leaves (weight-only int8, ``ops/quant.py``) place
    their int8 payload exactly like the bf16 weight would and derive the
    scale's sharding from it — the VERDICT r1 "sharding recipe" that lets
    ``quantized`` compose with tp/sp/dp.

    Divisibility guard: a tp-sharded dim that doesn't divide by the axis size
    is a config error worth a clear message (XLA's would be cryptic).
    """
    from ..ops.quant import QuantizedTensor

    def place_arr(x, s: NamedSharding):
        for dim, axes in enumerate(s.spec):
            if axes is None:
                continue
            names = (axes,) if isinstance(axes, str) else axes
            size = 1
            for nm in names:
                size *= s.mesh.shape[nm]
            if x.shape[dim] % size:
                raise ValueError(
                    f"dim {dim} of shape {x.shape} not divisible by mesh "
                    f"axes {names} (size {size})"
                )
        return jax.device_put(x, s)

    def place(x, s: NamedSharding):
        if isinstance(x, QuantizedTensor):
            import dataclasses

            # replace, not reconstruct: bits/pack_axis aux must survive
            # placement (an int4 tree rebuilt as default-int8 would feed
            # a contraction-halved payload to the int8 matmul path)
            return dataclasses.replace(
                x,
                q=place_arr(x.q, s),
                s=jax.device_put(x.s, scale_sharding(x.s.shape, s)),
            )
        return place_arr(x, s)

    return jax.tree.map(place, params, shardings.params,
                        is_leaf=lambda x: isinstance(x, QuantizedTensor))
