"""Fleet flight-recorder tests (-m slo): typed event rings (wrap
mid-capture, canonical sequences), clock-sync merged-trace monotonicity
under mixed-sign offsets, SLO burn-rate engine windows + ledger
determinism, post-mortem bundle round-trip, and one live-fleet
integration pass (events verb -> fleet trace -> bundle with the dead
worker's ring preserved).

The unit tests drive obs/{events,clocksync,slo,postmortem} directly with
synthetic clocks and tracks — no RPC plumbing — which is exactly the
testability contract those modules advertise. The integration test
reuses the test_chaos fleet harness (fake continuous engines, crc32
token chain) so event content is seed-deterministic.
"""

import asyncio
import json
import os
import time

import pytest

from distributed_inference_engine_tpu.api.coordinator import (
    Coordinator,
    CoordinatorConfig,
)
from distributed_inference_engine_tpu.cluster.worker import WorkerServer
from distributed_inference_engine_tpu.config import ModelConfig, ServerConfig
from distributed_inference_engine_tpu.obs import clocksync
from distributed_inference_engine_tpu.obs import postmortem as pm
from distributed_inference_engine_tpu.obs.events import (
    EVENTS,
    EventLog,
    canonical_from_snapshot,
)
from distributed_inference_engine_tpu.obs.slo import (
    BurnObjective,
    BurnRateEngine,
    violations_from_buckets,
)

pytestmark = pytest.mark.slo


# ------------------------------------------------------------ event rings

def test_emit_unknown_type_raises():
    log = EventLog("p")
    with pytest.raises(ValueError):
        log.emit("totally.fake_event", x=1)
    assert len(log) == 0, "a rejected emit must not land"


def test_event_catalog_shape():
    assert EVENTS, "catalog must be non-empty"
    for name, help_text in EVENTS.items():
        assert "." in name and name == name.lower()
        assert help_text.strip(), f"{name} needs a help string"


def test_ring_wrap_mid_capture():
    """Overflowing the ring drops the OLDEST events, counts the drops,
    and keeps ``seq`` global — so a wrap is visible as a gap at the
    front of the snapshot rather than silent truncation."""
    log = EventLog("p", capacity=4)
    for i in range(10):
        log.emit("admission.accept", request_id=f"r{i}")
    snap = log.snapshot()
    assert snap["seq"] == 10
    assert snap["dropped"] == 6
    assert len(snap["events"]) == 4
    seqs = [e["seq"] for e in snap["events"]]
    assert seqs == [6, 7, 8, 9], "gap 0..5 visible at the front"
    stats = log.get_stats()
    assert stats["events_emitted"] == 10
    assert stats["events_dropped"] == 6
    assert stats["events_buffered"] == 4


def test_canonical_sequence_ignores_timestamps():
    a, b = EventLog("a"), EventLog("b")
    for log in (a, b):
        log.emit("drain.begin", worker_id="w0")
        time.sleep(0.002)  # force differing stamps between the two logs
        log.emit("fabric.export", model="m", pages=3)
        log.emit("admission.reject", request_id="r1", reason="inbox_full")
    assert a.canonical_sequence() == b.canonical_sequence()
    # snapshot round trip (the RPC / bundle path) preserves the sequence
    assert canonical_from_snapshot(a.snapshot()) == a.canonical_sequence()
    # ...and the raw records DO differ in their timestamps
    ta = [e["t_mono"] for e in a.events()]
    tb = [e["t_mono"] for e in b.events()]
    assert ta != tb


def test_canonical_sequence_nested_args_hashable():
    log = EventLog("p")
    log.emit("model.stage", model="m", detail={"z": [1, 2], "a": "x"})
    ((etype, args),) = log.canonical_sequence()
    assert etype == "model.stage"
    assert hash(args) is not None, "canonical form must be hashable"


# -------------------------------------------------------------- clock sync

async def test_estimate_offset_min_rtt_sample_wins():
    """The estimate must track a large synthetic remote offset to within
    half the best round trip, and the jitter filter must prefer the
    fast sample."""
    OFF = 1234.5

    calls = {"n": 0}

    async def ping():
        calls["n"] += 1
        # every other round trip is fat: the filter should ignore them
        await asyncio.sleep(0.05 if calls["n"] % 2 == 0 else 0.0)
        return {"mono": time.perf_counter() + OFF}

    est = await clocksync.estimate_offset(ping, samples=6)
    assert est["samples"] == 6.0
    assert est["rtt_s"] < 0.05, "min-RTT sample must win"
    assert abs(est["offset_s"] - OFF) <= max(est["rtt_s"], 0.02)


async def test_estimate_offset_tolerates_missing_mono():
    async def old_worker_ping():
        return {"status": "ok"}          # pre-flight-recorder pong

    est = await clocksync.estimate_offset(old_worker_ping, samples=3)
    assert est == {"offset_s": 0.0, "rtt_s": 0.0, "samples": 0.0}


def _per_track_ts(trace):
    """Group emitted (non-metadata) events by (pid, tid) -> [ts...]."""
    tracks = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") == "M":
            continue
        tracks.setdefault((ev["pid"], ev["tid"]), []).append(ev["ts"])
    return tracks


def test_merge_mixed_sign_offsets_per_track_monotone():
    """Tracks whose clocks run AHEAD (+offset) and BEHIND (−offset) of
    the coordinator must both come out per-track monotone, on one
    shared non-negative epoch."""
    def ring(base, n):
        return [{"type": "admission.accept", "t_mono": base + 0.01 * i,
                 "args": {"i": i}} for i in range(n)]

    tracks = [
        {"name": "coordinator", "offset_s": 0.0, "events": ring(100.0, 5),
         "spans": [{"name": "request", "t": 100.001, "dur": 0.03,
                    "args": {}}]},
        {"name": "w0", "offset_s": +0.5, "events": ring(100.5, 5),
         "steps": [{"name": "decode_step", "t": 100.51, "dur": 0.002,
                    "args": {"step": 1}}]},
        {"name": "w1", "offset_s": -0.5, "events": ring(99.5, 5)},
    ]
    trace = clocksync.merge_fleet_trace(tracks, label="mixed")
    assert trace["metadata"]["tracks"] == 3
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"coordinator", "w0", "w1"}
    per = _per_track_ts(trace)
    assert per, "merged trace must contain emitted events"
    for key, stamps in per.items():
        assert stamps == sorted(stamps), f"track {key} not monotone"
        assert all(ts >= 0.0 for ts in stamps), "epoch must be global min"
    # the corrected w0/w1 rings line up with the coordinator's:
    # all three started at corrected t=100.0 -> identical first stamps
    firsts = {k: v[0] for k, v in per.items()
              if k[1] == clocksync.TID_EVENTS}
    assert len(set(round(t, 3) for t in firsts.values())) == 1


def test_merge_zero_event_ring_and_dump(tmp_path):
    """An empty fleet (or a worker with an empty ring) still merges to a
    valid, loadable trace — metadata-only, zero events."""
    trace = clocksync.merge_fleet_trace(
        [{"name": "w0", "offset_s": 0.2}], label="empty")
    assert trace["metadata"]["events"] == 0
    assert all(e["ph"] == "M" for e in trace["traceEvents"])

    path = str(tmp_path / "trace.json")
    clocksync.dump_trace(path, trace)
    with open(path) as f:
        loaded = json.load(f)
    assert loaded == trace


def test_spans_from_trace_marks():
    t0 = time.monotonic()
    marks = {"received": t0, "routed": t0 + 0.01, "dispatched": t0 + 0.02,
             "merged": t0 + 0.05, "responded": t0 + 0.06}
    spans = clocksync.spans_from_trace_marks(marks, request_id="r1")
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"request", "admit", "route", "dispatch"}
    assert by_name["request"]["args"]["request_id"] == "r1"
    assert abs(by_name["request"]["dur"] - 0.06) < 1e-6
    assert all(s["dur"] >= 0.0 for s in spans)
    assert clocksync.spans_from_trace_marks({}) == []


# ---------------------------------------------------------- burn-rate SLO

def test_violations_from_buckets_snaps_to_grid():
    buckets = {"0.1": 5.0, "0.5": 8.0, "+Inf": 10.0}
    assert violations_from_buckets(buckets, 10.0, 0.5) == 2.0
    # off-grid target snaps UP to the covering bound (conservative)
    assert violations_from_buckets(buckets, 10.0, 0.2) == 2.0
    assert violations_from_buckets(buckets, 10.0, 0.05) == 5.0
    assert violations_from_buckets(buckets, 0.0, 0.5) == 0.0
    assert violations_from_buckets({}, 10.0, 0.5) == 0.0


def test_burn_engine_requires_both_windows():
    """A fast-window spike alone must NOT engage the breach; only when
    the slow window confirms does the ledger record burn_on, and a
    clean fast window clears it."""
    eng = BurnRateEngine([BurnObjective("ttft", goal=0.9)],
                         fast_ticks=1, slow_ticks=4, threshold=3.0)
    for _ in range(3):
        assert eng.observe({"ttft": (10.0, 0.0)}) == []
    # tick 4: fast burn = (10/10)/0.1 = 10 >= 3, slow = (10/40)/0.1
    # = 2.5 < 3 -> fast alone is not enough
    assert eng.observe({"ttft": (10.0, 10.0)}) == []
    assert not eng.breached()
    # tick 5: slow = (20/40)/0.1 = 5 >= 3 -> breach engages
    (t_on,) = eng.observe({"ttft": (10.0, 10.0)})
    assert t_on == {"objective": "ttft", "event": "burn_on"}
    assert eng.breached() and eng.breached_objectives() == ["ttft"]
    # a clean tick empties the 1-tick fast window -> breach clears
    (t_off,) = eng.observe({"ttft": (10.0, 0.0)})
    assert t_off == {"objective": "ttft", "event": "burn_off"}
    assert not eng.breached()
    assert eng.ledger() == [t_on, t_off]


def test_burn_engine_clamps_and_empty_ticks():
    eng = BurnRateEngine([BurnObjective("ok", goal=0.5)],
                         fast_ticks=2, slow_ticks=2, threshold=1.0)
    # bad > total must clamp to total (rate caps at 1.0, never above)
    eng.observe({"ok": (4.0, 9.0)})
    assert eng.burn_rate("ok", fast=True) == pytest.approx(2.0)
    assert eng.breached()
    # missing objective = empty tick; windows still advance, so the
    # breach ages out as the bad tick scrolls off the 2-tick rings
    eng.observe({})
    eng.observe({})
    assert eng.burn_rate("ok", fast=True) == 0.0
    assert not eng.breached()
    assert [e["event"] for e in eng.ledger()] == ["burn_on", "burn_off"]


def test_burn_ledger_deterministic_and_timestamp_free():
    feed = [(10.0, 0.0)] * 3 + [(10.0, 10.0)] * 4 + [(10.0, 0.0)] * 5

    def run():
        eng = BurnRateEngine([BurnObjective("ttft", goal=0.9)],
                             fast_ticks=2, slow_ticks=6, threshold=1.0)
        for total, bad in feed:
            eng.observe({"ttft": (total, bad)})
        return eng

    a, b = run(), run()
    assert a.ledger() == b.ledger() and a.ledger()
    for entry in a.ledger():
        assert set(entry) == {"objective", "event"}, \
            "ledger entries must stay timestamp- and tick-free"
    assert a.get_stats()["objectives"]["ttft"]["transitions"] == \
        len(a.ledger())


# -------------------------------------------------------------- post-mortem

def _ring_snap(proc, n=2):
    log = EventLog(proc)
    for i in range(n):
        log.emit("admission.accept", request_id=f"{proc}-r{i}")
    return log.snapshot()


def test_bundle_round_trip(tmp_path):
    trace = clocksync.merge_fleet_trace(
        [{"name": "coordinator", "offset_s": 0.0,
          "events": [{"type": "drain.begin", "t_mono": 1.0,
                      "args": {"worker_id": "w0"}}]}])
    bundle = pm.write_bundle(
        str(tmp_path), "chaos_hard_kill",
        trace=trace,
        metrics_text="# TYPE up gauge\nup 1\n",
        event_rings={"coordinator": _ring_snap("coordinator")},
        dead_rings={"w1": _ring_snap("w1", n=3)},
        fault_ledger=[("w1", "server", "generate", 0, "kill")],
        dead_workers=("w1",),
        extra={"seed": 42},
    )
    back = pm.read_bundle(bundle)
    man = back["manifest"]
    assert man["reason"] == "chaos_hard_kill"
    assert man["dead_workers"] == ["w1"]
    assert man["files"] == sorted(["trace.json", "metrics.prom",
                                   "rings.json", "dead_rings.json",
                                   "faults.json"])
    assert man["counts"]["faults"] == 1
    assert man["extra"]["seed"] == 42
    assert back["trace"]["metadata"]["events"] == 1
    assert back["metrics"].startswith("# TYPE up")
    # the dead worker's LAST-KNOWN ring survives, canonical-comparable
    assert canonical_from_snapshot(back["dead_rings"]["w1"]) == \
        canonical_from_snapshot(_ring_snap("w1", n=3))
    assert back["faults"] == [["w1", "server", "generate", 0, "kill"]]
    assert pm.list_bundles(str(tmp_path)) == [bundle]


def test_bundle_writes_only_provided_payloads(tmp_path):
    bundle = pm.write_bundle(str(tmp_path), "crashloop_open")
    assert sorted(os.listdir(bundle)) == ["manifest.json"]
    back = pm.read_bundle(bundle)
    assert back["manifest"]["files"] == []
    assert back["manifest"]["counts"] == {
        "trace_events": 0, "rings": 0, "dead_rings": 0, "faults": 0}


def test_bundle_name_collision_gets_counter(tmp_path):
    a = pm.write_bundle(str(tmp_path), "same reason!")
    b = pm.write_bundle(str(tmp_path), "same reason!")
    assert a != b
    assert os.path.basename(b).startswith(os.path.basename(a))
    assert len(pm.list_bundles(str(tmp_path))) == 2
    assert pm.list_bundles(str(tmp_path / "nope")) == []


# ------------------------------------------------- live-fleet integration

async def _start_fleet(n_workers):
    coord = Coordinator(CoordinatorConfig(retry_seed=7,
                                          retry_backoff_base_s=0.01))
    await coord.start()
    cfg = ModelConfig(name="m", architecture="fake",
                      metadata={"continuous": 1, "max_slots": 4})
    workers = {}
    for i in range(n_workers):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=f"w{i}"))
        host, port = await w.start()
        workers[f"w{i}"] = w
        coord.add_worker(f"w{i}", host, port)
    await coord.deploy_model(cfg)
    return coord, workers


async def test_fleet_events_trace_and_postmortem(tmp_path):
    """End to end on a live 2-worker fleet: requests emit ring events,
    the events verb collects them, the merged trace carries one track
    per process with monotone corrected stamps, and a post-mortem after
    a hard kill preserves the dead worker's last-known ring."""
    coord, workers = await _start_fleet(2)
    try:
        for i in range(4):
            r = await coord.submit("m", prompt=[10 + i, 2],
                                   max_new_tokens=3)
            assert r["tokens"], "fake engine must produce tokens"

        await coord.estimate_offsets()
        rings = await coord.collect_events()
        assert set(rings) == {"w0", "w1"}
        accepted = [
            e for snap in rings.values()
            for e in snap["ring"]["events"]
            if e["type"] == "admission.accept"]
        assert len(accepted) == 4, "every admit must land in some ring"

        trace = await coord.fleet_trace(label="itest")
        names = {e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("name") == "process_name"}
        assert {"coordinator", "w0", "w1"} <= names
        for key, stamps in _per_track_ts(trace).items():
            assert stamps == sorted(stamps), f"track {key} not monotone"

        # hard-kill w1 AFTER collection: its cached ring is now the only
        # copy, which the bundle must preserve under dead_rings
        await workers["w1"].stop()
        bundle = await coord.write_postmortem(
            "itest_kill", dead_workers=("w1",), dir_path=str(tmp_path))
        assert bundle is not None
        back = pm.read_bundle(bundle)
        assert "w1" in back["manifest"]["dead_workers"]
        assert "w1" in back["dead_rings"]
        assert canonical_from_snapshot(back["dead_rings"]["w1"]["ring"]) \
            == canonical_from_snapshot(rings["w1"]["ring"])
        # the dump itself is on the coordinator's ring
        assert coord.events.canonical_sequence()[-1][0] == \
            "postmortem.bundle"
    finally:
        await coord.stop()
        for w in workers.values():
            try:
                await w.stop()
            except Exception:
                pass
