"""Attention ops for prefill and decode, XLA-native.

Replaces the reference's compute kernel — an ``asyncio.sleep``
(``src/mock_models/fake_model.py:47``) — with the real thing. Two entry
points matching the two serving phases:

- ``causal_attention``: prefill over the freshly computed K/V of the prompt
  (no history exists yet, so attending over the full cache would waste
  HBM bandwidth reading empty pages).
- ``cached_attention``: decode, one query token per slot against the
  HBM-resident KV cache, masked by each slot's live length.

Both are pure einsum/softmax chains: XLA fuses mask+softmax+matmul well on
the MXU for these shapes. The Pallas paged-attention kernel
(``ops/paged_attention.py``) takes over when the cache is paged.

GQA layout note: K/V carry ``n_kv_heads``; queries carry ``n_heads``. We
reshape Q to [B, T, n_kv, group, Dh] and broadcast K/V across the group dim —
no materialized repeat, XLA keeps it as an indexing pattern.
"""

from __future__ import annotations

import jax.numpy as jnp

NEG_INF = -1e30   # large-but-finite: -inf rows would softmax to NaN

_FP8 = ("float8_e4m3fn", "float8_e5m2")


def _upcast_fp8(k: jnp.ndarray, v: jnp.ndarray, dt) -> tuple:
    """fp8 KV caches (half the KV HBM of bf16) have no implicit promotion
    path — upcast to the query dtype at the attention boundary. Wider
    caches (fp32 kv under bf16 compute) keep their implicit promotion."""
    if k.dtype.name in _FP8:
        return k.astype(dt), v.astype(dt)
    return k, v


def _group_query(q: jnp.ndarray, n_kv_heads: int) -> jnp.ndarray:
    """[B, T, H, Dh] -> [B, T, Hkv, G, Dh] where H = Hkv * G."""
    b, t, h, d = q.shape
    return q.reshape(b, t, n_kv_heads, h // n_kv_heads, d)


def causal_attention(
    q: jnp.ndarray,          # [B, T, H, Dh]
    k: jnp.ndarray,          # [B, T, Hkv, Dh]
    v: jnp.ndarray,          # [B, T, Hkv, Dh]
    seq_lens: jnp.ndarray,   # [B] valid prompt lengths (right-padded batches)
    window: int = 0,         # sliding-window size (0 = full causal)
) -> jnp.ndarray:
    """Prefill attention: causal within the prompt, padding masked out.

    Returns [B, T, H, Dh].
    """
    b, t, h, dh = q.shape
    n_kv = k.shape[2]
    k, v = _upcast_fp8(k, v, q.dtype)
    qg = _group_query(q, n_kv)                                   # [B,T,Hkv,G,Dh]
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    # scores: [B, Hkv, G, T, T]
    scores = jnp.einsum("bikgd,bjkd->bkgij", qg, k).astype(jnp.float32) * scale
    i = jnp.arange(t)[:, None]
    j = jnp.arange(t)[None, :]
    causal = j <= i                                              # [T, T]
    if window:
        causal &= (i - j) < window                               # Mistral SWA
    valid = jnp.arange(t)[None, :] < seq_lens[:, None]           # [B, T] keys in-prompt
    mask = causal[None, :, :] & valid[:, None, :]                # [B, T, T]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgij,bjkd->bikgd", probs.astype(v.dtype), v)
    return out.reshape(b, t, h, dh)


def suffix_attention(
    q: jnp.ndarray,            # [B, Ts, H, Dh] suffix queries
    k_ctx: jnp.ndarray,        # [B, Tc, Hkv, Dh] cached-context keys (padded)
    v_ctx: jnp.ndarray,        # [B, Tc, Hkv, Dh]
    n_ctx: jnp.ndarray,        # [B] valid context length per row
    k_suf: jnp.ndarray,        # [B, Ts, Hkv, Dh] fresh suffix keys
    v_suf: jnp.ndarray,        # [B, Ts, Hkv, Dh]
    suffix_lens: jnp.ndarray,  # [B] valid suffix length per row
    window: int = 0,           # sliding-window size (0 = full causal)
) -> jnp.ndarray:
    """Prefill of a prompt SUFFIX against cached prefix KV (prefix cache
    hit, ``engine/paged_kv.py``): suffix query i (absolute position
    n_ctx+i) attends to every valid context key and causally within the
    suffix. Returns [B, Ts, H, Dh]."""
    b, ts, h, dh = q.shape
    tc = k_ctx.shape[1]
    n_kv = k_ctx.shape[2]
    k_ctx, v_ctx = _upcast_fp8(k_ctx, v_ctx, q.dtype)
    k_suf, v_suf = _upcast_fp8(k_suf, v_suf, q.dtype)
    qg = _group_query(q, n_kv)                                   # [B,Ts,Hkv,G,Dh]
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    k_all = jnp.concatenate([k_ctx, k_suf], axis=1)              # [B,Tc+Ts,...]
    v_all = jnp.concatenate([v_ctx, v_suf], axis=1)
    scores = jnp.einsum("bikgd,bjkd->bkgij", qg, k_all).astype(jnp.float32) * scale
    i = jnp.arange(ts)[:, None]                                  # query idx
    j = jnp.arange(tc + ts)[None, :]                             # key idx
    # context keys: valid iff j < n_ctx; suffix keys: causal AND < suffix_len
    in_ctx = (j < tc)
    suf_j = j - tc                                               # suffix-local key idx
    causal = suf_j <= i                                          # [Ts, Tc+Ts]
    mask_ctx = in_ctx & (j < n_ctx[:, None, None])               # [B,1,Tc+Ts] w/ i broadcast
    mask_suf = (~in_ctx) & causal[None, :, :] & \
        (suf_j[None, :, :] < suffix_lens[:, None, None])
    mask = mask_ctx | mask_suf                                   # [B, Ts, Tc+Ts]
    if window:
        # absolute positions: query = n_ctx + i; ctx key = j; suffix key =
        # n_ctx + suf_j — the query sees only the last `window` positions
        q_abs = n_ctx[:, None, None] + i[None, :, :]             # [B, Ts, 1]
        k_abs = jnp.where(in_ctx, j, n_ctx[:, None, None] + suf_j)
        mask &= (q_abs - k_abs) < window
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgij,bjkd->bikgd", probs.astype(v_all.dtype), v_all)
    return out.reshape(b, ts, h, dh).astype(q.dtype)   # see cached_attention


def window_decode_attention(
    q: jnp.ndarray,          # [B, H, Dh] decode queries
    k_side: jnp.ndarray,     # [B, W, Hkv, Dh] chunk side-window keys
    v_side: jnp.ndarray,     # [B, W, Hkv, Dh]
    n_valid: jnp.ndarray,    # [B] valid side entries per slot
) -> tuple:
    """Decode attention over the chunk's dense side window, returning the
    normalized output PLUS its flash-style stats (row max ``m`` and
    softmax denominator ``l``, both [B, H] fp32) so the caller can merge
    it with the paged-prefix partial via ``merge_attention``.

    This is half of the windowed decode scheme (``models.base
    .forward_decode_window``): during a decode chunk the page pools are
    frozen and fresh K/V accumulates here — the per-step pool scatter it
    replaces cost ~45 ms/step at 8B bs64 (XLA scatter lowering), which
    held the paged engine at ~28% of dense-engine throughput.
    """
    b, h, dh = q.shape
    w = k_side.shape[1]
    n_kv = k_side.shape[2]
    k_side, v_side = _upcast_fp8(k_side, v_side, q.dtype)
    qg = q.reshape(b, n_kv, h // n_kv, dh)
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    scores = jnp.einsum("bkgd,bwkd->bkgw", qg, k_side).astype(jnp.float32)
    scores = scores * scale
    valid = jnp.arange(w)[None, :] < n_valid[:, None]            # [B, W]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    m = scores.max(axis=-1)                                      # [B,Hkv,G]
    probs = jnp.exp(scores - m[..., None])
    # all-invalid rows: m == NEG_INF makes every exp() equal 1 — zero them
    # so l is a true denominator (their merge weight must be 0, not W)
    probs = jnp.where(valid[:, None, None, :], probs, 0.0)
    l = probs.sum(axis=-1)
    out = jnp.einsum("bkgw,bwkd->bkgd", probs.astype(v_side.dtype), v_side)
    out = out.astype(jnp.float32) / jnp.maximum(l[..., None], 1e-30)
    return (out.reshape(b, h, dh).astype(q.dtype),
            m.reshape(b, h), l.reshape(b, h))


def merge_attention(parts, dtype=None) -> jnp.ndarray:
    """Combine flash-style partial attentions over DISJOINT key sets.

    ``parts`` is a list of (out [B, H, Dh] normalized, m [B, H], l [B, H])
    as produced by ``window_decode_attention`` / ``ops.paged_attention``
    with stats: softmax over the union of key sets equals the l·e^{m-m*}
    -weighted average of the partial outputs. A part with no valid keys
    carries l = 0 (and m = NEG_INF) and contributes nothing.
    """
    m_tot = parts[0][1]
    for _, m, _ in parts[1:]:
        m_tot = jnp.maximum(m_tot, m)
    num = 0.0
    den = 0.0
    for out, m, l in parts:
        wgt = l * jnp.exp(m - m_tot)                             # [B, H]
        num = num + out.astype(jnp.float32) * wgt[..., None]
        den = den + wgt
    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.astype(dtype or parts[0][0].dtype)


def cached_attention(
    q: jnp.ndarray,          # [B, 1, H, Dh] decode queries
    cache_k: jnp.ndarray,    # [B, S, Hkv, Dh] full HBM cache rows
    cache_v: jnp.ndarray,    # [B, S, Hkv, Dh]
    lengths: jnp.ndarray,    # [B] live length per slot (incl. the new token)
    window: int = 0,         # sliding-window size (0 = full attention)
) -> jnp.ndarray:
    """Decode attention against the KV cache, masked to each slot's live
    prefix. Returns [B, 1, H, Dh]."""
    b, t, h, dh = q.shape
    s = cache_k.shape[1]
    n_kv = cache_k.shape[2]
    cache_k, cache_v = _upcast_fp8(cache_k, cache_v, q.dtype)
    qg = _group_query(q, n_kv)                                   # [B,1,Hkv,G,Dh]
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)
    scores = jnp.einsum("bikgd,bjkd->bkgij", qg, cache_k).astype(jnp.float32) * scale
    valid = jnp.arange(s)[None, :] < lengths[:, None]            # [B, S]
    if window:
        # query sits at position lengths-1; only keys within the window
        valid &= jnp.arange(s)[None, :] >= (lengths[:, None] - window)
    scores = jnp.where(valid[:, None, None, None, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgij,bjkd->bikgd", probs.astype(cache_v.dtype), cache_v)
    # query dtype out: the KV cache may be wider/narrower than the compute
    # dtype (EngineConfig.kv_dtype), and the residual stream must not
    # change dtype mid-scan (carry mismatch)
    return out.reshape(b, t, h, dh).astype(q.dtype)
