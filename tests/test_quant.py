"""Weight-only int8 quantization tests (ops/quant.py): the reference
stores a ``quantized`` flag it never reads
(``/root/reference/src/model_registry.py:55``); here it must actually
shrink weight bytes while keeping generations materially unchanged."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_engine_tpu.config import EngineConfig, ModelConfig
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import engine_from_config
from distributed_inference_engine_tpu.models.base import (
    forward_train,
    init_params,
)
from distributed_inference_engine_tpu.models.llama import (
    llama_spec,
    mixtral_spec,
)
from distributed_inference_engine_tpu.ops.quant import (
    QuantizedTensor,
    matmul_any,
    param_bytes,
    quantize_params,
    quantize_weight,
)

SPEC = llama_spec("llama-tiny", max_seq_len=64, dtype="float32")


def test_quantize_weight_roundtrip_error_bounded():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(64, 32).astype("float32"))
    qt = quantize_weight(w, (0,))
    assert qt.q.dtype == jnp.int8
    assert qt.s.shape == (1, 32)
    err = np.abs(np.asarray(qt.dequantize()) - np.asarray(w))
    # per-channel max error <= scale/2 (round-to-nearest)
    assert (err <= np.asarray(qt.s) / 2 + 1e-7).all()


def test_matmul_any_matches_dequantized_einsum():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 7, 64).astype("float32"))
    w = jnp.asarray(rs.randn(64, 32).astype("float32"))
    qt = quantize_weight(w, (0,))
    got = matmul_any("btd,de->bte", x, qt)
    want = jnp.einsum("btd,de->bte", x, qt.dequantize())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_quantized_params_shrink_and_logits_agree():
    params = init_params(SPEC, jax.random.key(0))
    qparams = quantize_params(SPEC, params)
    # the big matmul weights got int8 payloads
    assert isinstance(qparams["blocks"]["wq"], QuantizedTensor)
    assert isinstance(qparams["blocks"]["w_down"], QuantizedTensor)
    assert param_bytes(qparams) < 0.45 * param_bytes(params)

    rs = np.random.RandomState(2)
    toks = jnp.asarray(rs.randint(0, SPEC.vocab_size, (2, 12)), jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)
    full = np.asarray(forward_train(SPEC, params, toks, lens))
    quant = np.asarray(forward_train(SPEC, qparams, toks, lens))
    assert np.isfinite(quant).all()
    # top-1 agreement across positions: int8 weight-only should rarely
    # flip the argmax of a random-init model's logits
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.9, f"top-1 agreement {agree:.2f}"
    # logits stay close in relative terms
    denom = np.abs(full).max()
    assert np.abs(full - quant).max() / denom < 0.1


def test_quantized_engine_generates_like_full():
    params = init_params(SPEC, jax.random.key(0))
    cfg = EngineConfig(max_slots=2, max_seq_len=64)
    full_eng = Engine(SPEC, params=params, config=cfg)
    q_eng = Engine(SPEC, params=quantize_params(SPEC, params), config=cfg)
    reqs = [GenerationRequest(prompt=[5, 6, 7, 8], max_new_tokens=8,
                              temperature=0.0)]
    full_out = full_eng.generate([GenerationRequest(
        prompt=[5, 6, 7, 8], max_new_tokens=8, temperature=0.0)])[0].tokens
    q_out = q_eng.generate(reqs)[0].tokens
    assert len(q_out) == 8
    assert all(0 <= t < SPEC.vocab_size for t in q_out)
    # greedy chains can diverge after a flip, but the first token — a pure
    # function of the prefill logits — should match on a random-init model
    assert q_out[0] == full_out[0]


def test_engine_from_config_quantized_flag():
    cfg = ModelConfig(
        name="q", architecture="llama", dtype="float32", quantized=True,
        max_seq_len=64, max_batch_size=2, metadata={"size": "llama-tiny"},
    )
    eng = engine_from_config(cfg)
    assert isinstance(eng.params["blocks"]["wq"], QuantizedTensor)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=4)])
    assert len(out[0].tokens) == 4


def test_quantized_moe_exact_path_runs():
    spec = mixtral_spec(
        "mixtral-tiny", dtype="float32", max_seq_len=64,
        n_layers=2, d_model=64, n_heads=8, n_kv_heads=4, d_ff=96,
        vocab_size=128, n_experts=4, experts_per_token=2,
    )
    params = init_params(spec, jax.random.key(3))
    qparams = quantize_params(spec, params)
    assert isinstance(qparams["blocks"]["w_up"], QuantizedTensor)
    assert qparams["blocks"]["w_up"].s.shape == (2, 4, 1, 96)
    # router stays full precision (tiny + precision-sensitive)
    assert not isinstance(qparams["blocks"]["w_router"], QuantizedTensor)

    rs = np.random.RandomState(4)
    toks = jnp.asarray(rs.randint(0, spec.vocab_size, (1, 8)), jnp.int32)
    lens = jnp.full((1,), 8, jnp.int32)
    full = np.asarray(forward_train(spec, params, toks, lens))
    quant = np.asarray(forward_train(spec, qparams, toks, lens))
    assert np.isfinite(quant).all()
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.8, f"top-1 agreement {agree:.2f}"


def test_quantized_speculative_composes():
    """int8 target + full-precision draft through the config path: the
    registry's quantized flag and speculative metadata must compose (the
    target's QuantizedTensor tree flows through forward_window via
    matmul_any)."""
    cfg = ModelConfig(
        name="qs", architecture="llama", dtype="float32", quantized=True,
        max_seq_len=64, max_batch_size=2,
        metadata={"size": "llama-tiny", "speculative": 2,
                  "draft_size": "llama-tiny"},
    )
    eng = engine_from_config(cfg)
    assert isinstance(eng.params["blocks"]["wq"], QuantizedTensor)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=6)])[0]
    assert len(out.tokens) == 6


# ------------------------------------------------------------------- int4


def test_int4_pack_roundtrip_exact():
    """Packed nibbles decode back to the exact int4 values."""
    import numpy as np

    from distributed_inference_engine_tpu.ops.quant import quantize_weight

    rs = np.random.RandomState(0)
    w = rs.randn(8, 6).astype("float32")
    qt = quantize_weight(jnp.asarray(w), (0,), bits=4)
    assert qt.bits == 4 and qt.q.shape == (4, 6) and qt.shape == (8, 6)
    vals = np.asarray(qt._unpacked_int8())
    assert vals.min() >= -7 and vals.max() <= 7
    # round-trip against direct per-channel quantization
    scale = np.maximum(np.abs(w).max(axis=0, keepdims=True), 1e-8) / 7.0
    ref = np.clip(np.round(w / scale), -7, 7)
    np.testing.assert_array_equal(vals, ref)


def test_int4_matmul_matches_dequantized_reference():
    """_einsum_int4 (fused unpack in the dot operand) == explicit
    dequantize-then-einsum, for every pattern the model uses."""
    import numpy as np

    from distributed_inference_engine_tpu.ops.quant import (
        matmul_any,
        quantize_weight,
    )

    rs = np.random.RandomState(1)
    x2 = jnp.asarray(rs.randn(3, 5, 8).astype("float32"))
    for pattern, wshape, axes in (
        ("btd,df->btf", (8, 12), (0,)),
        ("...d,dv->...v", (8, 10), (0,)),
    ):
        w = jnp.asarray(rs.randn(*wshape).astype("float32"))
        qt = quantize_weight(w, axes, bits=4)
        got = matmul_any(pattern, x2, qt)
        ref = jnp.einsum(pattern, x2, qt.dequantize(jnp.float32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_int4_survives_layer_scan_slicing():
    """pack_axis is end-relative: slicing the stacked layer axis (what
    lax.scan and truncated_draft do) leaves the packing valid."""
    import numpy as np

    from distributed_inference_engine_tpu.ops.quant import (
        matmul_any,
        quantize_weight,
    )

    rs = np.random.RandomState(2)
    w = jnp.asarray(rs.randn(3, 8, 12).astype("float32"))   # [L, D, F]
    qt = quantize_weight(w, (1,), bits=4)

    def per_layer(x, q_l):
        return matmul_any("bd,df->bf", x, q_l)

    x = jnp.asarray(rs.randn(2, 8).astype("float32"))
    out = jax.lax.scan(lambda c, q_l: (c, per_layer(x, q_l)), None, qt)[1]
    ref = jnp.stack([jnp.einsum("bd,df->bf", x,
                                quantize_weight(w[i], (0,), bits=4)
                                .dequantize(jnp.float32))
                     for i in range(3)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_int4_engine_generates_and_matches_dequantized_engine():
    """A continuous engine on int4 params produces the same greedy tokens
    as the same engine on the explicitly dequantized tree."""
    import numpy as np

    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )
    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )
    from distributed_inference_engine_tpu.models.base import init_params
    from distributed_inference_engine_tpu.models.llama import llama_spec
    from distributed_inference_engine_tpu.ops.quant import (
        QuantizedTensor,
        quantize_params,
    )

    spec = llama_spec("llama-tiny", max_seq_len=128).replace(dtype="float32")
    params = init_params(spec, jax.random.key(0))
    q4 = quantize_params(spec, params, bits=4)
    assert q4["blocks"]["wq"].bits == 4
    deq = jax.tree.map(
        lambda x: x.dequantize(jnp.float32)
        if isinstance(x, QuantizedTensor) else x,
        q4, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    cfg = EngineConfig(max_slots=2, max_seq_len=128, prefill_buckets=[32],
                      page_size=16, num_pages=32, decode_steps_per_call=4,
                      kv_dtype="float32")
    reqs = lambda: [GenerationRequest(prompt=list(range(1, 20)),
                                      max_new_tokens=8, request_id="q")]
    e4 = ContinuousEngine(spec, params=q4, config=cfg)
    ed = ContinuousEngine(spec, params=deq, config=cfg)
    t4 = e4.generate(reqs())[0].tokens
    td = ed.generate(reqs())[0].tokens
    assert t4 == td and len(t4) == 8


def test_int4_interleaved_checkpoint_repacks_on_restore():
    """Pre-r4 int4 checkpoints pack even/odd interleaved; the restore
    codec must repack them to the current split-half layout (keyed by the
    absent layout marker) so old files keep decoding correctly."""
    import numpy as np

    from distributed_inference_engine_tpu.ops.quant import QuantizedTensor
    from distributed_inference_engine_tpu.utils.checkpoint import (
        _decode_tree,
        _encode_tree,
    )

    rs = np.random.RandomState(3)
    vals = rs.randint(-7, 8, size=(8, 6)).astype(np.int8)   # true int4 values
    # old layout: byte k holds (vals[2k] lo, vals[2k+1] hi)
    old_packed = ((vals[0::2].astype(np.uint8) & 0xF)
                  | (vals[1::2].astype(np.uint8) << 4)).view(np.int8)
    s = np.full((1, 6), 0.5, np.float32)
    node = {"__quantized_tensor__": np.int8(1), "q": jnp.asarray(old_packed),
            "s": jnp.asarray(s), "bits": np.int32(4),
            "pack_axis": np.int32(-2)}            # no "layout": pre-r4 file
    qt = _decode_tree({"w": dict(node)})["w"]
    np.testing.assert_array_equal(np.asarray(qt._unpacked_int8()), vals)

    # current files carry the marker and round-trip WITHOUT repacking
    enc = _encode_tree({"w": qt})["w"]
    assert int(enc["layout"]) == 1
    qt2 = _decode_tree({"w": enc})["w"]
    np.testing.assert_array_equal(np.asarray(qt2._unpacked_int8()), vals)


def test_int4_lm_head_vocab_padding_exact():
    """int4 lm_heads vocab-pad to a 2048-multiple (kernel block tiling);
    pad columns are zero-weight and unembed slices them off — logits for
    REAL columns must be unchanged vs an unpadded quantization."""
    import numpy as np

    from distributed_inference_engine_tpu.models.base import unembed
    from distributed_inference_engine_tpu.models.llama import llama_spec
    from distributed_inference_engine_tpu.ops.quant import (
        _pad_vocab,
        quantize_params,
        quantize_weight,
    )

    spec = llama_spec("llama-tiny", max_seq_len=32).replace(
        d_model=256, d_ff=256, vocab_size=300, dtype="float32")
    assert not spec.tie_embeddings
    rs = np.random.RandomState(0)
    from distributed_inference_engine_tpu.models.base import init_params

    params = init_params(spec, jax.random.key(0))
    q4 = quantize_params(spec, params, bits=4)
    assert q4["lm_head"].shape == (256, _pad_vocab(300))
    h = jnp.asarray(rs.randn(2, 256).astype("float32"))
    got = unembed(spec, q4, h)
    assert got.shape == (2, 300)            # sliced back to the real vocab
    # reference: unpadded per-column quantization of the same weights
    ref_w = quantize_weight(params["lm_head"], (0,), bits=4)
    ref = unembed(spec, {**q4, "lm_head": ref_w}, h)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
