from .framing import encode_frame, decode_frame, read_frame, write_frame, FrameError  # noqa: F401
from .tracing import RequestTrace, trace_span, new_request_id  # noqa: F401
