"""Rule family 4: docs↔code drift.

``drift-metrics-docs`` generalizes scripts/lint_metrics.py (now a shim
over this rule): the docs/observability.md catalog table and
``obs/collectors.CATALOG`` must agree in both directions, kinds
included.

``drift-knob-docs`` is the sibling check for the serving knobs: every
``EngineConfig.<field>``-style reference in README.md / docs/*.md must
name a real field of the config dataclasses (stale docs), and every
``BENCH_*`` env var bench.py actually reads must be documented in
README.md or bench.py's own docstring — and vice versa (phantom knobs).

Both are project rules: they anchor findings to the drifted file, keyed
by the drifted NAME (stable under unrelated edits).
"""

from __future__ import annotations

import ast
import binascii
import importlib
import os
import re
import sys
import types
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, Project, Rule, register

PKG = "distributed_inference_engine_tpu"
OBS_DOC = "docs/observability.md"
CONFIG_PY = f"{PKG}/config.py"
COLLECTORS_PY = f"{PKG}/obs/collectors.py"

# a docs catalog row: | `family_name` | kind | labels | help |
_ROW_RE = re.compile(
    r"^\|\s*`([a-zA-Z_][a-zA-Z0-9_]*)`\s*\|\s*(counter|gauge|histogram)\s*\|")
# a docs EVENT catalog row: | `subsystem.event` | emitter | description |
# — scanned only inside tables under the `| event | emitter | ...`
# header, so dotted names elsewhere (the trace phase glossary) and the
# dot-free metric rows can never collide with event rows
_EVENT_HEADER_RE = re.compile(r"^\|\s*event\s*\|\s*emitter\s*\|")
_EVENT_ROW_RE = re.compile(
    r"^\|\s*`([a-z_][a-z0-9_]*\.[a-z0-9_.]+)`\s*\|")
# knob references in prose: `EngineConfig.prefill_chunk` etc.
_KNOB_REF_RE = re.compile(
    r"`(EngineConfig|BatcherConfig|CacheConfig|HealthConfig|ServerConfig|"
    r"ModelConfig|MeshConfig|MultihostConfig)\.([a-z_][a-z0-9_]*)")
_BENCH_RE = re.compile(r"\bBENCH_[A-Z0-9_]+\b")


def _find_line(text: str, needle: str) -> int:
    for i, line in enumerate(text.splitlines(), start=1):
        if needle in line:
            return i
    return 1


# ------------------------------------------------------------- metrics

def load_catalog(root: str) -> Optional[Dict[str, str]]:
    """Import obs.collectors.CATALOG (jax-free by contract) from ``root``.

    The import runs under a per-root ALIAS package, not the real package
    name: the hosting process (pytest, a REPL) may already have the real
    ``distributed_inference_engine_tpu`` imported, and a sys.modules hit
    on the real name would silently return THAT catalog instead of the
    one in the tree being linted. The alias stubs only carry ``__path__``
    so relative imports inside obs/ resolve within ``root``."""
    pkg_dir = os.path.join(root, PKG)
    if not os.path.isfile(os.path.join(pkg_dir, "obs", "collectors.py")):
        return None
    alias = "_graftlint_catalog_%08x" % (
        binascii.crc32(os.path.abspath(root).encode()) & 0xFFFFFFFF)
    try:
        mod = sys.modules.get(alias + ".obs.collectors")
        if mod is None:
            for name, path in ((alias, pkg_dir),
                               (alias + ".obs", os.path.join(pkg_dir, "obs"))):
                stub = types.ModuleType(name)
                stub.__path__ = [path]
                sys.modules.setdefault(name, stub)
            importlib.invalidate_caches()   # root may be a fresh tmp dir
            mod = importlib.import_module(alias + ".obs.collectors")
        catalog = mod.CATALOG
    except Exception:
        return None
    return {name: kind for name, (kind, _l, _h) in catalog.items()}


def check_metrics_drift(root: str) -> List[Finding]:
    """Two-way catalog↔docs diff; plain-function entry so the
    scripts/lint_metrics.py shim can call it without the runner."""
    out: List[Finding] = []

    def mk(path: str, line: int, msg: str, key: str) -> Finding:
        return Finding(rule="drift-metrics-docs", path=path, line=line,
                       message=msg, key=key)

    doc_path = os.path.join(root, OBS_DOC)
    if not os.path.exists(doc_path):
        return [mk(OBS_DOC, 1, f"{OBS_DOC} missing", "missing-doc")]
    cat = load_catalog(root)
    if cat is None:
        return [mk(COLLECTORS_PY, 1,
                   "cannot import obs.collectors.CATALOG", "no-catalog")]
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    doc: Dict[str, str] = {}
    for i, line in enumerate(doc_text.splitlines(), start=1):
        m = _ROW_RE.match(line)
        if m:
            doc[m.group(1)] = m.group(2)
    col_text = ""
    col_path = os.path.join(root, COLLECTORS_PY)
    if os.path.exists(col_path):
        with open(col_path, encoding="utf-8") as f:
            col_text = f.read()
    for name in sorted(set(cat) - set(doc)):
        out.append(mk(COLLECTORS_PY, _find_line(col_text, f'"{name}"'),
                      f"metric family {name} ({cat[name]}) is emitted but "
                      f"undocumented in {OBS_DOC}", name))
    for name in sorted(set(doc) - set(cat)):
        out.append(mk(OBS_DOC, _find_line(doc_text, f"`{name}`"),
                      f"metric family {name} is documented but no "
                      f"collector emits it (stale row)", name))
    for name in sorted(set(doc) & set(cat)):
        if doc[name] != cat[name]:
            out.append(mk(OBS_DOC, _find_line(doc_text, f"`{name}`"),
                          f"metric family {name} documented as "
                          f"{doc[name]} but the catalog says {cat[name]}",
                          name))
    return out


@register
class DriftMetricsDocs(Rule):
    id = "drift-metrics-docs"
    family = "drift"
    severity = "error"
    doc = ("docs/observability.md catalog table and obs/collectors.CATALOG "
           "must agree both ways, kinds included (ex scripts/"
           "lint_metrics.py)")

    def check_project(self, project: Project) -> Iterable[Finding]:
        # only meaningful against the real repo tree
        if not os.path.exists(os.path.join(project.root, COLLECTORS_PY)):
            return ()
        return check_metrics_drift(project.root)


# -------------------------------------------------------------- events

EVENTS_PY = f"{PKG}/obs/events.py"


def load_events(root: str) -> Optional[Dict[str, str]]:
    """Import obs.events.EVENTS (jax-free by contract) from ``root``,
    under the same per-root alias scheme as ``load_catalog``."""
    pkg_dir = os.path.join(root, PKG)
    if not os.path.isfile(os.path.join(pkg_dir, "obs", "events.py")):
        return None
    alias = "_graftlint_catalog_%08x" % (
        binascii.crc32(os.path.abspath(root).encode()) & 0xFFFFFFFF)
    try:
        mod = sys.modules.get(alias + ".obs.events")
        if mod is None:
            for name, path in ((alias, pkg_dir),
                               (alias + ".obs", os.path.join(pkg_dir, "obs"))):
                stub = types.ModuleType(name)
                stub.__path__ = [path]
                sys.modules.setdefault(name, stub)
            importlib.invalidate_caches()
            mod = importlib.import_module(alias + ".obs.events")
        return dict(mod.EVENTS)
    except Exception:
        return None


def check_events_drift(root: str) -> List[Finding]:
    """Two-way event-catalog↔docs diff: every ``obs.events.EVENTS`` type
    must have a docs event-table row, and every documented event type
    must exist in the catalog (``EventLog.emit`` rejects unknown types,
    so a stale row documents an event that can never fire)."""
    out: List[Finding] = []

    def mk(path: str, line: int, msg: str, key: str) -> Finding:
        return Finding(rule="drift-events-docs", path=path, line=line,
                       message=msg, key=key)

    doc_path = os.path.join(root, OBS_DOC)
    if not os.path.exists(doc_path):
        return [mk(OBS_DOC, 1, f"{OBS_DOC} missing", "missing-doc")]
    events = load_events(root)
    if events is None:
        return [mk(EVENTS_PY, 1,
                   "cannot import obs.events.EVENTS", "no-events")]
    with open(doc_path, encoding="utf-8") as f:
        doc_text = f.read()
    doc: Set[str] = set()
    in_table = False
    for line in doc_text.splitlines():
        if _EVENT_HEADER_RE.match(line):
            in_table = True
            continue
        if in_table and not line.startswith("|"):
            in_table = False
        if not in_table:
            continue
        m = _EVENT_ROW_RE.match(line)
        if m:
            doc.add(m.group(1))
    ev_text = ""
    ev_path = os.path.join(root, EVENTS_PY)
    if os.path.exists(ev_path):
        with open(ev_path, encoding="utf-8") as f:
            ev_text = f.read()
    for name in sorted(set(events) - doc):
        out.append(mk(EVENTS_PY, _find_line(ev_text, f'"{name}"'),
                      f"event type {name} is in the catalog but "
                      f"undocumented in {OBS_DOC}", name))
    for name in sorted(doc - set(events)):
        out.append(mk(OBS_DOC, _find_line(doc_text, f"`{name}`"),
                      f"event type {name} is documented but absent from "
                      f"obs.events.EVENTS (stale row — emit would raise)",
                      name))
    return out


@register
class DriftEventsDocs(Rule):
    id = "drift-events-docs"
    family = "drift"
    severity = "error"
    doc = ("docs/observability.md event-catalog table and obs/events."
           "EVENTS must agree both ways (typed emit makes a stale row "
           "an event that can never fire)")

    def check_project(self, project: Project) -> Iterable[Finding]:
        if not os.path.exists(os.path.join(project.root, EVENTS_PY)):
            return ()
        return check_events_drift(project.root)


# --------------------------------------------------------------- knobs

def _config_fields(root: str) -> Optional[Dict[str, Set[str]]]:
    """class name -> field names, parsed from config.py's AST (no import:
    this must work with zero deps installed)."""
    path = os.path.join(root, CONFIG_PY)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    out: Dict[str, Set[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        fields: Set[str] = set()
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                fields.add(stmt.target.id)
        out[node.name] = fields
    return out


def _bench_reads(root: str) -> Tuple[Set[str], str, str]:
    """(env names bench.py reads, its docstring, full source)."""
    path = os.path.join(root, "bench.py")
    if not os.path.exists(path):
        return set(), "", ""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src)
    reads: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith("BENCH_") and \
                _BENCH_RE.fullmatch(node.value):
            reads.add(node.value)
    docstring = ast.get_docstring(tree) or ""
    return reads, docstring, src


def check_knob_drift(root: str) -> List[Finding]:
    out: List[Finding] = []

    def mk(path: str, line: int, msg: str, key: str) -> Finding:
        return Finding(rule="drift-knob-docs", path=path, line=line,
                       message=msg, key=key)

    fields = _config_fields(root)
    doc_files = ["README.md"]
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        doc_files += sorted(
            os.path.join("docs", f) for f in os.listdir(docs_dir)
            if f.endswith(".md"))
    # 1) prose references to config fields must name real fields
    if fields is not None:
        for rel in doc_files:
            p = os.path.join(root, rel)
            if not os.path.exists(p):
                continue
            with open(p, encoding="utf-8") as f:
                text = f.read()
            for i, line in enumerate(text.splitlines(), start=1):
                for m in _KNOB_REF_RE.finditer(line):
                    cls, field = m.group(1), m.group(2)
                    if cls in fields and field not in fields[cls]:
                        out.append(mk(
                            rel, i,
                            f"doc references {cls}.{field} but config.py "
                            f"defines no such field — stale knob doc",
                            f"{cls}.{field}"))
    # 2) BENCH_* two-way: reads vs README + bench.py docstring
    reads, docstring, bench_src = _bench_reads(root)
    if reads:
        readme_path = os.path.join(root, "README.md")
        readme = ""
        if os.path.exists(readme_path):
            with open(readme_path, encoding="utf-8") as f:
                readme = f.read()
        documented = set(_BENCH_RE.findall(readme)) | \
            set(_BENCH_RE.findall(docstring))
        for name in sorted(reads - documented):
            out.append(mk("bench.py", _find_line(bench_src, f'"{name}"'),
                          f"{name} is read by bench.py but documented "
                          f"neither in its docstring nor in README.md",
                          name))
        for name in sorted(documented - reads):
            where = "README.md" if name in _BENCH_RE.findall(readme) \
                else "bench.py"
            src = readme if where == "README.md" else bench_src
            out.append(mk(where, _find_line(src, name),
                          f"{name} is documented but bench.py never reads "
                          f"it — phantom knob", name))
    return out


@register
class DriftKnobDocs(Rule):
    id = "drift-knob-docs"
    family = "drift"
    severity = "error"
    doc = ("EngineConfig-family field references in README/docs must exist "
           "in config.py; BENCH_* env vars must be documented iff read by "
           "bench.py")

    def check_project(self, project: Project) -> Iterable[Finding]:
        if not os.path.exists(os.path.join(project.root, CONFIG_PY)) and \
                not os.path.exists(os.path.join(project.root, "bench.py")):
            return ()
        return check_knob_drift(project.root)
