"""Ring attention: causal attention with the sequence sharded over the ``sp``
mesh axis (long-context serving, SURVEY.md §5 long-context row).

Nothing in the reference scales with sequence length (its inputs are opaque
echoes), so this is capability-extension scoped by the build plan (SURVEY.md
§7 step 7): each device holds one sequence block of Q/K/V; K/V blocks rotate
around the ring via ``lax.ppermute`` (XLA lowers to ICI neighbor transfers)
while each device accumulates its Q block's attention with an online-softmax
(flash-attention style) running max/denominator — so the full [T, T] score
matrix never materializes and HBM per chip stays O(T/sp).

Causality across blocks falls out of absolute positions: block ownership
gives every K/V rotation step a position offset, and steps whose entire block
is in the future contribute nothing (masked to -inf, zero accumulated).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF

try:
    from jax import shard_map as _shard_map          # jax >= 0.7 public API
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def _ring_body(q, k, v, seq_lens, *, axis: str, n_kv_heads: int,
               window: int = 0):
    """Per-device body: q/k/v are LOCAL blocks [B, Tl, H|Hkv, Dh]."""
    b, tl, h, dh = q.shape
    g = h // n_kv_heads
    n = (lax.axis_size(axis) if hasattr(lax, "axis_size")
         else lax.psum(1, axis))           # psum(1): pre-0.5 jax spelling
    idx = lax.axis_index(axis)
    scale = 1.0 / jnp.sqrt(dh).astype(jnp.float32)

    qg = q.reshape(b, tl, n_kv_heads, g, dh)
    q_pos = idx * tl + jnp.arange(tl)                              # [Tl]

    # online-softmax state per (batch, head-group, query); marked
    # device-varying over the ring axis so the loop carry types match (the
    # accumulators genuinely diverge per device from step 0)
    def _vary(x):
        if hasattr(lax, "pcast"):
            return lax.pcast(x, axis, to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(x, axis)                 # older jax
        return x          # pre-varying-types jax: carries already match

    m = _vary(jnp.full((b, n_kv_heads, g, tl), NEG_INF, dtype=jnp.float32))
    l = _vary(jnp.zeros((b, n_kv_heads, g, tl), dtype=jnp.float32))
    acc = _vary(jnp.zeros((b, tl, n_kv_heads, g, dh), dtype=jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        m, l, acc, k_blk, v_blk = carry
        owner = (idx - s) % n                                      # whose block we hold
        k_pos = owner * tl + jnp.arange(tl)                        # [Tl]
        scores = jnp.einsum(
            "bikgd,bjkd->bkgij", qg, k_blk
        ).astype(jnp.float32) * scale                              # [B,Hkv,G,Tl,Tl]
        mask = k_pos[None, :] <= q_pos[:, None]                    # [Tl, Tl] causal
        if window:
            # sliding window, same convention as ops.attention
            # .causal_attention ((i - j) < window): absolute positions make
            # the mask rotation-invariant — each step just masks the block
            # it happens to hold. Blocks wholly outside every query's
            # window accumulate zero (their rotation still runs; a
            # skip-if-far optimization would save ICI hops only when
            # window << T/sp, not worth divergent control flow here).
            mask &= (q_pos[:, None] - k_pos[None, :]) < window
        if seq_lens is not None:
            mask = mask[None] & (k_pos[None, None, :] < seq_lens[:, None, None])
            mask = mask[:, None, None]                             # [B,1,1,Tl,Tl]
        else:
            mask = mask[None, None, None]
        scores = jnp.where(mask, scores, NEG_INF)

        blk_max = scores.max(axis=-1)                              # [B,Hkv,G,Tl]
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])                     # [B,Hkv,G,Tl,Tl]
        # fully-masked rows: p is exp(NEG_INF - NEG_INF) = 1 — zero them
        p = jnp.where(mask, p, 0.0)
        l = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bkgij,bjkd->bikgd", p, v_blk.astype(jnp.float32))
        acc = acc * corr.transpose(0, 3, 1, 2)[..., None] + pv
        m = new_m
        k_blk = lax.ppermute(k_blk, axis, perm)
        v_blk = lax.ppermute(v_blk, axis, perm)
        return m, l, acc, k_blk, v_blk

    m, l, acc, _, _ = lax.fori_loop(
        0, n, step, (m, l, acc, k, v), unroll=True
    )
    denom = jnp.maximum(l, 1e-30).transpose(0, 3, 1, 2)[..., None]
    out = (acc / denom).astype(q.dtype)
    return out.reshape(b, tl, h, dh)


def ring_attention(
    q: jnp.ndarray,           # [B, T, H, Dh]  (global view)
    k: jnp.ndarray,           # [B, T, Hkv, Dh]
    v: jnp.ndarray,           # [B, T, Hkv, Dh]
    mesh: Mesh,
    seq_lens: Optional[jnp.ndarray] = None,   # [B] valid lengths
    axis: str = "sp",
    window: int = 0,          # sliding-window size (0 = full causal)
) -> jnp.ndarray:
    """Causal (optionally length-masked, optionally sliding-window)
    attention with T sharded over ``axis``. Requires T % axis_size == 0.
    Returns [B, T, H, Dh] with the same sequence sharding."""
    n_kv = k.shape[2]
    body = functools.partial(_ring_body, axis=axis, n_kv_heads=n_kv,
                             window=window)
    seq_spec = P(None, axis, None, None)
    in_specs = (seq_spec, seq_spec, seq_spec)
    if seq_lens is not None:
        in_specs = in_specs + (P(),)
        args = (q, k, v, seq_lens)
        fn = body
    else:
        args = (q, k, v)
        fn = lambda q_, k_, v_: body(q_, k_, v_, None)
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=seq_spec,
    )(*args)
