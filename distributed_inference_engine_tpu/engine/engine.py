"""The inference engine: jit-compiled prefill + chunked decode on TPU.

This replaces the reference's mock inference core — ``FakeModel.predict``'s
50–150 ms ``asyncio.sleep`` (``src/mock_models/fake_model.py:47``) — with a
real XLA program, and is the component every host-side layer (worker, batcher,
coordinator) ultimately dispatches into (the ``[HOT]`` line of SURVEY.md §3.1).

Execution model (SURVEY.md §7 hard-part #1 — static shapes vs dynamic
serving):

- **Prefill** runs on (batch-bucket, seq-bucket) padded shapes; prompts are
  right-padded, lengths carried as data. One compiled program per bucket
  pair, reused forever after.
- **Decode** is a ``lax.scan`` over ``decode_steps_per_call`` steps, entirely
  on device: forward, sample, advance lengths, write KV — no host round-trip
  per token. The host syncs once per chunk to test "is anyone still active",
  amortizing the device→host latency over the chunk.
- **Sampling knobs are data** (``SamplingParams`` arrays), so greedy and
  nucleus requests share one compiled program.
- **KV buffers are donated** into the decode chunk, so XLA mutates the HBM
  cache in place instead of double-buffering ~GBs per step.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import EngineConfig
from ..models.base import (
    ModelSpec,
    Params,
    forward_decode,
    init_params,
    unembed,
)
from ..ops.sampling import (
    SamplingParams,
    sample_tokens,
    sample_tokens_with_logprobs,
)
from ..obs.timeline import StepTimeline
from ..utils.hotpath import hot_path
from ..utils.tracing import LatencyStats
from .types import (  # noqa: F401  (re-export)
    GenerationRequest,
    GenerationResult,
    scan_host_stops,
    trim_at_stops,
)


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in sorted(buckets):
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {max(buckets)}")


def _check_same_mesh(params, sp_mesh) -> None:
    """The params' placement and sp_mesh must agree: params on one mesh
    with activations constrained to another makes XLA reshard the whole
    model across device orderings inside every prefill. Covers both
    construction paths — a shard_fn and pre-sharded params passed
    directly; no-op when params carry no mesh."""
    leaf = jax.tree.leaves(params)[0]
    mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
    if mesh is not None and mesh != sp_mesh:
        raise ValueError(
            "params are placed on a different mesh than sp_mesh (via "
            "shard_fn or pre-sharded) — cross-mesh prefill would reshard "
            "params every dispatch; build both from the same Mesh")


def _pow2_buckets(cap: int, start: int = 1) -> List[int]:
    out, b = [], start
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


class Engine:
    """Single-program inference engine over one model.

    ``generate`` is synchronous device code; async callers (worker RPC,
    batcher backend) wrap it in an executor thread. Mesh/sharding-aware
    construction is layered in ``parallel/`` — the engine itself only sees
    (possibly sharded) params and arrays.
    """

    def __init__(
        self,
        spec: ModelSpec,
        params: Optional[Params] = None,
        config: Optional[EngineConfig] = None,
        seed: int = 0,
        shard_fn=None,   # optional: fn(params) -> sharded params (parallel/)
        sp_mesh=None,    # optional: mesh with a real sp axis — long prompts
                         # prefill sequence-parallel via ring attention
                         # (parallel/long_context.py) AND decode runs
                         # context-parallel against a sequence-sharded KV
                         # cache (greedy near-ties may resolve differently
                         # than unsharded: reordered fp reductions)
        artifact_path: Optional[str] = None,   # pre-fused serving artifact
                         # (engine/artifact.py): restore the prepared tree
                         # instead of init/quantize/fuse/pad; spec may be
                         # None (the artifact's sidecar is authoritative)
        artifact_selfcheck: bool = True,       # replay the golden-token
                         # probe before admitting traffic (mismatch raises
                         # ArtifactCorruptError — never serve wrong numerics)
    ) -> None:
        self.artifact_manifest: Optional[Dict[str, Any]] = None
        if artifact_path is not None:
            from .artifact import load_artifact

            a_spec, params, self.artifact_manifest = load_artifact(
                artifact_path)
            if spec is None:
                spec = a_spec
        self.spec = spec.validate()
        self.config = config or EngineConfig()
        if params is None:
            params = init_params(spec, jax.random.key(seed))
        if shard_fn is not None:
            params = shard_fn(params)
        if self.artifact_manifest is not None:
            # the artifact IS the post-prepare tree — re-preparing would
            # re-pay the fuse/pad cost the fast path exists to skip
            # (prepare_params is idempotent, but not free)
            self.params = params
        else:
            from ..ops.quant import prepare_params

            # kernel-mode selection (sharded int4 -> "cp") + qkv/gate+up
            # payload fusion, shared across engines (ops.quant.prepare_params)
            self.params = prepare_params(params)
        self._rng = jax.random.key(seed + 1)

        # context-parallel decode: with an sp mesh the dense KV cache is
        # PLACED sequence-sharded (parallel.sharding.kv_cache_pspec) and
        # stays that way through the decode scan — each chip holds and
        # reads 1/sp of the cache; GSPMD inserts the softmax/contraction
        # all-reduces. Applied per batch when the bucket dims divide the
        # axes (see generate()).
        self._cache_sharding = None
        if sp_mesh is not None:
            from jax.sharding import NamedSharding

            from ..parallel.sharding import kv_cache_pspec

            self._cache_sharding = NamedSharding(sp_mesh, kv_cache_pspec())

        cfg = self.config
        self.batch_buckets = _pow2_buckets(cfg.max_slots)
        self.prefill_buckets = sorted(
            b for b in cfg.prefill_buckets if b <= spec.max_seq_len
        ) or [min(128, spec.max_seq_len)]
        self.seq_buckets = _pow2_buckets(
            min(cfg.max_seq_len, spec.max_seq_len), start=128
        )

        # ---- jitted programs (compiled per bucket shape, cached by jax)
        spec_ = self.spec
        from ..parallel.long_context import prefill_fn_for

        if sp_mesh is not None:
            # no-op when params carry no mesh — covers pre-sharded
            # params passed without a shard_fn too
            _check_same_mesh(self.params, sp_mesh)
        fwd_prefill = prefill_fn_for(spec_, sp_mesh, self.prefill_buckets)

        @jax.jit
        def _prefill(params, tokens, seq_lens, sampling, key):
            hidden, ks, vs = fwd_prefill(spec_, params, tokens, seq_lens)
            b = tokens.shape[0]
            last = hidden[jnp.arange(b), seq_lens - 1]        # [B, D]
            logits = unembed(spec_, params, last)             # [B, V] fp32
            # sample INSIDE the program: an eager sample after prefill is
            # a chain of separate device dispatches — ruinous TTFT on a
            # remote/tunnelled device. Token + its logprob pack into one
            # [2, B] int32 buffer (logprob bitcast) = one blocking read.
            first, lp = sample_tokens_with_logprobs(logits, sampling, key)
            packed = jnp.stack(
                [first, jax.lax.bitcast_convert_type(lp, jnp.int32)])
            return packed, ks, vs

        @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(1, 2, 3, 4, 5, 6))
        def _decode_chunk(
            params, ck, cv, lengths, last_tokens, active, produced,
            max_new, sampling, eos_ids, key, n_steps: int,
        ):
            """n_steps of decode for every slot, fully on device.

            Shapes: ck/cv [L,B,S,Hkv,Dh]; lengths/last_tokens/active/produced/
            max_new/eos_ids [B]. Emits tokens [n_steps, B] (-1 for inactive).
            """

            def step(carry, step_key):
                ck, cv, lengths, last, active, produced = carry
                hidden, ck, cv = forward_decode(
                    spec_, params, last, lengths, ck, cv
                )
                logits = unembed(spec_, params, hidden)        # [B, V]
                next_tok, lp = sample_tokens_with_logprobs(
                    logits, sampling, step_key)
                was_active = active
                produced = produced + was_active.astype(jnp.int32)
                hit_eos = (next_tok == eos_ids) & (eos_ids >= 0)
                done = hit_eos | (produced >= max_new)
                active = was_active & ~done
                lengths = lengths + was_active.astype(jnp.int32)
                last = jnp.where(was_active, next_tok, last)
                emitted = jnp.where(was_active, next_tok, -1)
                lp = jnp.where(was_active, lp, 0.0)
                return (ck, cv, lengths, last, active, produced), (emitted, lp)

            keys = jax.random.split(key, n_steps)
            carry, (toks, lps) = jax.lax.scan(
                step, (ck, cv, lengths, last_tokens, active, produced), keys
            )
            # pack emitted tokens + their logprobs (bitcast) + live flags
            # into ONE buffer: the host then makes exactly one blocking
            # read per chunk. Each sync is a full round trip — ~100 ms on
            # a tunnelled/remote device.
            packed = jnp.concatenate(
                [toks, jax.lax.bitcast_convert_type(lps, jnp.int32),
                 carry[4][None].astype(jnp.int32)], axis=0)
            return carry, packed

        self._prefill = _prefill
        self._decode_chunk = _decode_chunk

        # ---- metrics
        self.prefill_stats = LatencyStats()
        self.decode_stats = LatencyStats()
        cap = int(getattr(config, "timeline_capacity", 4096) or 0)
        self.timeline: Optional[StepTimeline] = (
            StepTimeline(capacity=cap, name="static") if cap else None)
        self._tl_programs: set = set()
        self._total_requests = 0
        self._total_prompt_tokens = 0
        self._total_generated_tokens = 0
        self._total_errors = 0

        if self.artifact_manifest is not None and artifact_selfcheck:
            # golden-token self-check BEFORE any traffic: replays the
            # save-time probe against the restored tree through the real
            # compiled programs (also a bb=1 warmup). Raises
            # ArtifactCorruptError on divergence — the factory falls back
            # to the slow path rather than serve wrong numerics.
            from .artifact import verify_golden

            verify_golden(self, self.artifact_manifest)

    # ------------------------------------------------------------ generate

    @hot_path
    def generate(self, requests: List[GenerationRequest]) -> List[GenerationResult]:
        """Run a batch of generation jobs to completion. Static-shape safe:
        pads batch and sequence dims to buckets so repeat calls hit the jit
        cache."""
        if not requests:
            return []
        self._total_requests += len(requests)
        n = len(requests)
        bb = _next_bucket(n, self.batch_buckets)
        max_prompt = max(len(r.prompt) for r in requests)
        if min(len(r.prompt) for r in requests) < 1:
            raise ValueError("empty prompt")
        # overlong prompts keep their tail (sliding-window truncation)
        max_prompt = min(max_prompt, max(self.prefill_buckets))
        tb = _next_bucket(max_prompt, self.prefill_buckets)
        max_new = max(r.max_new_tokens for r in requests)
        total_cap = max(tb, _next_bucket(
            min(max_prompt + max_new, self.seq_buckets[-1]), self.seq_buckets
        ))

        # ---- host-side batch assembly (numpy, then one transfer)
        tokens = np.zeros((bb, tb), dtype=np.int32)
        seq_lens = np.ones((bb,), dtype=np.int32)      # padded rows: len 1
        max_new_arr = np.zeros((bb,), dtype=np.int32)
        eos = np.full((bb,), -1, dtype=np.int32)
        temps = np.zeros((bb,), dtype=np.float32)
        top_k = np.zeros((bb,), dtype=np.int32)
        top_p = np.ones((bb,), dtype=np.float32)
        min_p = np.zeros((bb,), dtype=np.float32)
        for i, r in enumerate(requests):
            p = r.prompt[-tb:]                          # clamp overlong prompts
            tokens[i, : len(p)] = p
            seq_lens[i] = len(p)
            max_new_arr[i] = max(1, min(r.max_new_tokens, total_cap - len(p)))
            eos[i] = r.eos_id
            temps[i] = r.temperature
            top_k[i] = r.top_k
            top_p[i] = r.top_p
            min_p[i] = r.min_p
        sampling = SamplingParams(
            jnp.asarray(temps), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(min_p),
        )

        t0 = time.perf_counter()
        self._rng, k0 = jax.random.split(self._rng)
        first_packed, ks, vs = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(seq_lens),
            sampling, k0,
        )

        # ---- seed decode state; KV cache sized to the total-seq bucket.
        # With an sp mesh the cache is born sequence-sharded (decode then
        # runs context-parallel); small buckets that don't divide the mesh
        # axes fall back to the default placement
        L, Hkv, Dh = self.spec.n_layers, self.spec.n_kv_heads, self.spec.head_dim
        dt = jnp.dtype(self.config.kv_dtype)
        dev = {}
        if self._cache_sharding is not None:
            from ..parallel.sharding import compatible_sharding

            # per-axis fallback: bb=1 can't split over dp, but that must
            # not cost the sequence split
            dev = {"device": compatible_sharding(
                self._cache_sharding, (L, bb, total_cap, Hkv, Dh))}
        ck = jnp.zeros((L, bb, total_cap, Hkv, Dh), dtype=dt, **dev)
        cv = jnp.zeros((L, bb, total_cap, Hkv, Dh), dtype=dt, **dev)
        ck = ck.at[:, :, :tb].set(ks.astype(dt))
        cv = cv.at[:, :, :tb].set(vs.astype(dt))

        lengths = jnp.asarray(seq_lens)
        is_real = np.zeros((bb,), dtype=bool)
        is_real[:n] = True
        # graftlint: ok[host-sync-hot-path] ONE packed first-token read per generate() batch
        first_packed_np = np.asarray(first_packed)      # ONE blocking read
        first_np = first_packed_np[0]
        first_lp_np = first_packed_np[1].view(np.float32)
        produced_np = is_real.astype(np.int32)          # the prefill sample
        hit = is_real & (first_np == eos) & (eos >= 0)
        active_np = is_real & ~hit & (produced_np < max_new_arr)
        first_np = np.where(is_real, first_np, -1)

        ttft = time.perf_counter() - t0
        self.prefill_stats.add(ttft)
        if self.timeline is not None:
            prog = ("prefill", bb, tb)
            first_seen = prog not in self._tl_programs
            self._tl_programs.add(prog)
            self.timeline.record("prefill", t0, ttft, rows=n,
                                 prefill_tokens=int(seq_lens[:n].sum()),
                                 **({"compile": True} if first_seen else {}))

        out_tokens: List[List[int]] = [[int(first_np[i])] for i in range(n)]
        out_lps: List[List[float]] = [[float(first_lp_np[i])]
                                      for i in range(n)]

        active = jnp.asarray(active_np)
        produced = jnp.asarray(produced_np)
        last = jnp.asarray(np.where(first_np >= 0, first_np, 0).astype(np.int32))
        max_new_j = jnp.asarray(max_new_arr)
        eos_j = jnp.asarray(eos)

        t1 = time.perf_counter()
        n_steps = self.config.decode_steps_per_call
        # loop condition runs on the HOST mirror of the active flags (seeded
        # from the prefill sample, updated from each chunk's packed row) —
        # a device-side active.any() would cost one extra round trip per
        # chunk
        act_host = active_np
        scanned = [0] * n        # host-stop scan resume offsets
        # the prefill-sampled FIRST token can itself match stop_ids/
        # stop_sequences (ADVICE r2): scan before the loop so such a
        # request never burns a full decode chunk
        stopped_rows = scan_host_stops(out_tokens, requests, act_host,
                                       scanned)
        if stopped_rows and act_host.any():
            active = active.at[
                jnp.asarray(stopped_rows, jnp.int32)].set(False)
        while act_host.any():
            self._rng, kc = jax.random.split(self._rng)
            (ck, cv, lengths, last, active, produced), packed = self._decode_chunk(
                self.params, ck, cv, lengths, last, active, produced,
                max_new_j, sampling, eos_j, kc, n_steps=n_steps,
            )
            # graftlint: ok[host-sync-hot-path] THE designed sync point: ONE packed read per n_steps-token decode chunk
            packed_np = np.asarray(packed)   # ONE blocking read per chunk
            toks_np = packed_np[:n_steps]               # [n_steps, bb]
            lps_np = packed_np[n_steps:2 * n_steps].view(np.float32)
            act_host = packed_np[-1].astype(bool)
            for i in range(n):
                for s in range(n_steps):
                    t = int(toks_np[s, i])
                    if t >= 0:
                        out_tokens[i].append(t)
                        out_lps[i].append(float(lps_np[s, i]))
            # early exit on host-side stops (ADVICE r1): the device loop
            # only knows eos_id, so a request whose stop_ids/stop_sequences
            # matched would otherwise burn decode chunks to max_new_tokens
            # and be trimmed after the fact. One batched flag clear —
            # skipped when the loop is exiting anyway.
            stopped_rows = scan_host_stops(out_tokens, requests, act_host,
                                           scanned)
            if stopped_rows and act_host.any():
                active = active.at[
                    jnp.asarray(stopped_rows, jnp.int32)].set(False)
        decode_t = time.perf_counter() - t1
        self.decode_stats.add(decode_t)
        if self.timeline is not None:
            prog = ("decode", bb, n_steps)
            first_seen = prog not in self._tl_programs
            self._tl_programs.add(prog)
            self.timeline.record("decode", t1, decode_t, rows=n,
                                 n_steps=n_steps,
                                 **({"compile": True} if first_seen else {}))

        results = []
        for i, r in enumerate(requests):
            toks, stopped = trim_at_stops(out_tokens[i], r)
            lps = out_lps[i][: len(toks)]
            self._total_prompt_tokens += len(r.prompt)
            self._total_generated_tokens += len(toks)
            results.append(
                GenerationResult(
                    request_id=r.request_id or f"gen-{self._total_requests}-{i}",
                    tokens=toks,
                    finish_reason="stop" if stopped else "length",
                    prompt_tokens=len(r.prompt),
                    logprobs=lps,
                    ttft_s=ttft,
                    decode_s=decode_t,
                )
            )
        return results

    # ------------------------------------------------------------- warmup

    def warmup(self, batch: Optional[int] = None,
               max_new_tokens: int = 2) -> int:
        """Pre-compile the serving programs by running one tiny generate
        per (batch bucket × prefill bucket) — EVERY batch bucket by
        default, because the first real request is typically a single one
        (bb=1) and warming only the largest bucket would leave exactly
        that shape cold. Because total-cap buckets round up, a warmup with
        small ``max_new_tokens`` usually lands in the same decode-chunk
        shape moderate generations use; the prompt is clamped below the
        top sequence bucket so at least one decode chunk actually runs.
        Stat counters do tick (warmup IS traffic). Returns the number of
        warmup generates run."""
        sizes = [batch] if batch else self.batch_buckets
        runs = 0
        for n in sizes:
            for tb in self.prefill_buckets:
                plen = max(1, min(tb, self.seq_buckets[-1] - max_new_tokens))
                self.generate([
                    GenerationRequest(prompt=[1] * plen,
                                      max_new_tokens=max_new_tokens)
                    for _ in range(n)
                ])
                runs += 1
        return runs

    def warmup_from_manifest(self, max_new_tokens: int = 2) -> int:
        """Artifact-aware warmup: compile only the batch buckets the
        artifact's writer recorded as its serving shapes, so a respawned
        worker warms what its predecessor actually served instead of the
        full bucket grid. Falls back to the full ``warmup`` when the
        manifest records nothing usable (absent, or config drifted)."""
        b = (self.artifact_manifest or {}).get("buckets", {})
        batches = [n for n in b.get("batch", []) if n in self.batch_buckets]
        if not batches:
            return self.warmup(max_new_tokens=max_new_tokens)
        return sum(self.warmup(batch=n, max_new_tokens=max_new_tokens)
                   for n in batches)

    # ------------------------------------------------------------- metrics

    def get_metrics(self) -> Dict[str, Any]:
        """Every component exposes get_stats/get_metrics (SURVEY.md §5)."""
        return {
            "total_requests": self._total_requests,
            "total_prompt_tokens": self._total_prompt_tokens,
            "total_generated_tokens": self._total_generated_tokens,
            "total_errors": self._total_errors,
            "prefill": self.prefill_stats.snapshot(),
            "decode": self.decode_stats.snapshot(),
            "spec": {
                "n_layers": self.spec.n_layers,
                "d_model": self.spec.d_model,
                "vocab_size": self.spec.vocab_size,
            },
        }
