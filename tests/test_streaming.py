"""Streaming + profiler tests: token chunks ride the framed connection
ahead of the final result (multi-frame responses, ``utils/rpc.py``
``_stream_methods``/``call_stream``), end-to-end through worker and
coordinator; ``profile`` wraps jax.profiler trace capture (SURVEY.md §5
tracing plan)."""

import asyncio
import os

import pytest

from distributed_inference_engine_tpu.api import (
    Coordinator,
    CoordinatorClient,
    CoordinatorConfig,
    CoordinatorServer,
)
from distributed_inference_engine_tpu.config import (
    EngineConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.cluster.worker import (
    WorkerClient,
    WorkerRPCError,
    WorkerServer,
)
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=64)


def _model_cfg(name="m", continuous=True):
    meta = {"size": "llama-tiny", "page_size": 16, "num_pages": 64,
            "attention_impl": "xla", "kv_dtype": "float32",
            "decode_steps_per_call": 3}
    if continuous:
        meta["continuous"] = 1
    return ModelConfig(name=name, architecture="llama", dtype="float32",
                       max_seq_len=64, max_batch_size=4, metadata=meta)


# -------------------------------------------------------------- engine level


def test_engine_stream_callback_matches_result():
    eng = ContinuousEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=64, page_size=16, num_pages=32,
        decode_steps_per_call=3, attention_impl="xla"))
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=10,
                                 temperature=0.0, request_id="s"),
               on_tokens=chunks.append)
    res = eng.run_until_idle()[0]
    streamed = [t for c in chunks for t in c]
    assert streamed == res.tokens
    assert len(chunks) >= 2                     # actually incremental


def test_engine_stream_respects_eos_trim():
    eng = ContinuousEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=64, page_size=16, num_pages=32,
        decode_steps_per_call=4, attention_impl="xla"))
    probe = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                            max_new_tokens=10,
                                            temperature=0.0)])[0].tokens
    eos = probe[3]
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=10,
                                 temperature=0.0, eos_id=eos),
               on_tokens=chunks.append)
    res = eng.run_until_idle()[0]
    streamed = [t for c in chunks for t in c]
    assert streamed == res.tokens               # no post-EOS leakage
    assert res.finish_reason == "stop"


# -------------------------------------------------------------- worker level


@pytest.mark.asyncio
async def test_worker_generate_stream_roundtrip():
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg())
        c = WorkerClient(*w.address, timeout=120.0)
        chunks = []
        req = GenerationRequest(prompt=[4, 5, 6], max_new_tokens=9,
                                temperature=0.0, request_id="r")
        res = await c.generate_stream("m", req, chunks.append)
        assert [t for ch in chunks for t in ch] == res.tokens
        assert len(res.tokens) == 9
        assert len(chunks) >= 2
        # matches non-streaming output
        plain = await c.generate("m", [GenerationRequest(
            prompt=[4, 5, 6], max_new_tokens=9, temperature=0.0)])
        assert plain[0].tokens == res.tokens
        await c.close()
    finally:
        await w.stop()


@pytest.mark.asyncio
async def test_worker_stream_on_static_engine_is_informative():
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg(continuous=False))
        c = WorkerClient(*w.address, timeout=120.0)
        with pytest.raises(WorkerRPCError, match="continuous"):
            await c.generate_stream(
                "m", GenerationRequest(prompt=[1], max_new_tokens=2),
                lambda t: None)
        # server keeps serving afterwards
        assert (await c.ping())["worker_id"] == "w"
        await c.close()
    finally:
        await w.stop()


# --------------------------------------------------------- coordinator level


@pytest.mark.asyncio
async def test_coordinator_stream_end_to_end():
    coord = Coordinator(CoordinatorConfig())
    server = CoordinatorServer(coord, ServerConfig(port=0))
    await server.start()
    workers = []
    try:
        w = WorkerServer(ServerConfig(worker_id="w0", port=0))
        host, port = await w.start()
        workers.append(w)
        coord.add_worker("w0", host, port)
        await coord.deploy_model(_model_cfg())

        chost, cport = server.address
        client = CoordinatorClient(chost, cport)
        chunks = []
        out = await client.generate_stream(
            "m", chunks.append, prompt=[7, 8, 9], max_new_tokens=8)
        assert [t for c in chunks for t in c] == out["tokens"]
        assert out["streamed"] is True
        assert out["metadata"]["worker_id"] == "w0"
        # plain path still works on the same connection
        plain = await client.generate("m", prompt=[7, 8, 9],
                                      max_new_tokens=8)
        assert plain["tokens"] == out["tokens"]
        await client.close()
    finally:
        await server.stop()
        for w in workers:
            await w.stop()


# ------------------------------------------------------------------ profiler


@pytest.mark.asyncio
async def test_profile_start_stop_cycle(tmp_path):
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        c = WorkerClient(*w.address, timeout=60.0)
        trace_dir = str(tmp_path / "trace")
        out = await c.call("profile", action="start", trace_dir=trace_dir)
        assert out["profiling"] is True
        with pytest.raises(WorkerRPCError, match="already active"):
            await c.call("profile", action="start")
        # do some work under the trace
        await w.load_model_async(_model_cfg())
        await c.generate("m", [GenerationRequest(prompt=[1, 2],
                                                 max_new_tokens=2)])
        out = await c.call("profile", action="stop")
        assert out["trace_dir"] == trace_dir
        assert os.path.isdir(trace_dir)
        with pytest.raises(WorkerRPCError, match="not active"):
            await c.call("profile", action="stop")
        await c.close()
    finally:
        await w.stop()


@pytest.mark.asyncio
async def test_coordinator_stream_fails_over_before_first_chunk():
    """A dead worker at dispatch time must not fail the stream — the
    coordinator retries on an alternate as long as nothing has streamed
    (review finding: streaming lacked the non-streaming path's failover)."""
    coord = Coordinator(CoordinatorConfig())
    await coord.start()
    workers = []
    try:
        for i in range(2):
            w = WorkerServer(ServerConfig(worker_id=f"w{i}", port=0))
            host, port = await w.start()
            workers.append(w)
            coord.add_worker(f"w{i}", host, port)
        await coord.deploy_model(_model_cfg())
        await workers[0].stop()          # kill one replica

        seen = []
        for i in range(3):
            out = await coord.submit_stream(
                "m", prompt=[5, 6, 7 + i], max_new_tokens=4,
                on_tokens=lambda t: seen.extend(t), key=f"k{i}")
            assert len(out["tokens"]) == 4
            assert out["metadata"]["worker_id"] == "w1"
        assert len(seen) == 12
    finally:
        await coord.stop()
        await workers[1].stop()


@pytest.mark.asyncio
async def test_client_disconnect_mid_stream_keeps_server_alive():
    """A client hanging up mid-stream is routine (aborted generation) —
    the worker must log-and-continue, not die or count an engine error."""
    import asyncio as aio

    from distributed_inference_engine_tpu.utils.framing import (
        read_frame,
        write_frame,
    )

    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg())
        host, port = w.address
        reader, writer = await aio.open_connection(host, port)
        await write_frame(writer, {
            "method": "generate_stream", "id": "x", "model": "m",
            "request": {"prompt": [1, 2, 3], "max_new_tokens": 40,
                        "temperature": 0.0},
        })
        # read one chunk frame, then slam the connection shut
        frame = await read_frame(reader)
        assert frame.get("stream") is True
        writer.close()
        # the server must still answer new connections and requests
        await aio.sleep(0.5)
        c = WorkerClient(host, port, timeout=120.0)
        out = await c.generate("m", [GenerationRequest(
            prompt=[1, 2], max_new_tokens=3)])
        assert len(out[0].tokens) == 3
        await c.close()
    finally:
        await w.stop()
