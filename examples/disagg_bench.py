"""Disaggregated prefill/decode: measured on real hardware (VERDICT r2 item 3).

Two pools in ONE process — a prefill WorkerServer and a continuous-decode
WorkerServer on loopback framed RPC, sharing one set of int8 weights (the
single available chip executes both pools' programs; the wire format,
framing, batching and handoff path are exactly the two-host deployment's).
Measures:

- handoff bytes per request (the dense [L, T, Hkv, Dh] KV payload),
- prefill + handoff serialization/transfer time (client-observed),
- decode-pool admission cost for handed-off KV,
- relay end-to-end (prefill pool -> decode peer -> results) vs the SAME
  decode engine serving the same requests single-pool.

Loopback measures serialization + copy + framing; a real DCN hop adds
bytes/bandwidth on top — the printed bytes-per-request is the number to
divide by your DCN bandwidth (docs/design.md's estimate, now measured).

Usage:  python examples/disagg_bench.py
Knobs:  BENCH_MODEL/BENCH_QUANT/BENCH_BATCH (default 16),
        BENCH_PROMPT (default 512), BENCH_NEW_TOKENS (default 128)

``--coordinator`` runs the COORDINATOR-path mode instead (ISSUE 10): the
same two pools, but deployed via ``deploy_model_disaggregated`` and driven
through ``Coordinator.submit`` — requests cross the real framed-RPC control
plane (coordinator -> prefill worker -> KV handoff -> decode worker),
against a single-pool reference worker deployed on the same coordinator.
The JSON row records handoff bytes (serialize/transfer, from the prefill
worker's ``handoff_bytes_shipped`` counter), handoff bytes/s, end-to-end
latency percentiles, and the coordinator-path overhead vs single-pool.

    BENCH_MODEL=llama-tiny BENCH_PROMPT=32 BENCH_NEW_TOKENS=8 \
        BENCH_BATCH=4 python examples/disagg_bench.py --coordinator
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("BENCH_BATCH", "16")
os.environ.setdefault("BENCH_PROMPT", "512")

import bench  # noqa: E402
from distributed_inference_engine_tpu.config import (  # noqa: E402
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.cluster.worker import (  # noqa: E402
    WorkerClient,
    WorkerServer,
)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


async def main():
    spec = bench._spec()
    n = bench.BATCH
    t0 = time.perf_counter()
    params = bench._build_params(spec, bench.QUANT)
    from distributed_inference_engine_tpu.config import EngineConfig
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )
    from distributed_inference_engine_tpu.engine.disagg import PrefillEngine

    max_seq = min(spec.max_seq_len, bench.PROMPT_LEN + bench.NEW_TOKENS)
    # 2x page backing: the delta-handoff phase needs the PREVIOUS batch's
    # registered prefix pages still resident — an exactly-sized pool
    # reclaims them for the next batch's allocations (measured: 14/16
    # probes missed with 1x backing)
    ecfg = EngineConfig(
        max_slots=n, max_seq_len=max_seq,
        prefill_buckets=[bench.PROMPT_LEN], decode_steps_per_call=64,
        page_size=128, num_pages=2 * n * (-(-max_seq // 128)) + 8,
    )

    def factory(cfg: ModelConfig):
        if cfg.metadata.get("role") == "prefill":
            return PrefillEngine(spec, params=params, config=ecfg)
        return ContinuousEngine(spec, params=params, config=ecfg)

    pre = WorkerServer(ServerConfig(worker_id="pool-prefill", port=0,
                                    max_frame_bytes=2 * 1024 * 1024 * 1024),
                       engine_factory=factory)
    dec = WorkerServer(ServerConfig(worker_id="pool-decode", port=0,
                                    max_frame_bytes=2 * 1024 * 1024 * 1024),
                       engine_factory=factory)
    ph, pp = await pre.start()
    dh, dp = await dec.start()
    await pre.load_model_async(ModelConfig(
        name="m", architecture=bench.MODEL, max_seq_len=max_seq,
        metadata={"role": "prefill"}))
    await dec.load_model_async(ModelConfig(
        name="m", architecture=bench.MODEL, max_seq_len=max_seq,
        metadata={"continuous": 1}))
    # 8B-scale first-compile of a 512-token prefill bucket takes minutes on
    # a tunnelled chip — the default RPC timeout is for serving, not warmup
    ca = WorkerClient(ph, pp, max_frame=2 * 1024 * 1024 * 1024, timeout=600.0)
    cb = WorkerClient(dh, dp, max_frame=2 * 1024 * 1024 * 1024, timeout=600.0)
    log(f"pools up ({bench.MODEL}, int8={bench.QUANT}, bs{n}, prompt "
        f"{bench.PROMPT_LEN} + {bench.NEW_TOKENS} new): "
        f"{time.perf_counter() - t0:.1f}s")

    from distributed_inference_engine_tpu.cluster.worker import (
        request_to_dict,
    )
    from distributed_inference_engine_tpu.engine.disagg import (
        handoff_to_wire,
    )

    def reqs(seed):
        return bench._requests(spec, seed, n)

    # ---- warmup/compile both paths, including the per-group batch
    # buckets the pipelined relay admits (group prefills run at n/4)
    t0 = time.perf_counter()
    warm = await ca.prefill("m", reqs(1))
    await cb.call("generate_prefilled", model="m",
                  requests=[request_to_dict(r) for r in reqs(1)],
                  handoffs=[handoff_to_wire(h) for h in warm],
                  timeout=600.0)
    await cb.generate("m", reqs(2), timeout=600.0)
    for pg in (1, 4):
        short = reqs(3)
        for r in short:
            r.max_new_tokens = 2
        await ca.call("prefill_generate", model="m",
                      requests=[request_to_dict(r) for r in short],
                      decode_host=dh, decode_port=dp, peer_timeout=600.0,
                      pipeline_groups=pg, timeout=600.0)
    log(f"warmup (compile both pools): {time.perf_counter() - t0:.1f}s")

    # ---- 1) prefill + handoff transfer (client-observed, loopback frame)
    t0 = time.perf_counter()
    handoffs = await ca.prefill("m", reqs(10))
    t_prefill_ship = time.perf_counter() - t0
    kv_bytes = sum(h.k.nbytes + h.v.nbytes for h in handoffs)

    # ---- 2) decode-pool admission of handed-off KV (2 tokens)
    short = reqs(10)
    for r in short:
        r.max_new_tokens = 2
    t0 = time.perf_counter()
    await cb.call("generate_prefilled", model="m",
                  requests=[request_to_dict(r) for r in short],
                  handoffs=[handoff_to_wire(h) for h in handoffs],
                  timeout=600.0)
    t_admit = time.perf_counter() - t0

    # ---- 3) relay end-to-end vs single-pool, same engine, same requests.
    # pipeline_groups=1: monolithic (prefill all -> ship all -> decode);
    # =4: group g+1 prefills while group g's KV is in flight and decoding
    t0 = time.perf_counter()
    out = await ca.call("prefill_generate", model="m",
                        requests=[request_to_dict(r) for r in reqs(20)],
                        decode_host=dh, decode_port=dp, peer_timeout=600.0,
                        pipeline_groups=1, timeout=600.0)
    t_mono = time.perf_counter() - t0
    toks_mono = sum(len(r["tokens"]) for r in out["results"])

    t0 = time.perf_counter()
    out = await ca.call("prefill_generate", model="m",
                        requests=[request_to_dict(r) for r in reqs(21)],
                        decode_host=dh, decode_port=dp, peer_timeout=600.0,
                        pipeline_groups=4, timeout=600.0)
    t_disagg = time.perf_counter() - t0
    toks_disagg = sum(len(r["tokens"]) for r in out["results"])

    t0 = time.perf_counter()
    res_single = await cb.generate("m", reqs(30), timeout=600.0)
    t_single = time.perf_counter() - t0
    toks_single = sum(len(r.tokens) for r in res_single)

    # ---- 4) prefix-aware delta handoff: repeat the SAME prompts — the
    # decode pool's prefix cache holds their full pages, so the relay
    # ships only each prompt's final partial page
    shipped0 = (await ca.call("metrics"))["handoff_bytes_shipped"]
    t0 = time.perf_counter()
    out = await ca.call("prefill_generate", model="m",
                        requests=[request_to_dict(r) for r in reqs(21)],
                        decode_host=dh, decode_port=dp, peer_timeout=600.0,
                        timeout=600.0)
    t_delta = time.perf_counter() - t0
    toks_delta = sum(len(r["tokens"]) for r in out["results"])
    shipped_delta = ((await ca.call("metrics"))["handoff_bytes_shipped"]
                     - shipped0)

    row = {
        "metric": f"disagg_{bench.MODEL}{'_int8' if bench.QUANT else ''}"
                  f"_bs{n}_p{bench.PROMPT_LEN}",
        "kv_handoff_mb_per_req": round(kv_bytes / n / 1e6, 2),
        "prefill_ship_s": round(t_prefill_ship, 2),
        "admit_s": round(t_admit, 2),
        "disagg_mono_e2e_s": round(t_mono, 2),
        "disagg_pipe4_e2e_s": round(t_disagg, 2),
        "single_e2e_s": round(t_single, 2),
        "disagg_tok_s": round(toks_disagg / t_disagg, 1),
        "single_tok_s": round(toks_single / t_single, 1),
        "pipeline_gain_pct": round(100 * (t_mono - t_disagg) / t_mono, 1),
        "overhead_vs_single_pct": round(
            100 * (t_disagg - t_single) / t_single, 1),
        "delta_repeat_e2e_s": round(t_delta, 2),
        "delta_shipped_mb_per_req": round(shipped_delta / n / 1e6, 2),
        "delta_bytes_saved_pct": round(
            100 * (1 - shipped_delta / max(kv_bytes, 1)), 1),
    }
    assert (toks_mono > 0 and toks_disagg > 0 and toks_single > 0
            and toks_delta > 0)
    print(json.dumps(row), flush=True)
    await ca.close()
    await cb.close()
    await pre.stop()
    await dec.stop()


async def main_coordinator():
    """Coordinator-path mode: prefill + decode + single-pool reference
    workers on one coordinator; both paths driven through
    ``Coordinator.submit`` over the framed control plane. Workers build
    their own engines from the ModelConfig (random-init, fixed key), so
    the disagg path and the reference share weights and must agree
    token-for-token at temperature 0."""
    from distributed_inference_engine_tpu.api.coordinator import (
        Coordinator, CoordinatorConfig,
    )

    n = bench.BATCH
    max_seq = bench.PROMPT_LEN + bench.NEW_TOKENS
    big = 2 * 1024 * 1024 * 1024
    coord = Coordinator(CoordinatorConfig(dispatch_timeout_s=600.0))
    await coord.start()
    servers = {}
    for wid in ("p0", "d0", "ref0"):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=wid, max_frame_bytes=big))
        host, port = await w.start()
        servers[wid] = w
        coord.add_worker(wid, host, port)
    t0 = time.perf_counter()
    cfg = ModelConfig(name="m", architecture=bench.MODEL,
                      max_seq_len=max_seq, max_batch_size=n,
                      metadata={"continuous": 1, "max_slots": n})
    ref = ModelConfig(name="m_ref", architecture=bench.MODEL,
                      max_seq_len=max_seq, max_batch_size=n,
                      metadata={"continuous": 1, "max_slots": n})
    await coord.deploy_model_disaggregated(cfg, ["p0"], ["d0"])
    await coord.deploy_model(ref, worker_ids=["ref0"])
    log(f"coordinator fleet up ({bench.MODEL}, prompt {bench.PROMPT_LEN} "
        f"+ {bench.NEW_TOKENS} new): {time.perf_counter() - t0:.1f}s")

    import numpy as np
    rs = np.random.RandomState(17)
    prompts = [[int(rs.randint(1, 96)) for _ in range(bench.PROMPT_LEN)]
               for _ in range(n)]

    async def run(model, seed_tag):
        lats = []
        t0 = time.perf_counter()
        outs = []
        for i, p in enumerate(prompts):
            t1 = time.perf_counter()
            r = await coord.submit(model, prompt=p,
                                   max_new_tokens=bench.NEW_TOKENS,
                                   request_id=f"{seed_tag}{i}",
                                   no_cache=True)
            lats.append(time.perf_counter() - t1)
            outs.append(r)
        return outs, time.perf_counter() - t0, lats

    # warmup/compile both paths, then the timed passes
    await run("m", "warm")
    await run("m_ref", "warmref")
    m0 = await coord.router.client_for("p0").metrics()
    outs, t_disagg, lats = await run("m", "c")
    m1 = await coord.router.client_for("p0").metrics()
    refs, t_single, ref_lats = await run("m_ref", "s")
    shipped = (m1["handoff_bytes_shipped"] - m0["handoff_bytes_shipped"])
    exact = sum(1 for a, b in zip(outs, refs)
                if a["tokens"] == b["tokens"])
    toks = sum(len(r["tokens"]) for r in outs)
    row = {
        "metric": f"disagg_coord_{bench.MODEL}_bs{n}_p{bench.PROMPT_LEN}",
        "mode": "coordinator",
        "requests": n,
        "token_exact_vs_single": exact,
        "handoff_mb_per_req": round(shipped / n / 1e6, 3),
        "handoff_bytes_per_s": round(shipped / t_disagg, 1),
        "disagg_e2e_s": round(t_disagg, 2),
        "single_e2e_s": round(t_single, 2),
        "disagg_tok_s": round(toks / t_disagg, 1),
        "lat_p50_s": round(bench.pct(lats, 0.5), 3),
        "lat_p99_s": round(bench.pct(lats, 0.99), 3),
        "single_lat_p50_s": round(bench.pct(ref_lats, 0.5), 3),
        "overhead_vs_single_pct": round(
            100 * (t_disagg - t_single) / max(t_single, 1e-9), 1),
    }
    assert exact == n, f"coordinator disagg path diverged: {exact}/{n}"
    print(json.dumps(row), flush=True)
    await coord.stop()
    for w in servers.values():
        await w.stop()


if __name__ == "__main__":
    if "--coordinator" in sys.argv[1:]:
        asyncio.run(main_coordinator())
    else:
        asyncio.run(main())
