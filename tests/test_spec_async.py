"""Bubble-scheduled async speculation tests (engine/spec_async.py +
engine/spec_accept.py + the continuous engine's verify chunk — ISSUE 15).

Correctness bar, same as the r5 sync engine but stricter in scope:
speculation may only change LATENCY, never content. Greedy output with
the drafter on must be token-for-token the plain continuous engine's own
chain — for any draft quality (accept-all through reject-all), any
weight dtype, and any bubble-budget decision. The acceptance math itself
is pinned bit-for-bit against a frozen reimplementation of the r5
rejection-sampling rule so the shared module can never drift under
either consumer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_engine_tpu.config import EngineConfig
from distributed_inference_engine_tpu.engine.continuous import (
    ContinuousEngine,
)
from distributed_inference_engine_tpu.engine.spec_accept import (
    rejection_accept,
)
from distributed_inference_engine_tpu.engine.spec_async import resolve_draft
from distributed_inference_engine_tpu.engine.speculative import (
    scale_top_blocks,
)
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.base import (
    ModelSpec,
    init_params,
)
from distributed_inference_engine_tpu.obs.timeline import busy_gap_split

pytestmark = pytest.mark.spec

# n_kv_heads * head_dim must stay a multiple of 128 (paged-layout lane
# alignment); 2 heads x 64 = 128 is the smallest compliant shape.
SPEC = ModelSpec(vocab_size=128, d_model=128, n_layers=2, n_heads=2,
                 n_kv_heads=2, d_ff=128, max_seq_len=128, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.key(0))


def _cfg(spec_async, floor=0.0, k=4):
    return EngineConfig(max_slots=4, page_size=16, num_pages=64,
                        max_seq_len=96, decode_steps_per_call=6,
                        spec_async=spec_async, spec_draft_model="layers:1",
                        spec_max_draft=k, spec_bubble_floor_s=floor)


def _run(spec, params, cfg, *, temp=0.0, n_req=3, nt=20, seed=0,
         draft=None):
    """Submit ``n_req`` streamed requests, pump to completion; returns
    (streamed tokens per request, engine)."""
    kw = {}
    if draft is not None:
        kw = {"draft_spec": draft[0], "draft_params": draft[1]}
    eng = ContinuousEngine(spec, params, cfg, seed=seed, **kw)
    streamed = {i: [] for i in range(n_req)}
    for i in range(n_req):
        r = GenerationRequest(prompt=[7 + i, 11, 13], max_new_tokens=nt,
                              temperature=temp)
        eng.submit(r, on_tokens=(lambda t, i=i: streamed[i].extend(t)))
    for _ in range(400):
        if eng.step() == 0 and not eng.n_waiting:
            break
    return streamed, eng


# ---------------------------------------------------------------------------
# acceptance math: bit-parity against a frozen r5 reference
# ---------------------------------------------------------------------------


def _frozen_r5_accept(p, q, drafts, greedy, key_resid, key_bonus,
                      valid=None):
    """Independent numpy reimplementation of the r5 acceptance block
    (frozen at the refactor): loop form, same key usage and op order as
    the pre-refactor ``_round_core``. Any drift in the shared module
    shows up as a bit mismatch here."""
    b, k = drafts.shape
    u = np.asarray(jax.random.uniform(key_resid, drafts.shape))
    accept = np.zeros((b, k), bool)
    for i in range(b):
        for j in range(k):
            d = int(drafts[i, j])
            if greedy[i]:
                accept[i, j] = int(np.argmax(p[i, j])) == d
            else:
                accept[i, j] = u[i, j] * q[i, j, d] < p[i, j, d]
            if valid is not None and not valid[i, j]:
                accept[i, j] = False
    n_acc = np.zeros(b, np.int32)
    for i in range(b):
        while n_acc[i] < k and accept[i, n_acc[i]]:
            n_acc[i] += 1
    final_dist = np.zeros((b, p.shape[-1]))
    for i in range(b):
        if n_acc[i] == k:
            final_dist[i] = p[i, k]
        else:
            pos = min(int(n_acc[i]), k - 1)
            resid = np.maximum(p[i, pos] - q[i, pos], 0.0)
            if resid.sum() <= 1e-9:
                resid = p[i, pos]
            final_dist[i] = resid / resid.sum()
    f_samp = np.asarray(jax.random.categorical(
        key_bonus, jnp.log(jnp.maximum(jnp.asarray(final_dist), 1e-30)),
        axis=-1))
    final = np.where(greedy, final_dist.argmax(-1), f_samp)
    return n_acc, final.astype(np.int32), accept


@pytest.mark.parametrize("greedy_all", [True, False])
@pytest.mark.parametrize("masked", [False, True])
def test_rejection_accept_bit_parity_vs_frozen_r5(greedy_all, masked):
    b, k, v = 5, 4, 32
    rng = np.random.RandomState(7 + masked)
    p = rng.dirichlet(np.ones(v) * 0.3, size=(b, k + 1))
    q = rng.dirichlet(np.ones(v) * 0.3, size=(b, k))
    drafts = rng.randint(0, v, size=(b, k)).astype(np.int32)
    greedy = np.full(b, greedy_all)
    valid = (rng.rand(b, k) < 0.6) if masked else None
    kr, kb = jax.random.split(jax.random.key(3))
    n_ref, f_ref, a_ref = _frozen_r5_accept(p, q, drafts, greedy, kr, kb,
                                            valid)
    n, f, a = rejection_accept(
        jnp.asarray(p, jnp.float32), jnp.asarray(q, jnp.float32),
        jnp.asarray(drafts), jnp.asarray(greedy), kr, kb,
        valid=None if valid is None else jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(n), n_ref)
    np.testing.assert_array_equal(np.asarray(f), f_ref)
    np.testing.assert_array_equal(np.asarray(a), a_ref)


def test_plain_rows_reduce_to_plain_decode():
    """A verify row with zero draft columns (all-False mask + zero
    q_probs) must sample exactly the target distribution at position 0 —
    that is what lets plain rows ride the verify program unchanged."""
    b, k, v = 3, 4, 16
    rng = np.random.RandomState(11)
    p = rng.dirichlet(np.ones(v), size=(b, k + 1)).astype(np.float32)
    q = np.zeros((b, k, v), np.float32)
    drafts = np.zeros((b, k), np.int32)
    kr, kb = jax.random.split(jax.random.key(5))
    n, f, _ = rejection_accept(
        jnp.asarray(p), jnp.asarray(q), jnp.asarray(drafts),
        jnp.asarray(np.ones(b, bool)), kr, kb,
        valid=jnp.zeros((b, k), bool))
    assert np.asarray(n).tolist() == [0] * b
    np.testing.assert_array_equal(np.asarray(f), p[:, 0].argmax(-1))


# ---------------------------------------------------------------------------
# greedy chain identity across weight dtypes and drafter extremes
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def base_off(params):
    streamed, _ = _run(SPEC, params, _cfg(False))
    return streamed


def test_greedy_exact_f32(params, base_off):
    streamed, eng = _run(SPEC, params, _cfg(True))
    assert streamed == base_off
    m = eng.get_metrics()
    assert m["spec_async_drafted_tokens"] > 0, "drafter never engaged"
    assert m["spec_async_verify_steps"] > 0, "verify path never ran"
    # compile-count guard: the verify program buckets only on the stop
    # mask — one fixed [B, k+1] window shape, at most two programs
    verify_programs = {p for p in eng._tl_programs
                       if isinstance(p, tuple) and p and p[0] == "verify"}
    assert 0 < len(verify_programs) <= 2, verify_programs


def test_greedy_exact_int4(params):
    from distributed_inference_engine_tpu.ops.quant import quantize_params

    qparams = quantize_params(SPEC, params, bits=4)
    off, _ = _run(SPEC, qparams, _cfg(False))
    on, eng = _run(SPEC, qparams, _cfg(True))
    assert on == off
    assert eng.get_metrics()["spec_async_drafted_tokens"] > 0


def test_accept_all_extreme(params):
    """eps=0 scaled target + layers:1 draft: the drafter's forward IS the
    target's (top block contributes zero residual), so greedy acceptance
    hits the machinery ceiling — only budget-cut tails are lost."""
    sp = scale_top_blocks(SPEC, params, n_shared=1, eps=0.0)
    off, _ = _run(SPEC, sp, _cfg(False))
    on, eng = _run(SPEC, sp, _cfg(True))
    assert on == off
    m = eng.get_metrics()
    assert m["spec_async_accept_rate"] >= 0.9, m["spec_async_accept_rate"]


def test_reject_all_extreme(params, base_off):
    """An independently initialized draft agrees with the target
    near-never — acceptance collapses but output must not move."""
    d_spec = SPEC.replace(n_layers=1)
    d_params = init_params(d_spec, jax.random.key(99))
    streamed, eng = _run(SPEC, params, _cfg(True),
                         draft=(d_spec, d_params))
    assert streamed == base_off
    m = eng.get_metrics()
    assert m["spec_async_drafted_tokens"] > 0
    assert m["spec_async_accept_rate"] < 0.2, m["spec_async_accept_rate"]


def test_saturation_auto_idle(params, base_off):
    """A bubble floor the rig can never clear must idle the drafter
    completely (zero drafted tokens, zero verify dispatches) while output
    stays the plain chain — the <=2% saturation-goodput contract's
    mechanism."""
    streamed, eng = _run(SPEC, params, _cfg(True, floor=10.0))
    assert streamed == base_off
    m = eng.get_metrics()
    assert m["spec_async_drafted_tokens"] == 0
    assert m["spec_async_verify_steps"] == 0
    assert m["spec_async_auto_idles"] > 0


def test_same_seed_determinism(params):
    """Sampled decode with the drafter on: two same-seed runs must emit
    identical streams AND identical drafter ledgers (the fleet receipts
    contract, at engine scope)."""
    a, ea = _run(SPEC, params, _cfg(True), temp=0.8)
    b, eb = _run(SPEC, params, _cfg(True), temp=0.8)
    assert a == b
    ma, mb = ea.get_metrics(), eb.get_metrics()
    for key in ("spec_async_drafted_tokens", "spec_async_accepted_tokens",
                "spec_async_wasted_tokens", "spec_async_verify_steps"):
        assert ma[key] == mb[key], key


# ---------------------------------------------------------------------------
# scheduling contracts: hook ordering, mid-flight catch-up only
# ---------------------------------------------------------------------------


def test_spec_async_rejects_defer_sync(params):
    cfg = _cfg(True)
    cfg.defer_sync = True
    cfg.num_pages = 4 * (96 // 16)   # fully backed, isolates the spec gate
    with pytest.raises(ValueError, match="spec_async"):
        ContinuousEngine(SPEC, params, cfg, seed=0)


def test_resolve_draft_layer_clamp(params):
    d_spec, _ = resolve_draft(SPEC, params, "layers:9")
    assert d_spec.n_layers == SPEC.n_layers - 1
    with pytest.raises(ValueError):
        resolve_draft(SPEC.replace(n_layers=1),
                      init_params(SPEC.replace(n_layers=1),
                                  jax.random.key(0)), "layers:1")


def test_pump_overlap_hook_runs_poll_before_draft():
    """Ordering regression pin: inside the pump's overlap hook the stream
    ring drains BEFORE the drafter schedules — computed tokens beat
    predicted ones, and the poll commits state the draft catch-up reads."""
    from distributed_inference_engine_tpu.serving.pump import EnginePump

    calls = []

    class _Spec:
        def schedule(self):
            calls.append("draft")
            return 0

    class _Eng:
        config = EngineConfig()
        overlap_hook = None
        speculator = _Spec()

        def poll_stream(self):
            calls.append("poll")
            return 0

        def step(self):
            return 0

        def drain_finished(self):
            return []

    eng = _Eng()
    EnginePump(eng)
    assert eng.overlap_hook is not None
    eng.overlap_hook()
    assert calls == ["poll", "draft"]


def test_midflight_schedule_is_catchup_only(params):
    """Draft overrun can never delay the next dispatch because a
    mid-flight schedule() (called from the overlap hook while a chunk is
    in flight) only catches caches up — it must never create a pending
    proposal the verify path would have to wait on. Also checks the
    bubble split the budget reads stays well-formed."""
    eng = ContinuousEngine(SPEC, params, _cfg(True), seed=0)
    spec = eng.speculator
    seen = []
    orig = spec.schedule

    def wrapped():
        before = set(spec._pending)
        n = orig()
        seen.append((eng._inflight_chunks,
                     set(spec._pending) - before))
        return n

    spec.schedule = wrapped
    # stand in for the pump's overlap hook (no pump in this test): the
    # engine fires it right after dispatching each chunk, mid-flight
    eng.overlap_hook = wrapped
    streamed = []
    eng.submit(GenerationRequest(prompt=[3, 5, 7], max_new_tokens=24,
                                 temperature=0.0),
               on_tokens=streamed.extend)
    for _ in range(400):
        if eng.step() == 0 and not eng.n_waiting:
            break
    midflight = [s for s in seen if s[0] >= 1]
    assert midflight, "overlap hook never invoked the drafter"
    assert all(not new for _, new in midflight), \
        "mid-flight schedule() created a pending proposal"
    assert any(new for infl, new in seen if infl == 0), \
        "step-top schedule() never proposed"
    split = busy_gap_split(eng.timeline.events())
    assert split["n_events"] > 0 and split["busy_s"] > 0
    assert 0.0 <= split["bubble_frac"] <= 1.0
