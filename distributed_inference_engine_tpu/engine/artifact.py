"""Pre-fused serving artifacts: seconds-scale worker cold start.

Engine init pays for its weights three times — load/init, quantize, then
``ops.quant.prepare_params`` (kernel-mode resolve + qkv/gate-up fusion +
lm-head pad). On an 8B model that is minutes of wall clock, which voids
the control plane's failover story: a respawned worker is "replaced"
3.5 minutes later. An artifact freezes the *post*-prepare tree once, so
every subsequent boot is an Orbax restore plus a self-check instead of a
re-derivation.

Layout (one directory per model):

    <path>/spec.json       ModelSpec sidecar   (utils/checkpoint.py)
    <path>/params/         Orbax PyTree of the PREPARED tree — fused
                           payloads, padded lm head, QuantizedTensor
                           nodes bit-exact through the int4 round trip
    <path>/manifest.json   commit point, written LAST via atomic
                           tmp+rename (utils/files.py)

Crash consistency is the manifest-last protocol: ``save_artifact`` writes
params first and publishes the manifest only after everything else is on
disk, so a crash mid-save leaves a manifest-less directory that
``has_artifact`` treats as absent — a respawning worker can never trust a
half-written tree. Rewrites delete the old manifest *first* for the same
reason: a stale manifest must not vouch for params mid-replacement.

Trust, but verify (three layers, cheapest first):

1. **Feature hash** — sha256 of the deploy config's identity fields. A
   config drift (dtype flip, different quant bits, other checkpoint)
   raises ``ArtifactMismatchError`` before any bytes are read.
2. **Tree checksum** — sha256 over every leaf's path/dtype/shape/bytes.
   Truncated files, flipped bits, or an Orbax restore error raise
   ``ArtifactCorruptError``.
3. **Golden-token probe** — the manifest records a tiny greedy generation
   captured at save time; the engine re-runs it before admitting traffic.
   This is the end-to-end check the checksum cannot give (it exercises
   the actual compiled programs against the restored tree) and doubles as
   a bb=1 warmup. Mismatch ⇒ ``ArtifactCorruptError`` ⇒ the factory falls
   back to the slow path — wrong numerics are never served.
"""

from __future__ import annotations

import hashlib
import json
import logging
import pathlib
from typing import Any, Dict, List, Optional, Tuple

from ..utils import checkpoint
from ..utils.files import atomic_write_json

logger = logging.getLogger(__name__)

MANIFEST_FILE = "manifest.json"
ARTIFACT_VERSION = 1
# the probe prompt is arbitrary but FIXED: it must replay bit-identically
# at load time, and ids this small exist in every real vocabulary
GOLDEN_PROMPT: Tuple[int, ...] = (1, 2, 3, 5, 8, 13, 21)
GOLDEN_MAX_NEW = 8


class ArtifactError(RuntimeError):
    """Base for artifact load/validation failures (factory catches this
    to fall back to the slow path)."""


class ArtifactCorruptError(ArtifactError):
    """The artifact's bytes or numerics are wrong: unreadable manifest,
    checksum mismatch, failed Orbax restore, or golden-probe divergence."""


class ArtifactMismatchError(ArtifactError):
    """The artifact is internally consistent but was built for a
    different deploy config (feature hash differs)."""


# -------------------------------------------------------------- hashing

def feature_hash(cfg) -> str:
    """sha256 of the ``ModelConfig`` fields that change the prepared
    tree. Engine *runtime* knobs (buckets, page sizes, batcher limits)
    deliberately stay out: the same artifact serves any of them."""
    ident = {
        "architecture": cfg.architecture,
        "path": cfg.path or "",
        "dtype": cfg.dtype or "",
        "max_seq_len": int(cfg.max_seq_len),
        "quantized": bool(cfg.quantized),
        "weight_bits": int(cfg.metadata.get("weight_bits", 8)),
        "size": str(cfg.metadata.get("size", "")),
        "seed": int(cfg.metadata.get("seed", 0)),
    }
    blob = json.dumps(ident, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def tree_checksum(params: Any) -> str:
    """sha256 over every leaf's (path, dtype, shape, bytes), leaves
    sorted by path so the digest is traversal-order independent.
    QuantizedTensor nodes are registered pytrees — their q/s arrays (and
    therefore the int4 packing) are covered leaf-by-leaf."""
    import jax
    import numpy as np

    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    items = sorted(((jax.tree_util.keystr(path), leaf)
                    for path, leaf in leaves), key=lambda kv: kv[0])
    h = hashlib.sha256()
    for key, leaf in items:
        arr = np.asarray(leaf)
        h.update(key.encode("utf-8"))
        h.update(str(arr.dtype).encode("utf-8"))
        h.update(str(arr.shape).encode("utf-8"))
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def quant_summary(params: Any) -> Dict[str, int]:
    """``{"int4": n, "int8": m}`` count of QuantizedTensor nodes by bit
    width — recorded in the manifest so an operator can read what mode an
    artifact holds without restoring it."""
    from ..ops.quant import QuantizedTensor

    out: Dict[str, int] = {}

    def walk(node: Any) -> None:
        if isinstance(node, QuantizedTensor):
            key = f"int{node.bits}"
            out[key] = out.get(key, 0) + 1
        elif isinstance(node, dict):
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)

    walk(params)
    return out


# ------------------------------------------------------------- manifest

def _manifest_path(path: str) -> pathlib.Path:
    return pathlib.Path(path).absolute() / MANIFEST_FILE


def has_artifact(path: str) -> bool:
    """True iff ``path`` holds a COMMITTED artifact — the manifest is
    written last, so its presence is the commit point."""
    return _manifest_path(path).is_file()


def write_manifest(path: str, manifest: Dict[str, Any]) -> str:
    return atomic_write_json(str(_manifest_path(path)), manifest)


def load_manifest(path: str) -> Dict[str, Any]:
    p = _manifest_path(path)
    try:
        manifest = json.loads(p.read_text())
    except FileNotFoundError:
        raise ArtifactCorruptError(
            f"no artifact manifest at {p} (absent or uncommitted save)")
    except (OSError, ValueError) as e:
        raise ArtifactCorruptError(
            f"artifact manifest {p} unreadable ({e})") from e
    if not isinstance(manifest, dict):
        raise ArtifactCorruptError(f"artifact manifest {p} is not an object")
    version = manifest.get("version")
    if version != ARTIFACT_VERSION:
        raise ArtifactCorruptError(
            f"artifact manifest {p} has version {version!r}; this build "
            f"reads version {ARTIFACT_VERSION}")
    missing = [k for k in ("checksum", "feature_hash") if k not in manifest]
    if missing:
        raise ArtifactCorruptError(
            f"artifact manifest {p} is missing fields {missing}")
    return manifest


# ----------------------------------------------------------- save / load

def save_artifact(path: str, spec, params: Any, cfg=None,
                  buckets: Optional[Dict[str, List[int]]] = None,
                  engine=None,
                  golden_prompt: Optional[List[int]] = None,
                  golden_max_new: int = GOLDEN_MAX_NEW) -> str:
    """Persist a PREPARED param tree (+ spec sidecar + manifest).

    ``params`` must be the post-``prepare_params`` tree — that is the
    entire point of the artifact; loading skips preparation. ``engine``
    (optional) records a golden-token probe by running a tiny greedy
    generation NOW, at save time, on the very tree being persisted; a
    loader replays it before admitting traffic. Returns ``path``."""
    p = pathlib.Path(path).absolute()
    stale = p / MANIFEST_FILE
    if stale.exists():
        # rewrite: retract the commit point FIRST so the old manifest
        # cannot vouch for half-replaced params if we crash below
        stale.unlink()
    checkpoint.save_params(str(p), spec, params)
    manifest: Dict[str, Any] = {
        "version": ARTIFACT_VERSION,
        "feature_hash": feature_hash(cfg) if cfg is not None else "",
        "checksum": tree_checksum(params),
        "quant": quant_summary(params),
        "buckets": dict(buckets or {}),
        "golden": None,
    }
    if engine is not None:
        prompt = [int(t) for t in (golden_prompt or GOLDEN_PROMPT)]
        tokens = run_probe(engine, prompt, golden_max_new)
        manifest["golden"] = {"prompt": prompt,
                              "max_new_tokens": int(golden_max_new),
                              "tokens": tokens}
    write_manifest(str(p), manifest)
    logger.info("serving artifact committed at %s (quant=%s, golden=%s)",
                p, manifest["quant"] or "none",
                "yes" if manifest["golden"] else "no")
    return str(p)


def load_artifact(path: str, cfg=None,
                  template: Optional[Any] = None) -> Tuple[Any, Any, Dict]:
    """Restore ``(spec, params, manifest)`` from a committed artifact.

    Raises ``ArtifactMismatchError`` when ``cfg`` is given and its
    feature hash differs from the manifest's (cheap, before any restore),
    and ``ArtifactCorruptError`` for unreadable/truncated/bit-flipped
    params — any Orbax failure is wrapped, so callers need exactly one
    except clause to fall back to the slow path."""
    manifest = load_manifest(path)
    if cfg is not None and manifest["feature_hash"]:
        want = feature_hash(cfg)
        if want != manifest["feature_hash"]:
            raise ArtifactMismatchError(
                f"artifact {path} was built for a different config "
                f"(feature hash {manifest['feature_hash'][:12]}… != "
                f"{want[:12]}…) — refusing to serve it")
    try:
        spec = checkpoint.load_spec(path)
        params = checkpoint.load_params(path, template=template)
    except ArtifactError:
        raise
    except Exception as e:
        raise ArtifactCorruptError(
            f"artifact {path} failed to restore ({type(e).__name__}: "
            f"{e})") from e
    got = tree_checksum(params)
    if got != manifest["checksum"]:
        raise ArtifactCorruptError(
            f"artifact {path} checksum mismatch (manifest "
            f"{manifest['checksum'][:12]}…, restored {got[:12]}…) — "
            "params are corrupt")
    return spec, params, manifest


# ---------------------------------------------------------- golden probe

def run_probe(engine, prompt: List[int], max_new: int) -> List[int]:
    """One tiny greedy generation on ``engine``, returned as plain ints.
    Handles both engine interfaces: batch ``generate`` (static engine)
    and ``submit`` + ``run_until_idle`` (continuous)."""
    from .types import GenerationRequest

    req = GenerationRequest(prompt=[int(t) for t in prompt],
                            max_new_tokens=int(max_new),
                            temperature=0.0,
                            request_id="__artifact_probe__")
    if hasattr(engine, "generate"):
        result = engine.generate([req])[0]
        return [int(t) for t in result.tokens]
    rid = engine.submit(req)
    for r in engine.run_until_idle():
        if r.request_id == rid:
            return [int(t) for t in r.tokens]
    raise ArtifactCorruptError(
        "golden probe vanished: continuous engine never finished it")


def verify_golden(engine, manifest: Optional[Dict[str, Any]]) -> bool:
    """Replay the manifest's golden probe on ``engine``; True when it ran
    and matched, False when the manifest records none. Divergence raises
    ``ArtifactCorruptError`` — the caller must NOT admit traffic."""
    golden = (manifest or {}).get("golden")
    if not golden:
        return False
    want = [int(t) for t in golden["tokens"]]
    got = run_probe(engine, golden["prompt"], golden["max_new_tokens"])
    if got != want:
        raise ArtifactCorruptError(
            f"golden-token self-check FAILED: expected {want}, got {got} "
            "— artifact numerics are wrong, refusing to serve")
    return True
