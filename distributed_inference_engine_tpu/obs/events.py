"""Structured fleet event log (ISSUE 19 leg 1): a bounded per-process
ring of TYPED events covering the fleet control plane — admission
decisions, dispatch retries/failovers, drains, KV-fabric transfers,
model stage/swap, chaos fault injections, and breaker/respawn/crash-loop
transitions.

Schema discipline mirrors the metric catalog in ``collectors.py``: the
``EVENTS`` table below is the single source of truth (name → help), the
docs table in ``docs/observability.md`` is linted against it in BOTH
directions (``scripts/graftlint`` drift rule), and ``EventLog.emit``
rejects unknown types at the call site so a typo cannot mint an
undocumented event family at runtime.

Each record is ``{"seq", "type", "t_wall", "t_mono", "args"}``:

- ``seq``   — per-process monotone sequence number (never reset, so a
  ring wrap is visible as a gap at the front);
- ``t_wall`` — ``time.time()`` for human-readable cross-host anchoring;
- ``t_mono`` — ``time.perf_counter()``, the clock ``clocksync`` aligns
  across processes for the merged fleet trace;
- ``args``  — small JSON-safe payload (worker ids, request ids, counts).

Determinism: ``canonical_sequence()`` strips seq and both timestamps so
two same-seed chaos runs can assert identical event SEQUENCES even
though wall time differs.

No jax imports (package discipline — see ``obs/__init__``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

#: event type → help. The docs event catalog is linted against exactly
#: this mapping (scripts/graftlint drift rule ``drift-events-docs``).
EVENTS: Dict[str, str] = {
    # -- admission (coordinator gate + worker-side pump gate) --------------
    "admission.shed": "Request shed at coordinator admission "
                      "(fleet-level degradation gate)",
    "admission.accept": "Request admitted into an engine pump inbox",
    "admission.reject": "Request refused by a pump (inbox full / "
                        "overload shed)",
    # -- dispatch ----------------------------------------------------------
    "dispatch.retry": "Dispatch re-tried on another replica after a "
                      "transport failure or draining shed",
    "dispatch.failover": "Stream resumed on an alternate worker via "
                         "prefix replay",
    # -- drain -------------------------------------------------------------
    "drain.begin": "Graceful drain started (worker stops admitting)",
    "drain.done": "Drain completed (in-flight work quiesced)",
    # -- KV fabric ---------------------------------------------------------
    "fabric.export": "kv_export RPC produced a prefix wire",
    "fabric.import": "kv_import RPC landed pages in the host KV tier",
    # -- model lifecycle ---------------------------------------------------
    "model.stage": "Background model stage started on a worker",
    "model.swap": "Hot swap activated a staged model",
    # -- chaos -------------------------------------------------------------
    "fault.injected": "Seeded chaos fault fired in this process's "
                      "RPC plane",
    # -- breaker / supervisor transitions ----------------------------------
    "breaker.open": "LB circuit breaker opened for a worker",
    "breaker.half_open": "LB circuit breaker moved to half-open "
                         "(probation)",
    "breaker.close": "LB circuit breaker closed (worker healthy again)",
    "respawn.begin": "Supervisor detected a dead worker and began "
                     "respawning it",
    "respawn.done": "Supervisor respawn completed (worker re-admitted)",
    "crashloop.open": "Crash-loop breaker opened (worker given up on)",
    "upgrade.rollback": "Rolling upgrade rolled a worker back after a "
                        "failed golden probe",
    # -- SLO burn-rate engine ----------------------------------------------
    "slo.burn_on": "SLO burn-rate breach engaged (fast+slow windows "
                   "both burning)",
    "slo.burn_off": "SLO burn-rate breach cleared",
    # -- post-mortem -------------------------------------------------------
    "postmortem.bundle": "Crash post-mortem bundle written",
}


class EventLog:
    """Bounded, thread-safe ring of typed events for one process.

    ``proc`` names the owning process track in the merged fleet trace
    (e.g. ``"coordinator"`` or a worker id). Emission is cheap — one
    dict append under a lock — and never raises for ring pressure
    (drops are counted, the newest event always lands).
    """

    def __init__(self, proc: str, capacity: int = 2048) -> None:
        self.proc = str(proc)
        self.capacity = max(1, int(capacity))
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def emit(self, etype: str, **args: Any) -> None:
        """Append one typed event. Unknown types raise ``ValueError`` —
        the catalog above is the schema, enforced at the call site."""
        if etype not in EVENTS:
            raise ValueError(f"unknown event type {etype!r} (add it to "
                             "obs.events.EVENTS and the docs catalog)")
        rec = {
            "seq": 0,                    # patched under the lock below
            "type": etype,
            "t_wall": time.time(),
            "t_mono": time.perf_counter(),
            "args": args,
        }
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(rec)

    # -- export ------------------------------------------------------------

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def snapshot(self) -> Dict[str, Any]:
        """Stable wire/bundle form: the whole ring plus schema and drop
        accounting. This is what the ``events`` RPC verb returns and
        what post-mortem bundles persist."""
        with self._lock:
            return {
                "schema": SCHEMA_VERSION,
                "proc": self.proc,
                "seq": self._seq,
                "dropped": self._dropped,
                "events": [dict(e) for e in self._events],
            }

    def canonical_sequence(self) -> List[Tuple[str, Tuple]]:
        """Timestamp-free event sequence for same-seed determinism
        assertions: ``[(type, sorted(args.items())), ...]`` in emission
        order (seq order — stable within one process)."""
        with self._lock:
            return [
                (e["type"], tuple(sorted(
                    (k, _canon(v)) for k, v in e["args"].items())))
                for e in self._events
            ]

    def get_stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"events_emitted": self._seq,
                    "events_dropped": self._dropped,
                    "events_buffered": len(self._events)}


def _canon(v: Any) -> Any:
    """JSON-safe, hashable canonical form for determinism comparison
    (floats that encode durations are excluded upstream — args should
    carry ids and counts, not timings)."""
    if isinstance(v, (list, tuple)):
        return tuple(_canon(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _canon(x)) for k, x in v.items()))
    return v


def canonical_from_snapshot(snap: Dict[str, Any]) -> List[Tuple[str, Tuple]]:
    """``canonical_sequence`` over a serialized ``snapshot()`` (e.g. one
    collected over RPC or read back from a post-mortem bundle)."""
    out: List[Tuple[str, Tuple]] = []
    for e in snap.get("events", ()):
        args = e.get("args") or {}
        out.append((e["type"], tuple(sorted(
            (k, _canon(v)) for k, v in args.items()))))
    return out
