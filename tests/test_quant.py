"""Weight-only int8 quantization tests (ops/quant.py): the reference
stores a ``quantized`` flag it never reads
(``/root/reference/src/model_registry.py:55``); here it must actually
shrink weight bytes while keeping generations materially unchanged."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_engine_tpu.config import EngineConfig, ModelConfig
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import engine_from_config
from distributed_inference_engine_tpu.models.base import (
    forward_train,
    init_params,
)
from distributed_inference_engine_tpu.models.llama import (
    llama_spec,
    mixtral_spec,
)
from distributed_inference_engine_tpu.ops.quant import (
    QuantizedTensor,
    matmul_any,
    param_bytes,
    quantize_params,
    quantize_weight,
)

SPEC = llama_spec("llama-tiny", max_seq_len=64, dtype="float32")


def test_quantize_weight_roundtrip_error_bounded():
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(64, 32).astype("float32"))
    qt = quantize_weight(w, (0,))
    assert qt.q.dtype == jnp.int8
    assert qt.s.shape == (1, 32)
    err = np.abs(np.asarray(qt.dequantize()) - np.asarray(w))
    # per-channel max error <= scale/2 (round-to-nearest)
    assert (err <= np.asarray(qt.s) / 2 + 1e-7).all()


def test_matmul_any_matches_dequantized_einsum():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 7, 64).astype("float32"))
    w = jnp.asarray(rs.randn(64, 32).astype("float32"))
    qt = quantize_weight(w, (0,))
    got = matmul_any("btd,de->bte", x, qt)
    want = jnp.einsum("btd,de->bte", x, qt.dequantize())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_quantized_params_shrink_and_logits_agree():
    params = init_params(SPEC, jax.random.key(0))
    qparams = quantize_params(SPEC, params)
    # the big matmul weights got int8 payloads
    assert isinstance(qparams["blocks"]["wq"], QuantizedTensor)
    assert isinstance(qparams["blocks"]["w_down"], QuantizedTensor)
    assert param_bytes(qparams) < 0.45 * param_bytes(params)

    rs = np.random.RandomState(2)
    toks = jnp.asarray(rs.randint(0, SPEC.vocab_size, (2, 12)), jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)
    full = np.asarray(forward_train(SPEC, params, toks, lens))
    quant = np.asarray(forward_train(SPEC, qparams, toks, lens))
    assert np.isfinite(quant).all()
    # top-1 agreement across positions: int8 weight-only should rarely
    # flip the argmax of a random-init model's logits
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.9, f"top-1 agreement {agree:.2f}"
    # logits stay close in relative terms
    denom = np.abs(full).max()
    assert np.abs(full - quant).max() / denom < 0.1


def test_quantized_engine_generates_like_full():
    params = init_params(SPEC, jax.random.key(0))
    cfg = EngineConfig(max_slots=2, max_seq_len=64)
    full_eng = Engine(SPEC, params=params, config=cfg)
    q_eng = Engine(SPEC, params=quantize_params(SPEC, params), config=cfg)
    reqs = [GenerationRequest(prompt=[5, 6, 7, 8], max_new_tokens=8,
                              temperature=0.0)]
    full_out = full_eng.generate([GenerationRequest(
        prompt=[5, 6, 7, 8], max_new_tokens=8, temperature=0.0)])[0].tokens
    q_out = q_eng.generate(reqs)[0].tokens
    assert len(q_out) == 8
    assert all(0 <= t < SPEC.vocab_size for t in q_out)
    # greedy chains can diverge after a flip, but the first token — a pure
    # function of the prefill logits — should match on a random-init model
    assert q_out[0] == full_out[0]


def test_engine_from_config_quantized_flag():
    cfg = ModelConfig(
        name="q", architecture="llama", dtype="float32", quantized=True,
        max_seq_len=64, max_batch_size=2, metadata={"size": "llama-tiny"},
    )
    eng = engine_from_config(cfg)
    assert isinstance(eng.params["blocks"]["wq"], QuantizedTensor)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=4)])
    assert len(out[0].tokens) == 4


def test_quantized_moe_exact_path_runs():
    spec = mixtral_spec(
        "mixtral-tiny", dtype="float32", max_seq_len=64,
        n_layers=2, d_model=64, n_heads=8, n_kv_heads=4, d_ff=96,
        vocab_size=128, n_experts=4, experts_per_token=2,
    )
    params = init_params(spec, jax.random.key(3))
    qparams = quantize_params(spec, params)
    assert isinstance(qparams["blocks"]["w_up"], QuantizedTensor)
    assert qparams["blocks"]["w_up"].s.shape == (2, 4, 1, 96)
    # router stays full precision (tiny + precision-sensitive)
    assert not isinstance(qparams["blocks"]["w_router"], QuantizedTensor)

    rs = np.random.RandomState(4)
    toks = jnp.asarray(rs.randint(0, spec.vocab_size, (1, 8)), jnp.int32)
    lens = jnp.full((1,), 8, jnp.int32)
    full = np.asarray(forward_train(spec, params, toks, lens))
    quant = np.asarray(forward_train(spec, qparams, toks, lens))
    assert np.isfinite(quant).all()
    agree = (full.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.8, f"top-1 agreement {agree:.2f}"


def test_quantized_speculative_composes():
    """int8 target + full-precision draft through the config path: the
    registry's quantized flag and speculative metadata must compose (the
    target's QuantizedTensor tree flows through forward_window via
    matmul_any)."""
    cfg = ModelConfig(
        name="qs", architecture="llama", dtype="float32", quantized=True,
        max_seq_len=64, max_batch_size=2,
        metadata={"size": "llama-tiny", "speculative": 2,
                  "draft_size": "llama-tiny"},
    )
    eng = engine_from_config(cfg)
    assert isinstance(eng.params["blocks"]["wq"], QuantizedTensor)
    out = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                          max_new_tokens=6)])[0]
    assert len(out.tokens) == 6
