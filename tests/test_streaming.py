"""Streaming + profiler tests: token chunks ride the framed connection
ahead of the final result (multi-frame responses, ``utils/rpc.py``
``_stream_methods``/``call_stream``), end-to-end through worker and
coordinator; ``profile`` wraps jax.profiler trace capture (SURVEY.md §5
tracing plan)."""

import asyncio
import os

import pytest

from distributed_inference_engine_tpu.api import (
    Coordinator,
    CoordinatorClient,
    CoordinatorConfig,
    CoordinatorServer,
)
from distributed_inference_engine_tpu.config import (
    EngineConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.cluster.worker import (
    WorkerClient,
    WorkerRPCError,
    WorkerServer,
)
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.llama import llama_spec

SPEC = llama_spec("llama-tiny", max_seq_len=64)

pytestmark = pytest.mark.streaming


def _model_cfg(name="m", continuous=True):
    meta = {"size": "llama-tiny", "page_size": 16, "num_pages": 64,
            "attention_impl": "xla", "kv_dtype": "float32",
            "decode_steps_per_call": 3}
    if continuous:
        meta["continuous"] = 1
    return ModelConfig(name=name, architecture="llama", dtype="float32",
                       max_seq_len=64, max_batch_size=4, metadata=meta)


# -------------------------------------------------------------- engine level


def test_engine_stream_callback_matches_result():
    eng = ContinuousEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=64, page_size=16, num_pages=32,
        decode_steps_per_call=3, attention_impl="xla"))
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=10,
                                 temperature=0.0, request_id="s"),
               on_tokens=chunks.append)
    res = eng.run_until_idle()[0]
    streamed = [t for c in chunks for t in c]
    assert streamed == res.tokens
    assert len(chunks) >= 2                     # actually incremental


def test_engine_stream_respects_eos_trim():
    eng = ContinuousEngine(SPEC, config=EngineConfig(
        max_slots=2, max_seq_len=64, page_size=16, num_pages=32,
        decode_steps_per_call=4, attention_impl="xla"))
    probe = eng.generate([GenerationRequest(prompt=[1, 2, 3],
                                            max_new_tokens=10,
                                            temperature=0.0)])[0].tokens
    eos = probe[3]
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=10,
                                 temperature=0.0, eos_id=eos),
               on_tokens=chunks.append)
    res = eng.run_until_idle()[0]
    streamed = [t for c in chunks for t in c]
    assert streamed == res.tokens               # no post-EOS leakage
    assert res.finish_reason == "stop"


# -------------------------------------------------------------- worker level


@pytest.mark.asyncio
async def test_worker_generate_stream_roundtrip():
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg())
        c = WorkerClient(*w.address, timeout=120.0)
        chunks = []
        req = GenerationRequest(prompt=[4, 5, 6], max_new_tokens=9,
                                temperature=0.0, request_id="r")
        res = await c.generate_stream("m", req, chunks.append)
        assert [t for ch in chunks for t in ch] == res.tokens
        assert len(res.tokens) == 9
        assert len(chunks) >= 2
        # matches non-streaming output
        plain = await c.generate("m", [GenerationRequest(
            prompt=[4, 5, 6], max_new_tokens=9, temperature=0.0)])
        assert plain[0].tokens == res.tokens
        await c.close()
    finally:
        await w.stop()


@pytest.mark.asyncio
async def test_worker_stream_on_static_engine_is_informative():
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg(continuous=False))
        c = WorkerClient(*w.address, timeout=120.0)
        with pytest.raises(WorkerRPCError, match="continuous"):
            await c.generate_stream(
                "m", GenerationRequest(prompt=[1], max_new_tokens=2),
                lambda t: None)
        # server keeps serving afterwards
        assert (await c.ping())["worker_id"] == "w"
        await c.close()
    finally:
        await w.stop()


# --------------------------------------------------------- coordinator level


@pytest.mark.asyncio
async def test_coordinator_stream_end_to_end():
    coord = Coordinator(CoordinatorConfig())
    server = CoordinatorServer(coord, ServerConfig(port=0))
    await server.start()
    workers = []
    try:
        w = WorkerServer(ServerConfig(worker_id="w0", port=0))
        host, port = await w.start()
        workers.append(w)
        coord.add_worker("w0", host, port)
        await coord.deploy_model(_model_cfg())

        chost, cport = server.address
        client = CoordinatorClient(chost, cport)
        chunks = []
        out = await client.generate_stream(
            "m", chunks.append, prompt=[7, 8, 9], max_new_tokens=8)
        assert [t for c in chunks for t in c] == out["tokens"]
        assert out["streamed"] is True
        assert out["metadata"]["worker_id"] == "w0"
        # plain path still works on the same connection
        plain = await client.generate("m", prompt=[7, 8, 9],
                                      max_new_tokens=8)
        assert plain["tokens"] == out["tokens"]
        await client.close()
    finally:
        await server.stop()
        for w in workers:
            await w.stop()


# ------------------------------------------------------------------ profiler


@pytest.mark.asyncio
async def test_profile_start_stop_cycle(tmp_path):
    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        c = WorkerClient(*w.address, timeout=60.0)
        trace_dir = str(tmp_path / "trace")
        out = await c.call("profile", action="start", trace_dir=trace_dir)
        assert out["profiling"] is True
        with pytest.raises(WorkerRPCError, match="already active"):
            await c.call("profile", action="start")
        # do some work under the trace
        await w.load_model_async(_model_cfg())
        await c.generate("m", [GenerationRequest(prompt=[1, 2],
                                                 max_new_tokens=2)])
        out = await c.call("profile", action="stop")
        assert out["trace_dir"] == trace_dir
        assert os.path.isdir(trace_dir)
        with pytest.raises(WorkerRPCError, match="not active"):
            await c.call("profile", action="stop")
        await c.close()
    finally:
        await w.stop()


@pytest.mark.asyncio
async def test_coordinator_stream_fails_over_before_first_chunk():
    """A dead worker at dispatch time must not fail the stream — the
    coordinator retries on an alternate as long as nothing has streamed
    (review finding: streaming lacked the non-streaming path's failover)."""
    coord = Coordinator(CoordinatorConfig())
    await coord.start()
    workers = []
    try:
        for i in range(2):
            w = WorkerServer(ServerConfig(worker_id=f"w{i}", port=0))
            host, port = await w.start()
            workers.append(w)
            coord.add_worker(f"w{i}", host, port)
        await coord.deploy_model(_model_cfg())
        await workers[0].stop()          # kill one replica

        seen = []
        for i in range(3):
            out = await coord.submit_stream(
                "m", prompt=[5, 6, 7 + i], max_new_tokens=4,
                on_tokens=lambda t: seen.extend(t), key=f"k{i}")
            assert len(out["tokens"]) == 4
            assert out["metadata"]["worker_id"] == "w1"
        assert len(seen) == 12
    finally:
        await coord.stop()
        await workers[1].stop()


# ------------------------------------------- sub-chunk streaming (ISSUE 13)


def _ecfg(**over):
    kw = dict(max_slots=2, max_seq_len=64, page_size=16, num_pages=32,
              decode_steps_per_call=4, attention_impl="xla")
    kw.update(over)
    return EngineConfig(**kw)


def test_token_ring_roundtrip_bit_exact():
    """defer_sync path: each chunk's emitted rows ride the device->host
    ring and are harvested by poll_stream inside the host bubble; the
    streamed concatenation must equal the packed-harvest result exactly."""
    eng = ContinuousEngine(SPEC, config=_ecfg(defer_sync=True))
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=12,
                                 temperature=0.0, request_id="ring"),
               on_tokens=chunks.append)
    results = []
    for _ in range(10000):
        live = eng.step()
        eng.poll_stream()               # the pump's host-bubble poll
        results.extend(eng.drain_finished())
        if live == 0 and not eng.n_waiting:
            break
    assert results and results[0].tokens
    streamed = [t for c in chunks for t in c]
    assert streamed == results[0].tokens        # bit-exact ring copy
    m = eng.get_metrics()
    assert m["stream_ring_pushes"] >= 1
    assert m["stream_ring_polls"] >= 1


def test_subchunk_greedy_parity_with_packed_harvest():
    """Greedy decode is chunking-invariant: 1-step sub-chunks must yield
    token-for-token the same output as the full 4-step megastep, and the
    streamed frames must splice to exactly that."""

    def run(scs, stream):
        eng = ContinuousEngine(SPEC, config=_ecfg(stream_chunk_steps=scs))
        chunks = []
        eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=14,
                                     temperature=0.0, request_id="g"),
                   on_tokens=chunks.append if stream else None)
        res = eng.run_until_idle()[0]
        return res.tokens, [t for c in chunks for t in c]

    ref, _ = run(0, stream=False)           # packed-harvest batch path
    sub, streamed = run(1, stream=True)     # 1-step sub-chunks
    assert sub == ref
    assert streamed == sub


def test_subchunk_stream_trims_stops_identically():
    """A stop hit inside a sub-chunk must trim the stream exactly like the
    packed path: stop token included, nothing after it leaks (greedy and
    sampled-with-min_p=1.0, which pins sampling to the argmax)."""
    probe = ContinuousEngine(SPEC, config=_ecfg()).generate(
        [GenerationRequest(prompt=[1, 2, 3], max_new_tokens=12,
                           temperature=0.0)])[0].tokens
    stop = probe[5]
    cut = probe.index(stop) + 1             # first occurrence, inclusive
    for temp, min_p in ((0.0, 0.0), (0.8, 1.0)):
        eng = ContinuousEngine(SPEC, config=_ecfg(stream_chunk_steps=1),
                               seed=0)
        chunks = []
        eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=12,
                                     temperature=temp, min_p=min_p,
                                     stop_ids=[stop]),
                   on_tokens=chunks.append)
        res = eng.run_until_idle()[0]
        assert res.tokens == probe[:cut]
        assert res.finish_reason == "stop"
        streamed = [t for c in chunks for t in c]
        assert streamed == res.tokens       # no post-stop leakage


def test_adaptive_chunk_compile_count_guard():
    """The streaming clamp is pow2-bucketed: a mixed streaming+batch run
    adds at most ONE new decode chunk length beyond the configured
    megastep, and pure-batch slots keep the full chunk."""
    eng = ContinuousEngine(SPEC, config=_ecfg(max_slots=4,
                                              stream_chunk_steps=1))
    # pure-batch wave first: full 4-step decode program only
    eng.generate([GenerationRequest(prompt=[1, 2], max_new_tokens=8,
                                    temperature=0.0)])
    batch_steps = {p[1] for p in eng._tl_programs if p[0] == "decode"}
    assert batch_steps == {4}
    assert eng.get_metrics()["stream_clamped_chunks"] == 0
    # streaming + batch mix: clamp engages, ONE extra length appears
    chunks = []
    eng.submit(GenerationRequest(prompt=[1, 2, 3], max_new_tokens=8,
                                 temperature=0.0), on_tokens=chunks.append)
    eng.submit(GenerationRequest(prompt=[4, 5], max_new_tokens=8,
                                 temperature=0.0))
    eng.run_until_idle()
    decode_steps = {p[1] for p in eng._tl_programs if p[0] == "decode"}
    assert decode_steps == {4, 1}, \
        "clamp must add exactly one pow2 decode length"
    assert eng.get_metrics()["stream_clamped_chunks"] >= 1
    assert [t for c in chunks for t in c]


def test_firsts_snapshot_one_fetch_per_rescue_wave():
    """Regression for the hoisted per-slot ascontiguousarray recompute: a
    whole retire wave shares at most ONE deferred-firsts readback, and a
    cache hit costs zero host reads."""
    eng = ContinuousEngine(SPEC, config=_ecfg(max_slots=4, defer_sync=True))
    reqs = [GenerationRequest(prompt=[1 + i, 2, 3], max_new_tokens=6,
                              temperature=0.0) for i in range(3)]
    res = eng.generate(reqs)
    assert all(len(r.tokens) == 6 for r in res)
    # direct probe: one invalidation, two lookups, ONE fetch
    eng._firsts_host = None
    base = eng._firsts_fetches
    a = eng._firsts_snapshot()
    b = eng._firsts_snapshot()
    assert a is b
    assert eng._firsts_fetches == base + 1
    assert eng.get_metrics()["firsts_fetches"] == eng._firsts_fetches


@pytest.mark.asyncio
async def test_midstream_kill_resumes_subchunk_through_fabric():
    """Sub-chunk frames + mid-stream kill: the resume must replay from the
    ring's high-water mark — token-exact, no duplicate or missing frame —
    through the prefix-affinity/KV-fabric path, and the coordinator ITL
    histogram must have observed the sub-chunk gaps."""
    from distributed_inference_engine_tpu.models.fake import _chain

    def expected(prompt, n, vocab=997):
        st = 0
        for t in prompt:
            st = _chain(st, t)
        out = []
        for _ in range(n):
            nxt = st % vocab
            st = _chain(st, nxt)
            out.append(nxt)
        return out

    coord = Coordinator(CoordinatorConfig(
        lb_strategy="prefix_affinity", affinity_page_size=4,
        affinity_pages=2, retry_seed=7, retry_backoff_base_s=0.01,
        fabric_snapshot_delay_s=0.0))
    await coord.start()
    meta = {"continuous": 1, "max_slots": 4, "prefix_cache": 1,
            "prefix_page_size": 4, "step_latency_s": 0.02,
            "tokens_per_step": 4, "stream_chunk_tokens": 1}
    cfg = ModelConfig(name="m", architecture="fake", metadata=meta)
    workers = {}
    try:
        for i in range(2):
            w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                          worker_id=f"w{i}"))
            host, port = await w.start()
            workers[f"w{i}"] = w
            coord.add_worker(f"w{i}", host, port)
        await coord.deploy_model(cfg)

        got, killed = [], []

        def on_tokens(toks):
            got.append(list(toks))
            if len(got) == 5 and not killed:
                for wid, w in workers.items():
                    if w._request_count:
                        killed.append(wid)
                        asyncio.ensure_future(w.stop())

        prompt = [5, 6, 7, 8]
        r = await coord.submit_stream("m", prompt=prompt, max_new_tokens=24,
                                      on_tokens=on_tokens)
        exp = expected(prompt, 24)
        flat = [t for c in got for t in c]
        assert killed, "the serving worker must have been killed mid-stream"
        assert flat == exp, "replay must start at the ring high-water mark"
        assert r["tokens"] == exp
        assert r["metadata"].get("stream_resumed")
        st = coord.get_stats()
        assert st["stream_resumes"] == 1
        assert st["stream_frames"] >= len(got)
        assert st["stream_itl"]["count"] >= 1
        assert st["stream_emit_lag"]
    finally:
        await coord.stop()
        for w in workers.values():
            try:
                await w.stop()
            except Exception:
                pass


@pytest.mark.asyncio
async def test_client_disconnect_mid_stream_keeps_server_alive():
    """A client hanging up mid-stream is routine (aborted generation) —
    the worker must log-and-continue, not die or count an engine error."""
    import asyncio as aio

    from distributed_inference_engine_tpu.utils.framing import (
        read_frame,
        write_frame,
    )

    w = WorkerServer(ServerConfig(worker_id="w", port=0))
    await w.start()
    try:
        await w.load_model_async(_model_cfg())
        host, port = w.address
        reader, writer = await aio.open_connection(host, port)
        await write_frame(writer, {
            "method": "generate_stream", "id": "x", "model": "m",
            "request": {"prompt": [1, 2, 3], "max_new_tokens": 40,
                        "temperature": 0.0},
        })
        # read one chunk frame, then slam the connection shut
        frame = await read_frame(reader)
        assert frame.get("stream") is True
        writer.close()
        # the server must still answer new connections and requests
        await aio.sleep(0.5)
        c = WorkerClient(host, port, timeout=120.0)
        out = await c.generate("m", [GenerationRequest(
            prompt=[1, 2], max_new_tokens=3)])
        assert len(out[0].tokens) == 3
        await c.close()
    finally:
        await w.stop()
