"""Model-family coverage beyond GPT-2/Llama/Mixtral: Qwen2 (qkv bias),
Mistral (sliding-window attention), Gemma (head_dim override, scaled
embeddings, +1 RMSNorm, GeGLU).

The reference has no real models at all (SURVEY.md §0 — its engine is an
``asyncio.sleep``), so families are capability extension; these tests hold
the new spec axes to the same parity standard as the original ones: every
variant must run the full static AND paged/continuous serving paths, and the
quirk flags must demonstrably change (or preserve) the math.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_engine_tpu.config import EngineConfig
from distributed_inference_engine_tpu.engine.engine import Engine
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models import (
    build_engine,
    gemma_spec,
    mistral_spec,
    qwen_spec,
    spec_for_architecture,
)
from distributed_inference_engine_tpu.models.base import (
    forward_train,
    init_params,
)
from distributed_inference_engine_tpu.models.loader import (
    load_checkpoint,
    spec_from_hf_config,
)

ECFG = dict(max_slots=2, max_seq_len=128, prefill_buckets=[32],
            decode_steps_per_call=8)


def _gen(engine, prompt=(1, 2, 3, 4, 5), n=12):
    return engine.generate(
        [GenerationRequest(prompt=list(prompt), max_new_tokens=n)])[0].tokens


def test_each_family_generates_greedy_deterministically():
    for fac, size in ((qwen_spec, "qwen-tiny"), (mistral_spec, "mistral-tiny"),
                      (gemma_spec, "gemma-tiny")):
        spec = fac(size, max_seq_len=128)
        a = _gen(Engine(spec, config=EngineConfig(**ECFG), seed=3))
        b = _gen(Engine(spec, config=EngineConfig(**ECFG), seed=3))
        assert a == b, f"{size}: greedy decode must be deterministic"
        assert len(a) == 12


def test_qwen_param_tree_has_qkv_bias_only():
    spec = qwen_spec("qwen-tiny")
    params = init_params(spec, jax.random.key(0))
    b = params["blocks"]
    assert {"bq", "bk", "bv"} <= set(b)
    assert "bo" not in b and "b_up" not in b and "b_down" not in b
    # bias actually reaches the math: nonzero bq must change logits
    toks = jnp.asarray([[1, 2, 3]], dtype=jnp.int32)
    lens = jnp.asarray([3], dtype=jnp.int32)
    base = forward_train(spec, params, toks, lens)
    params2 = jax.tree.map(lambda x: x, params)
    params2["blocks"]["bq"] = params2["blocks"]["bq"] + 1.0
    moved = forward_train(spec, params2, toks, lens)
    assert float(jnp.abs(base - moved).max()) > 1e-4


def test_gemma_head_dim_override_and_quirks():
    spec = gemma_spec("gemma-tiny")
    assert spec.head_dim == 32 and spec.d_model // spec.n_heads == 64
    params = init_params(spec, jax.random.key(0))
    assert params["blocks"]["wq"].shape == (4, 256, 4 * 32)
    assert "lm_head" not in params          # tied embeddings
    toks = jnp.asarray([[5, 6, 7, 8]], dtype=jnp.int32)
    lens = jnp.asarray([4], dtype=jnp.int32)
    logits = forward_train(spec, params, toks, lens)
    assert np.isfinite(np.asarray(logits)).all()
    # emb_scale must change the function
    plain = spec.replace(emb_scale=False)
    assert float(jnp.abs(
        forward_train(plain, params, toks, lens) - logits).max()) > 1e-4
    # norm_plus_one: with stored weights at 0, (1 + 0) == plain weights at 1
    z = jax.tree.map(lambda x: x, params)
    z["lnf_scale"] = jnp.zeros_like(z["lnf_scale"])
    z["blocks"]["ln1_scale"] = jnp.zeros_like(z["blocks"]["ln1_scale"])
    z["blocks"]["ln2_scale"] = jnp.zeros_like(z["blocks"]["ln2_scale"])
    o = jax.tree.map(lambda x: x, params)
    o["lnf_scale"] = jnp.ones_like(o["lnf_scale"])
    o["blocks"]["ln1_scale"] = jnp.ones_like(o["blocks"]["ln1_scale"])
    o["blocks"]["ln2_scale"] = jnp.ones_like(o["blocks"]["ln2_scale"])
    np.testing.assert_allclose(
        np.asarray(forward_train(spec, z, toks, lens)),
        np.asarray(forward_train(spec.replace(norm_plus_one=False), o,
                                 toks, lens)),
        rtol=2e-2, atol=2e-2,   # bf16 params
    )


def test_logit_softcap_bounds_logits():
    spec = gemma_spec("gemma-tiny", logit_softcap=5.0, dtype="float32")
    params = init_params(spec, jax.random.key(1))
    toks = jnp.asarray([[1, 2, 3]], dtype=jnp.int32)
    logits = forward_train(spec, params, toks, jnp.asarray([3]))
    assert float(jnp.abs(logits).max()) <= 5.0


def test_sliding_window_wide_window_matches_full():
    base = mistral_spec("mistral-tiny", max_seq_len=128, sliding_window=0,
                        dtype="float32")
    wide = base.replace(sliding_window=128)
    params = init_params(base, jax.random.key(2))
    rs = np.random.RandomState(0)
    toks = jnp.asarray(rs.randint(1, 1000, (2, 48)), dtype=jnp.int32)
    lens = jnp.asarray([48, 30], dtype=jnp.int32)
    np.testing.assert_allclose(
        np.asarray(forward_train(wide, params, toks, lens)),
        np.asarray(forward_train(base, params, toks, lens)),
        rtol=1e-5, atol=1e-5,
    )
    # a real window must change late positions (they lose early context)
    narrow = base.replace(sliding_window=8)
    diff = np.abs(np.asarray(forward_train(narrow, params, toks, lens))
                  - np.asarray(forward_train(base, params, toks, lens)))
    assert diff[0, -1].max() > 1e-3         # beyond the window: differs
    np.testing.assert_allclose(diff[0, :8], 0.0, atol=1e-6)  # inside: identical


def test_sliding_window_decode_matches_prefill_logits():
    """The decode path (cached_attention + window) must continue exactly the
    chain prefill (causal_attention + window) predicts: greedy generation
    re-scored by a full windowed forward reproduces the same argmaxes past
    the window boundary."""
    spec = mistral_spec("mistral-tiny", max_seq_len=128, sliding_window=16,
                        dtype="float32")
    eng = Engine(spec, config=EngineConfig(**ECFG), seed=0)
    prompt = list(range(1, 33))             # prompt 32 > window 16
    out = eng.generate([GenerationRequest(prompt=prompt, max_new_tokens=8)])[0]
    full = prompt + out.tokens
    logits = forward_train(spec, eng.params,
                           jnp.asarray([full], dtype=jnp.int32),
                           jnp.asarray([len(full)], dtype=jnp.int32))
    rescored = np.asarray(jnp.argmax(logits[0], axis=-1))
    for i, tok in enumerate(out.tokens):
        assert tok == int(rescored[len(prompt) - 1 + i]), f"step {i}"


def test_sliding_window_continuous_engine_matches_static():
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )

    spec = mistral_spec("mistral-tiny", max_seq_len=128, dtype="float32")
    assert spec.sliding_window == 64
    # prefill bucket must hold the whole 80-token prompt (the engines clamp
    # overlong prompts to the largest bucket, which would mask the window)
    cfg_s = EngineConfig(**{**ECFG, "prefill_buckets": [96]})
    cfg_c = EngineConfig(**{**ECFG, "prefill_buckets": [96],
                            "page_size": 16, "num_pages": 24})
    prompt = list(range(1, 81))             # 80 tokens: exceeds the window
    static = Engine(spec, config=cfg_s, seed=0)
    cont = ContinuousEngine(spec, params=static.params, config=cfg_c)
    a = static.generate([GenerationRequest(prompt=prompt, max_new_tokens=10)])[0]
    b = cont.generate([GenerationRequest(prompt=prompt, max_new_tokens=10)])[0]
    assert a.tokens == b.tokens


def test_hf_config_and_checkpoint_roundtrip_qwen(tmp_path):
    from safetensors.numpy import save_file

    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "qwen2", "architectures": ["Qwen2ForCausalLM"],
        "vocab_size": 64, "hidden_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 2,
        "intermediate_size": 24, "max_position_embeddings": 64,
        "rope_theta": 1e6, "rms_norm_eps": 1e-6,
        "tie_word_embeddings": False,
    }))
    spec = spec_from_hf_config(str(tmp_path)).replace(dtype="float32")
    assert spec.qkv_bias and not spec.use_bias

    rs = np.random.RandomState(1)
    D, F, V = spec.d_model, spec.d_ff, spec.vocab_size
    Hd, Kd = spec.n_heads * spec.head_dim, spec.n_kv_heads * spec.head_dim
    raw = {
        "model.embed_tokens.weight": rs.randn(V, D).astype(np.float32),
        "model.norm.weight": np.ones(D, dtype=np.float32),
        "lm_head.weight": rs.randn(V, D).astype(np.float32),
    }
    for l in range(2):
        raw[f"model.layers.{l}.input_layernorm.weight"] = np.ones(D, np.float32)
        raw[f"model.layers.{l}.post_attention_layernorm.weight"] = np.ones(D, np.float32)
        raw[f"model.layers.{l}.self_attn.q_proj.weight"] = rs.randn(Hd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.q_proj.bias"] = rs.randn(Hd).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.k_proj.weight"] = rs.randn(Kd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.k_proj.bias"] = rs.randn(Kd).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.v_proj.weight"] = rs.randn(Kd, D).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.v_proj.bias"] = rs.randn(Kd).astype(np.float32)
        raw[f"model.layers.{l}.self_attn.o_proj.weight"] = rs.randn(D, Hd).astype(np.float32)
        raw[f"model.layers.{l}.mlp.gate_proj.weight"] = rs.randn(F, D).astype(np.float32)
        raw[f"model.layers.{l}.mlp.up_proj.weight"] = rs.randn(F, D).astype(np.float32)
        raw[f"model.layers.{l}.mlp.down_proj.weight"] = rs.randn(D, F).astype(np.float32)
    save_file(raw, str(tmp_path / "model.safetensors"))

    params = load_checkpoint(str(tmp_path), spec)
    np.testing.assert_allclose(
        np.asarray(params["blocks"]["bq"][1]),
        raw["model.layers.1.self_attn.q_proj.bias"], rtol=1e-6)
    logits = forward_train(spec, params, jnp.asarray([[1, 2, 3]]),
                           jnp.asarray([3]))
    assert np.isfinite(np.asarray(logits)).all()


def test_hf_config_mistral_and_gemma(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "mistral", "architectures": ["MistralForCausalLM"],
        "vocab_size": 32000, "hidden_size": 4096, "num_hidden_layers": 32,
        "num_attention_heads": 32, "num_key_value_heads": 8,
        "intermediate_size": 14336, "sliding_window": 4096,
        "rope_theta": 10000.0,
    }))
    spec = spec_from_hf_config(str(tmp_path))
    assert spec.sliding_window == 4096 and not spec.qkv_bias

    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "mistral", "architectures": ["MistralForCausalLM"],
        "vocab_size": 32768, "hidden_size": 4096, "num_hidden_layers": 32,
        "num_attention_heads": 32, "num_key_value_heads": 8,
        "intermediate_size": 14336, "sliding_window": None,
    }))
    assert spec_from_hf_config(str(tmp_path)).sliding_window == 0

    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "gemma", "architectures": ["GemmaForCausalLM"],
        "vocab_size": 256000, "hidden_size": 3072, "num_hidden_layers": 28,
        "num_attention_heads": 16, "num_key_value_heads": 16,
        "intermediate_size": 24576, "head_dim": 256,
        "max_position_embeddings": 8192, "rms_norm_eps": 1e-6,
    }))
    spec = spec_from_hf_config(str(tmp_path))
    assert spec.head_dim == 256 and spec.emb_scale and spec.norm_plus_one
    assert spec.mlp == "geglu" and spec.tie_embeddings


def test_factory_dispatch_for_new_families():
    assert spec_for_architecture("qwen2-7b").qkv_bias
    assert spec_for_architecture("mistral-7b-v01").sliding_window == 4096
    assert spec_for_architecture("gemma-2b").n_kv_heads == 1
    assert spec_for_architecture("mixtral-tiny").n_experts == 4  # not shadowed
    eng = build_engine("qwen-tiny")
    assert eng.spec.qkv_bias
