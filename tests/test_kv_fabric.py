"""KV fabric tests (-m fabric; engine/kv_fabric.py + the kv_export /
kv_import RPC plane + the coordinator's migration triggers).

Correctness bar, same as the r7 host tier: an IMPORTED page must be
bit-identical to a locally-prefilled one (asserted across float32 /
bfloat16 / fp8 KV), every checksum must verify before anything is
stored (a rejected import inserts NOTHING and admission falls back to
normal prefill), and the coordinator must pre-warm BEFORE half-open so
a rejoining worker's trial probe lands on imported KV.
"""

import asyncio

import jax
import numpy as np
import pytest

from distributed_inference_engine_tpu.api.coordinator import (
    Coordinator,
    CoordinatorConfig,
)
from distributed_inference_engine_tpu.cluster.worker import WorkerServer
from distributed_inference_engine_tpu.config import (
    EngineConfig,
    HealthConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.engine.continuous import ContinuousEngine
from distributed_inference_engine_tpu.engine.kv_fabric import (
    FabricRejected,
    build_fake_wire,
    check_fake_wire,
    wire_nbytes,
)
from distributed_inference_engine_tpu.engine.kv_offload import HostKVOffload
from distributed_inference_engine_tpu.engine.types import GenerationRequest
from distributed_inference_engine_tpu.models.base import init_params
from distributed_inference_engine_tpu.models.fake import _chain
from distributed_inference_engine_tpu.models.llama import llama_spec
from distributed_inference_engine_tpu.utils.faults import (
    SERVER,
    FaultPlan,
    FaultSpec,
)

pytestmark = pytest.mark.fabric

SPEC = llama_spec("llama-tiny", max_seq_len=128)
PAGE = 8
SYS = list(range(1, 25))          # 24 tokens = 3 full pages
PROMPT = SYS + [30, 31]


def _cfg(kv_dtype="float32", **over):
    base = dict(max_slots=4, max_seq_len=128, page_size=PAGE,
                num_pages=16, decode_steps_per_call=4,
                attention_impl="xla", prefix_cache=True,
                kv_dtype=kv_dtype, kv_offload=True)
    base.update(over)
    return EngineConfig(**base)


@pytest.fixture(scope="module")
def params():
    return init_params(SPEC, jax.random.key(0))


def _req(rid="r", prompt=None, max_new=6):
    return GenerationRequest(prompt=list(prompt or PROMPT),
                             max_new_tokens=max_new, temperature=0.0,
                             request_id=rid)


# ------------------------------------------------------- wire unit tests


def test_fake_wire_roundtrip_and_rejects():
    w = build_fake_wire([1, 2, 3, 4], page_size=2)
    assert check_fake_wire(w, page_size=2) == [1, 2, 3, 4]
    assert wire_nbytes(w) == 4 * 8
    with pytest.raises(FabricRejected):
        check_fake_wire(w, page_size=4)          # geometry mismatch
    bad = dict(w)
    bad["tokens"] = [1, 2, 3, 5]                 # payload tampered
    with pytest.raises(FabricRejected):
        check_fake_wire(bad, page_size=2)
    misaligned = build_fake_wire([1, 2, 3], page_size=2)
    with pytest.raises(FabricRejected):
        check_fake_wire(misaligned, page_size=2)
    with pytest.raises(FabricRejected):
        check_fake_wire({"kind": "fake"}, page_size=2)


def test_host_store_stages_layerwise_chunks_bit_exact():
    """upload_layers_per_chunk=1 staging splits the page into per-layer
    device_put chunks; concatenated they are bit-identical to the host
    array, and consuming a staged entry accounts restage overlap."""
    store = HostKVOffload(max_bytes=1 << 20)
    k = np.arange(4 * PAGE * 16, dtype=np.float32).reshape(4, PAGE, 16)
    v = -k
    assert store.put(b"h", k, v)
    assert store.start_upload(b"h")
    got_k, got_v = store.get(b"h")
    assert isinstance(got_k, list) and len(got_k) == 4
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(c) for c in got_k], axis=0), k)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(c) for c in got_v], axis=0), v)
    assert store.get_stats()["restage_overlap_s"] > 0.0


# ------------------------------------------- paged export/import parity


@pytest.mark.parametrize("kv_dtype",
                         ["float32", "bfloat16", "float8_e4m3fn"])
def test_export_import_bit_parity_across_kv_dtypes(params, kv_dtype):
    """The tentpole invariant: pages exported from one engine and
    imported into another are bit-identical to locally-prefilled pages,
    and the importer admits them from its host tier (no recompute) with
    token-exact generation — for every KV dtype, including quantized."""
    a = ContinuousEngine(SPEC, params=params, config=_cfg(kv_dtype))
    want = a.generate([_req("a1")])[0].tokens
    wire_a = a.kv_export(PROMPT)
    assert wire_a is not None and len(wire_a["pages"]) == 3
    assert wire_a["dtype"] == kv_dtype

    # an independent engine prefilling the same prompt exports the SAME
    # bytes: imported == locally-prefilled, bit for bit
    c = ContinuousEngine(SPEC, params=params, config=_cfg(kv_dtype))
    c.generate([_req("c1")])
    wire_c = c.kv_export(PROMPT)
    assert [(p["hash"], p["k"], p["v"]) for p in wire_a["pages"]] == \
        [(p["hash"], p["k"], p["v"]) for p in wire_c["pages"]]

    b = ContinuousEngine(SPEC, params=params, config=_cfg(kv_dtype))
    assert b.kv_import(wire_a) == 3
    # the host tier holds exactly the wire's bytes
    for pg in wire_a["pages"]:
        k_arr, v_arr = b.kv.offload.peek(pg["hash"])
        assert k_arr.tobytes() == pg["k"] and v_arr.tobytes() == pg["v"]
    got = b.generate([_req("b1")])[0].tokens
    assert got == want
    host = b.get_metrics()["kv"]["host_tier"]
    assert host["host_hit_pages_admit"] == 3      # admitted, not recomputed
    # kv_import prefetched the chain: the host→device restage ran
    # overlapped (staged layer-wise at import, consumed at admission)
    assert host["restage_overlap_s"] > 0.0
    # a re-export from the importer round-trips the same bytes
    wire_b = b.kv_export(PROMPT)
    assert [(p["hash"], p["k"], p["v"]) for p in wire_b["pages"]] == \
        [(p["hash"], p["k"], p["v"]) for p in wire_a["pages"]]


def test_import_checksum_reject_stores_nothing(params):
    """A corrupted wire must be rejected as a whole — no partial pages in
    the host tier — and the importer still serves token-exact via the
    normal cold prefill fallback."""
    a = ContinuousEngine(SPEC, params=params, config=_cfg())
    want = a.generate([_req("a1")])[0].tokens
    wire = a.kv_export(PROMPT)

    def tampered(mutate):
        bad = {k: v for k, v in wire.items()}
        bad["pages"] = [dict(p) for p in wire["pages"]]
        mutate(bad)
        return bad

    flip = tampered(lambda w: w["pages"][1].update(
        k=b"\xff" + w["pages"][1]["k"][1:]))
    b = ContinuousEngine(SPEC, params=params, config=_cfg())
    with pytest.raises(FabricRejected):
        b.kv_import(flip)
    with pytest.raises(FabricRejected):          # manifest covers the set
        b.kv_import(tampered(lambda w: w["pages"].pop()))
    with pytest.raises(FabricRejected):          # geometry must match
        b.kv_import(tampered(lambda w: w.update(page_size=PAGE * 2)))
    # dtype mismatch: a bf16 wire never lands in a float32 pool
    bf = ContinuousEngine(SPEC, params=params, config=_cfg("bfloat16"))
    bf.generate([_req("bf1")])
    with pytest.raises(FabricRejected):
        b.kv_import(bf.kv_export(PROMPT))
    assert len(b.kv.offload) == 0                # nothing ever stored
    assert b.generate([_req("b1")])[0].tokens == want
    assert b.get_metrics()["kv"]["host_tier"]["host_hit_pages_admit"] == 0


# --------------------------------------------------- fleet-level (fake)

VOCAB = 997
PREFIX = [7, 7, 7, 7]            # one full affinity page (page_size=4)


def expected_tokens(prompt, n, vocab=VOCAB):
    st = 0
    for t in prompt:
        st = _chain(st, t)
    out = []
    for _ in range(n):
        nxt = st % vocab
        st = _chain(st, nxt)
        out.append(nxt)
    return out


async def start_fabric_fleet(n_workers, model_meta=None, fault_plan=None,
                             **coord_overrides):
    """Prefix-affinity fleet of continuous fakes WITH the fake prefix
    cache on, so kv_export/kv_import carry real (token) payloads."""
    kw = dict(lb_strategy="prefix_affinity", affinity_page_size=4,
              affinity_pages=2, retry_seed=7, retry_backoff_base_s=0.01,
              fabric_snapshot_delay_s=0.0)
    kw.update(coord_overrides)
    coord = Coordinator(CoordinatorConfig(**kw))
    await coord.start()
    meta = {"continuous": 1, "max_slots": 4, "prefix_cache": 1,
            "prefix_page_size": 4, "admit_latency_per_token_s": 1e-4}
    meta.update(model_meta or {})
    cfg = ModelConfig(name="m", architecture="fake", metadata=meta)
    workers = {}
    for i in range(n_workers):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=f"w{i}"))
        if fault_plan is not None:
            w.fault_plan = fault_plan
        host, port = await w.start()
        workers[f"w{i}"] = w
        coord.add_worker(f"w{i}", host, port)
    await coord.deploy_model(cfg, register_shards=False)
    return coord, workers, cfg


async def stop_fleet(coord, workers):
    await coord.stop()
    for w in workers.values():
        try:
            await w.stop()
        except Exception:
            pass


def _client(coord, wid):
    return (coord.router.client_for(wid)
            if wid in coord.router.workers else coord.lb.client_for(wid))


async def test_worker_rpc_export_import_and_reject_counters():
    """The RPC plane end to end: export off the warm worker, import into
    the cold one (metrics account bytes both ways), and a tampered wire
    comes back as a TYPED reject that counts a fallback and admits
    nothing — the importer's next admission pays normal prefill."""
    coord, workers, _ = await start_fabric_fleet(2)
    try:
        p = PREFIX + [50]
        r = await coord.submit("m", prompt=p, max_new_tokens=4,
                               no_cache=True)
        assert r["tokens"] == expected_tokens(p, 4)
        bound = next(iter(coord.lb._affinity.values()))
        other = next(w for w in workers if w != bound)

        wire = await _client(coord, bound).kv_export("m", p)
        assert wire is not None and wire["tokens"] == PREFIX
        res = await _client(coord, other).kv_import("m", wire)
        assert res["imported_pages"] == 1 and not res.get("rejected")

        bad = dict(wire)
        bad["tokens"] = [8, 8, 8, 8]             # checksum now stale
        res = await _client(coord, other).kv_import("m", bad)
        assert res["imported_pages"] == 0 and res.get("rejected")

        m_bound = await _client(coord, bound).metrics()
        m_other = await _client(coord, other).metrics()
        assert m_bound["kv_fabric_exports"] >= 1
        assert m_bound["kv_fabric_export_bytes"] >= wire_nbytes(wire)
        assert m_other["kv_fabric_imports"] == 1
        assert m_other["kv_fabric_import_bytes"] == wire_nbytes(wire)
        assert m_other["kv_fabric_import_fallbacks"] == 1
        # the good import made the prefix warm on the importer: traffic
        # pinned there admits the head for free (fake engine accounting)
        eng = m_other["models"]["m"]
        assert eng["fabric_imports"] == 1
        assert eng["fabric_imported_tokens"] == len(PREFIX)
    finally:
        await stop_fleet(coord, workers)


async def test_drain_hands_off_bindings_warm():
    """Graceful drain migrates the retiree's bound prefixes: target
    imports them BEFORE quarantine, bindings MOVE (handoffs, not
    rebind-drops), and follow-up traffic rides the warm copy."""
    coord, workers, _ = await start_fabric_fleet(3)
    try:
        for i in range(4):
            p = PREFIX + [100 + i]
            r = await coord.submit("m", prompt=p, max_new_tokens=4,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens(p, 4)
        bound = next(iter(coord.lb._affinity.values()))
        rebinds0 = coord.lb.get_all_stats()["affinity_rebinds"]

        summary = await coord.drain_worker(bound)
        hand = summary.get("kv_fabric_handoff")
        assert hand and hand["bindings_moved"] >= 1
        assert hand["prefixes_warmed"] >= 1
        target = hand["target"]
        assert target != bound
        lb = coord.lb.get_all_stats()
        assert lb["affinity_handoffs"] >= 1
        # moved, NOT dropped: quarantine found no bindings left to count
        assert lb["affinity_rebinds"] == rebinds0
        assert set(coord.lb._affinity.values()) == {target}

        for i in range(4, 8):
            p = PREFIX + [100 + i]
            r = await coord.submit("m", prompt=p, max_new_tokens=4,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens(p, 4)
        m = await _client(coord, target).metrics()
        eng = m["models"]["m"]
        assert eng["fabric_imports"] >= 1
        # the handoff import made the prefix warm BEFORE the first
        # follow-up request: every admission credited the shared head
        assert eng["prefix_cached_tokens"] >= 4 * len(PREFIX)
        assert coord.get_stats()["kv_fabric_prewarm_pushes"] >= 1
    finally:
        await stop_fleet(coord, workers)


async def test_respawn_prewarms_before_half_open():
    """The supervisor ordering contract: on respawn the coordinator
    pushes hot prefixes into the worker BEFORE enter_half_open, so the
    trial probe lands against imported KV."""
    coord, workers, cfg = await start_fabric_fleet(
        2, model_meta={"step_latency_s": 0.005},
        health=HealthConfig(check_interval=0.05, check_timeout=0.5,
                            max_consecutive_failures=2),
        supervisor_interval_s=0.05, supervisor_backoff_base_s=0.02,
        supervisor_backoff_max_s=0.1)
    events = []
    orig_prewarm = coord.prewarm_worker
    orig_half_open = coord.lb.enter_half_open

    async def wrapped_prewarm(wid, **kw):
        got = await orig_prewarm(wid, **kw)
        events.append(("prewarm", wid, got))
        return got

    def wrapped_half_open(wid):
        events.append(("half_open", wid, None))
        return orig_half_open(wid)

    coord.prewarm_worker = wrapped_prewarm
    coord.lb.enter_half_open = wrapped_half_open
    spawned = []

    async def restart_hook(worker_id, info):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=worker_id))
        host, port = await w.start()
        spawned.append(w)
        return host, port

    coord.start_supervisor(restart_hook)
    try:
        r = await coord.submit("m", prompt=PREFIX + [60], max_new_tokens=4,
                               no_cache=True)
        assert r["tokens"] == expected_tokens(PREFIX + [60], 4)
        bound = next(iter(coord.lb._affinity.values()))

        prompts = [PREFIX + [61 + i] for i in range(8)]
        tasks = [asyncio.ensure_future(
            coord.submit("m", prompt=p, max_new_tokens=6, no_cache=True))
            for p in prompts]
        await asyncio.sleep(0.05)
        await workers.pop(bound).stop()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        assert all(isinstance(r, dict)
                   and r["tokens"] == expected_tokens(p, 6)
                   for p, r in zip(prompts, results))
        for _ in range(100):
            if coord.get_stats()["supervisor_respawns"] >= 1:
                break
            await asyncio.sleep(0.05)
        assert coord.get_stats()["supervisor_respawns"] >= 1

        seq = [(kind, wid) for kind, wid, _ in events]
        assert ("prewarm", bound) in seq and ("half_open", bound) in seq
        assert seq.index(("prewarm", bound)) < \
            seq.index(("half_open", bound)), \
            f"prewarm must precede half-open: {seq}"
        # the pre-warm actually landed pages (survivor held the bindings)
        pushed = next(got for kind, wid, got in events
                      if (kind, wid) == ("prewarm", bound))
        assert pushed >= 1
        assert coord.get_stats()["kv_fabric_prewarm_pushes"] >= 1
    finally:
        await stop_fleet(coord, workers)
        for w in spawned:
            try:
                await w.stop()
            except Exception:
                pass


async def test_stream_failover_imports_cached_wire_token_exact():
    """Mid-stream kill of the bound worker: the resumed stream is
    token-exact AND the alternate imported the dead stream's KV pages
    from the coordinator's snapshot cache (binding handed off, not
    dropped cold)."""
    coord, workers, _ = await start_fabric_fleet(
        2, model_meta={"step_latency_s": 0.01})
    try:
        # bind the prefix + let the background snapshot land the wire
        r = await coord.submit("m", prompt=PREFIX + [41], max_new_tokens=4,
                               no_cache=True)
        assert r["tokens"] == expected_tokens(PREFIX + [41], 4)
        for _ in range(100):
            if coord._fabric_cache:
                break
            await asyncio.sleep(0.01)
        assert coord._fabric_cache, "snapshot pull never landed"
        bound = next(iter(coord.lb._affinity.values()))

        got, killed = [], []

        def on_tokens(toks):
            got.append(list(toks))
            if len(got) == 3 and not killed:
                killed.append(bound)
                asyncio.ensure_future(workers[bound].stop())

        prompt = PREFIX + [42]
        r = await coord.submit_stream("m", prompt=prompt,
                                      max_new_tokens=20,
                                      on_tokens=on_tokens)
        exp = expected_tokens(prompt, 20)
        assert killed and r["tokens"] == exp
        assert [t for c in got for t in c] == exp

        stats = coord.get_stats()
        assert stats["kv_fabric_failover_imports"] >= 1
        assert coord.lb.get_all_stats()["affinity_handoffs"] >= 1
        survivor = next(w for w in workers if w != bound)
        assert set(coord.lb._affinity.values()) == {survivor}
        m = await _client(coord, survivor).metrics()
        assert m["kv_fabric_imports"] >= 1
        assert m["models"]["m"]["fabric_imports"] >= 1
    finally:
        await stop_fleet(coord, workers)


async def test_garbled_import_falls_back_to_prefill():
    """Chaos thread-through: a garbled kv_import surfaces as a failed
    (never wrong) push — pre-warm counts failures, nothing is admitted
    on the target, and traffic stays token-exact via normal prefill."""
    plan = FaultPlan(seed=5, specs=[
        FaultSpec(kind="garble", rate=1.0, site=SERVER,
                  verbs=("kv_import",)),
    ])
    coord, workers, _ = await start_fabric_fleet(2, fault_plan=plan)
    try:
        r = await coord.submit("m", prompt=PREFIX + [70], max_new_tokens=4,
                               no_cache=True)
        assert r["tokens"] == expected_tokens(PREFIX + [70], 4)
        bound = next(iter(coord.lb._affinity.values()))
        other = next(w for w in workers if w != bound)

        pushed = await coord.prewarm_worker(other)
        assert pushed == 0
        stats = coord.get_stats()
        assert stats["kv_fabric_prewarm_pushes"] == 0
        assert stats["kv_fabric_prewarm_failures"] >= 1
        m = await _client(coord, other).metrics()
        assert m["models"]["m"]["fabric_imports"] == 0

        # the fleet still serves exactly — cold prefill fallback
        for i in range(4):
            p = PREFIX + [71 + i]
            r = await coord.submit("m", prompt=p, max_new_tokens=4,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens(p, 4)
        # export stays un-faulted: only the import verb was garbled
        wire = await _client(coord, bound).kv_export("m", PREFIX + [70])
        assert wire is not None and wire["tokens"] == PREFIX
    finally:
        await stop_fleet(coord, workers)
