"""distributed_inference_engine_tpu — a TPU-native distributed LLM serving framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
``Real-VeerSandhu/Distributed-Inference-Engine`` (reference mounted at
``/root/reference``): coordinator/worker serving with a model registry
(versions, shards, consistent-hash routing), a router with health checks and
deterministic failover, a strategy-based load balancer, a size/latency-triggered
request batcher, and a response cache with LRU/LFU/FIFO eviction — with the
reference's mock inference core (``src/mock_models/fake_model.py``) replaced by
a real XLA engine: jit-compiled prefill/decode over a ``jax.sharding.Mesh``,
an HBM-resident KV cache, and host-side asyncio orchestration.

Layer map (heir of SURVEY.md §1):

    api/        coordinator front-end + client        (the reference's missing coordinator.py)
    cluster/    registry, router, load balancer, RPC  (reference L1+L4: model_registry/router/load_balancer)
    serving/    batcher, response cache               (reference L3+L2: batcher.py, kvstore.py)
    engine/     jit prefill/decode, KV cache, sched   (replaces reference L2 mock_models/)
    models/     GPT-2 / Llama model families + fake   (no reference counterpart; BASELINE.json configs)
    ops/        attention, sampling, pallas kernels   (TPU compute path)
    parallel/   mesh, shardings, ring attention       (reference §2.3 parallelism, re-done as jax.sharding)
    utils/      framing, tracing, logging             (the README-promised utils.py, done properly)
"""

__version__ = "0.1.0"

from .config import (  # noqa: F401
    ModelConfig,
    MeshConfig,
    EngineConfig,
    BatcherConfig,
    CacheConfig,
    HealthConfig,
    load_config,
)
from .serving.cache import ResponseCache, KVStore, create_kv_store  # noqa: F401
