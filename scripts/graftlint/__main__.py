"""CLI: ``python -m scripts.graftlint [paths...]``.

Exit 0 iff every finding is suppressed (pragma or baseline). The
baseline is append-forbidden by default: new findings FAIL the run and
the only way to accept them wholesale is the loud ``--update-baseline``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

from .core import (BASELINE_DEFAULT, Baseline, all_rules, build_project,
                   format_json, format_text, run_rules, suppress,
                   unsuppressed)

DEFAULT_PATHS = ["distributed_inference_engine_tpu", "bench.py"]


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based hot-path / jit-stability / async-hygiene / "
                    "drift analyzer for the serving stack")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files or directories (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=None,
                    help="repo root for relpaths + drift rules "
                         "(default: cwd)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="baseline file (default: scripts/"
                         "graftlint_baseline.json); 'none' disables")
    ap.add_argument("--update-baseline", action="store_true",
                    help="REWRITE the baseline to accept every current "
                         "unsuppressed finding — loud, reviewed, deliberate")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid:26s} [{rule.family}/{rule.severity}] {rule.doc}")
        return 0

    paths = args.paths or DEFAULT_PATHS
    root = os.path.abspath(args.root or os.getcwd())
    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    project = build_project(paths, root)
    findings = run_rules(project, rules)
    baseline_path = None if args.baseline == "none" else args.baseline
    baseline = Baseline.load(baseline_path) if baseline_path else Baseline()
    findings = suppress(project, findings, baseline)
    live = unsuppressed(findings)

    if args.update_baseline:
        if not baseline_path:
            print("graftlint: --update-baseline needs a baseline path",
                  file=sys.stderr)
            return 2
        n = Baseline.write(baseline_path, live)
        print(f"graftlint: BASELINE UPDATED — {baseline_path} now accepts "
              f"{n} finding(s). Review the diff before committing.")
        return 0

    if args.format == "json":
        print(format_json(findings))
    else:
        print(format_text(findings, len(project.modules)))
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
