"""Multi-host process bootstrap: ``jax.distributed`` + global mesh helpers.

SURVEY.md §7 hard-part #4 (multi-host process model): one TPU pod slice =
N host processes, each owning its local chips, coordinating through JAX's
distributed runtime — the collective plane then spans hosts transparently
(ICI within a slice, DCN across slices), while the framework's OWN RPC
plane (cluster/worker.py) keeps carrying request traffic between the same
hosts. The reference has neither plane split nor multi-process anything —
its "distributed" is N asyncio servers on localhost (SURVEY.md §2.4).

Usage on each TPU-VM host of a slice::

    from distributed_inference_engine_tpu.parallel.multihost import (
        initialize_multihost, global_mesh)

    initialize_multihost()              # env-driven on Cloud TPU; or pass
                                        # coordinator_address/process_id/...
    mesh = global_mesh(MeshConfig(dp=2, tp=8))   # over ALL hosts' devices

Every host then runs the SAME pjit'd program over the global mesh; arrays
sharded over a host's addressable devices stay local, and XLA emits DCN
collectives where shardings demand cross-host movement.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)

_initialized = False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[list] = None,
) -> int:
    """Join this process to the JAX distributed runtime; returns the
    process index.

    With no arguments, Cloud TPU environments auto-discover everything
    from the metadata/env (the common path); explicit arguments support
    bring-your-own clusters (e.g. ``coordinator_address="10.0.0.1:1234",
    num_processes=4, process_id=$RANK``). Idempotent: a second call is a
    no-op returning the existing index.
    """
    global _initialized
    import jax

    if _initialized:
        return jax.process_index()
    kwargs = {}
    # forward each knob independently — a user may rely on an env-provided
    # coordinator while still pinning rank/topology explicitly
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True
    logger.info("jax.distributed up: process %d/%d, %d local / %d global "
                "devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())
    return jax.process_index()


def global_mesh(cfg, devices=None):
    """Build the dp/pp/sp/tp mesh over the global device set. Alias of
    ``parallel.mesh.make_mesh`` (which already defaults to
    ``jax.devices()`` — global across processes once the distributed
    runtime is up), re-exported here so pod-slice code reads explicitly."""
    from .mesh import make_mesh

    return make_mesh(cfg, devices)


def is_primary() -> bool:
    """True on the process that should do singleton work (logging,
    checkpoint writes, serving the coordinator RPC port)."""
    import jax

    return jax.process_index() == 0
