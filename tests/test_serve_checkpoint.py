"""End-to-end real-checkpoint serving path (examples/serve_checkpoint.py):
a synthetic HF checkpoint directory — config.json + model.safetensors +
vocab.json/merges.txt — goes through spec_from_hf_config →
load_checkpoint → (optional) quantize_params → BPETokenizer → continuous
engine → detokenized text. This is the committed proof behind the README
"Real-checkpoint status" note: the environment has no real weights, but
the full path a user with weights runs is driven here token-for-token.
"""

import json

import numpy as np
import pytest


@pytest.fixture
def ckpt_dir(tmp_path):
    from safetensors.numpy import save_file

    # n_kv_heads*head_dim must be a multiple of 128 (paged-KV lane rule)
    D, F, V, L, H, Hkv = 128, 64, 300, 2, 4, 4
    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "llama", "architectures": ["LlamaForCausalLM"],
        "vocab_size": V, "hidden_size": D, "num_hidden_layers": L,
        "num_attention_heads": H, "num_key_value_heads": Hkv,
        "intermediate_size": F, "max_position_embeddings": 64,
        "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
        "torch_dtype": "float32", "eos_token_id": 299,
    }))
    rs = np.random.RandomState(0)
    Hd, Kd = D, D
    raw = {
        "model.embed_tokens.weight": rs.randn(V, D).astype(np.float32) * .05,
        "model.norm.weight": np.ones(D, dtype=np.float32),
        "lm_head.weight": rs.randn(V, D).astype(np.float32) * .05,
    }
    for l in range(L):
        raw[f"model.layers.{l}.input_layernorm.weight"] = np.ones(D, np.float32)
        raw[f"model.layers.{l}.post_attention_layernorm.weight"] = \
            np.ones(D, np.float32)
        raw[f"model.layers.{l}.self_attn.q_proj.weight"] = \
            rs.randn(Hd, D).astype(np.float32) * .05
        raw[f"model.layers.{l}.self_attn.k_proj.weight"] = \
            rs.randn(Kd, D).astype(np.float32) * .05
        raw[f"model.layers.{l}.self_attn.v_proj.weight"] = \
            rs.randn(Kd, D).astype(np.float32) * .05
        raw[f"model.layers.{l}.self_attn.o_proj.weight"] = \
            rs.randn(D, Hd).astype(np.float32) * .05
        raw[f"model.layers.{l}.mlp.gate_proj.weight"] = \
            rs.randn(F, D).astype(np.float32) * .05
        raw[f"model.layers.{l}.mlp.up_proj.weight"] = \
            rs.randn(F, D).astype(np.float32) * .05
        raw[f"model.layers.{l}.mlp.down_proj.weight"] = \
            rs.randn(D, F).astype(np.float32) * .05
    save_file(raw, str(tmp_path / "model.safetensors"))

    # GPT-2-style byte-level BPE files: bytes 0-255 as latin-1-ish chars
    # plus a couple of merges, exactly the HF on-disk format
    from distributed_inference_engine_tpu.utils.tokenizer import (
        _bytes_to_unicode,
    )

    b2u = _bytes_to_unicode()
    vocab = {ch: i for i, ch in enumerate(b2u[b] for b in range(256))}
    he = b2u[ord("h")] + b2u[ord("e")]
    vocab[he] = 256
    hel = he + b2u[ord("l")]
    vocab[hel] = 257
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text(
        "#version: 0.2\n"
        f"{b2u[ord('h')]} {b2u[ord('e')]}\n"
        f"{he} {b2u[ord('l')]}\n")
    return tmp_path


@pytest.mark.parametrize("quant", [0, 4])
def test_serve_checkpoint_end_to_end(ckpt_dir, quant):
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent
                           / "examples"))
    from serve_checkpoint import build_engine

    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )

    engine, tok, eos_ids = build_engine(str(ckpt_dir), quant=quant,
                                        max_slots=2, max_seq_len=64)
    assert eos_ids == [299]        # read from config.json
    ids = tok.encode("hello")
    assert ids[0] == 257, ids      # "hel" merge applied: BPE files honored
    res = engine.generate([GenerationRequest(
        prompt=ids, max_new_tokens=6, temperature=0.0, request_id="t")])[0]
    assert len(res.tokens) == 6
    text = tok.decode(res.tokens)
    assert isinstance(text, str)        # round-trips through the detokenizer
    # quantized serving of a LOADED checkpoint matches shapes/dtype rules
    if quant:
        from distributed_inference_engine_tpu.ops.quant import (
            QuantizedTensor,
        )

        assert isinstance(engine.params["lm_head"], QuantizedTensor)
        assert engine.params["lm_head"].bits == 4


def test_tokenizer_json_layout(ckpt_dir):
    """Modern HF checkpoints (Llama-3/Qwen2) ship one tokenizer.json;
    build_tokenizer must parse it to the SAME tokenizer the split
    vocab.json+merges.txt files produce."""
    from distributed_inference_engine_tpu.utils.tokenizer import (
        BPETokenizer,
        build_tokenizer,
    )

    split = build_tokenizer(str(ckpt_dir))
    vocab = json.loads((ckpt_dir / "vocab.json").read_text())
    merges = [line.split() for line in
              (ckpt_dir / "merges.txt").read_text().splitlines()[1:]]
    (ckpt_dir / "tokenizer.json").write_text(json.dumps({
        "model": {"type": "BPE", "vocab": vocab,
                  "merges": [f"{a} {b}" for a, b in merges]}}))
    (ckpt_dir / "vocab.json").unlink()
    (ckpt_dir / "merges.txt").unlink()
    single = build_tokenizer(str(ckpt_dir))
    assert isinstance(single, BPETokenizer)
    for text in ("hello", "hell", "he said hello"):
        assert single.encode(text) == split.encode(text)
    # added_tokens (Llama-3-era specials living OUTSIDE model.vocab)
    # merge in: the eos id must decode instead of silently dropping
    (ckpt_dir / "tokenizer.json").write_text(json.dumps({
        "added_tokens": [{"id": 299, "content": "<|eot|>"}],
        "model": {"type": "BPE", "vocab": vocab,
                  "merges": [f"{a} {b}" for a, b in merges]}}))
    with_added = build_tokenizer(str(ckpt_dir))
    assert with_added.vocab["<|eot|>"] == 299
    assert with_added.decode([299]) == "<|eot|>"
    from distributed_inference_engine_tpu.utils.tokenizer import (
        ByteTokenizer,
    )

    # non-BPE tokenizer.json degrades to the byte fallback, not an error
    (ckpt_dir / "tokenizer.json").write_text(json.dumps({
        "model": {"type": "Unigram"}}))
    assert isinstance(build_tokenizer(str(ckpt_dir)), ByteTokenizer)
    # SentencePiece-style BPE (type "BPE" but a metasymbol vocab without
    # the byte-unit alphabet — Llama-2/Mistral-v0.1) must ALSO fall back:
    # byte-level encoding through it would silently drop most bytes
    (ckpt_dir / "tokenizer.json").write_text(json.dumps({
        "model": {"type": "BPE",
                  "vocab": {"▁hello": 0, "▁world": 1},
                  "merges": []}}))
    assert isinstance(build_tokenizer(str(ckpt_dir)), ByteTokenizer)
