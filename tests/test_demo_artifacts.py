"""The committed demo artifacts work end-to-end: ``examples/demo_config.toml``
drives the coordinator CLI, and ``examples/client.py`` talks to it.

This is the declared-surface pair the reference README names but never
shipped (``/root/reference/README.md:37-38``: an example client script and a
demo config file). Subprocess-based so the CLIs' argument parsing, readiness
lines, and exit codes are what's under test, not in-process shortcuts.
"""

import os
import queue
import re
import signal
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "examples", "demo_config.toml")
CLIENT = os.path.join(REPO, "examples", "client.py")

# single-device CPU is plenty for llama-tiny and halves process start cost
_ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": "",
        "PYTHONPATH": REPO, "PYTHONUNBUFFERED": "1"}


class _LineReader:
    """Background thread draining a subprocess's stdout into a queue so
    waits are deadline-bounded: a silently wedged subprocess fails the
    test at the timeout instead of hanging a blocking readline() forever
    (select() alone can't do this — lines already pulled into Python's
    buffered reader are invisible to the fd)."""

    def __init__(self, proc):
        self.proc = proc
        self.lines = []                  # full history, for error messages
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            self._q.put(line)
        self._q.put(None)                # EOF sentinel

    def wait_line(self, pattern: str, timeout: float = 120.0) -> str:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise AssertionError(
                    f"timed out waiting for {pattern!r}; output:\n"
                    f"{''.join(self.lines)}")
            try:
                line = self._q.get(timeout=min(remaining, 0.5))
            except queue.Empty:
                continue
            if line is None:
                raise AssertionError(
                    f"process exited {self.proc.returncode} before "
                    f"{pattern!r}; output so far:\n{''.join(self.lines)}")
            self.lines.append(line)
            if re.search(pattern, line):
                return line


def _stop(proc) -> None:
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
    proc.stdout.close()


@pytest.fixture(scope="module")
def demo_fleet():
    """One worker + one coordinator loaded from the committed demo config."""
    worker = subprocess.Popen(
        [sys.executable, "-m", "distributed_inference_engine_tpu.cli.worker",
         "--worker-id", "w0", "--host", "127.0.0.1", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_ENV, cwd=REPO)
    coord = None
    try:
        line = _LineReader(worker).wait_line(r"listening on ")
        wport = int(line.rsplit(":", 1)[1])
        # --port 0 overrides the file's pinned 8000 (test isolation); the
        # model deploy itself comes from the [[models]] section
        coord = subprocess.Popen(
            [sys.executable, "-m",
             "distributed_inference_engine_tpu.cli.coordinator",
             "--config", CONFIG, "--port", "0",
             "--worker", f"w0=127.0.0.1:{wport}"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_ENV, cwd=REPO)
        reader = _LineReader(coord)
        reader.wait_line(r"deployed tiny across 1 workers")
        line = reader.wait_line(r"coordinator listening on ")
        cport = int(line.rsplit(":", 1)[1])
        yield cport
    finally:
        if coord is not None:
            _stop(coord)
        _stop(worker)


def _run_client(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, CLIENT, *args], env=_ENV, cwd=REPO,
        capture_output=True, text=True, timeout=120)


def test_client_generates_through_demo_config(demo_fleet):
    out = _run_client("--port", str(demo_fleet), "--model", "tiny",
                      "--prompt", "1 2 3", "-n", "6")
    assert out.returncode == 0, out.stdout + out.stderr
    m = re.search(r"request 0: tokens=\[([^\]]*)\]", out.stdout)
    assert m, out.stdout
    assert len(m.group(1).split(",")) == 6
    assert "done: 1/1 ok, 6 tokens" in out.stdout


def test_client_streams_and_fans_out(demo_fleet):
    out = _run_client("--port", str(demo_fleet), "--model", "tiny",
                      "--prompt", "4 5", "-n", "4", "--stream")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "stream: [" in out.stdout          # per-chunk callback fired

    out = _run_client("--port", str(demo_fleet), "--model", "tiny",
                      "--prompt", "7 8 9", "-n", "3", "--requests", "4")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "done: 4/4 ok, 12 tokens" in out.stdout


def test_client_fails_loudly_on_unknown_model(demo_fleet):
    out = _run_client("--port", str(demo_fleet), "--model", "nope",
                      "--prompt", "1", "-n", "2")
    assert out.returncode == 1
    assert "FAILED" in out.stderr
