"""HF safetensors checkpoint loading into the stacked-layer param tree.

Heir of the registry's ``model_path`` field, which the reference never reads
(no weights exist anywhere in it — SURVEY.md §5 checkpoint/resume row). Here
``load_checkpoint`` maps a HuggingFace checkpoint directory (GPT-2 or Llama
naming) onto the stacked ``[n_layers, ...]`` pytree of ``models/base.py``,
casting to the spec dtype.

Zero-egress environment note: weights must already be on local disk; nothing
is downloaded. ``save_checkpoint`` writes the same HF naming, so tests can
fabricate tiny checkpoints and round-trip them.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Dict, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .base import ModelSpec, Params


def _iter_safetensor_files(path: pathlib.Path) -> Iterator[pathlib.Path]:
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    yield from files


def _load_raw(path: pathlib.Path) -> Dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    raw: Dict[str, np.ndarray] = {}
    for f in _iter_safetensor_files(path):
        raw.update(load_file(str(f)))
    return raw


def _stack(raw: Dict[str, np.ndarray], template: str, n_layers: int,
           transpose: bool = False) -> np.ndarray:
    mats = []
    for layer in range(n_layers):
        name = template.format(layer)
        if name not in raw:
            raise KeyError(f"checkpoint missing tensor {name}")
        m = raw[name]
        mats.append(m.T if transpose else m)
    return np.stack(mats)


# HF GPT-2 Conv1D stores weights as [in, out] (no transpose needed for x @ W);
# HF Llama nn.Linear stores [out, in] (transpose to our [in, out] layout).

def _map_gpt2(raw: Dict[str, np.ndarray], spec: ModelSpec) -> Dict[str, Any]:
    L, D = spec.n_layers, spec.d_model
    pre = "" if "wte.weight" in raw else "transformer."
    qkv = _stack(raw, pre + "h.{}.attn.c_attn.weight", L)       # [L, D, 3D]
    qkv_b = _stack(raw, pre + "h.{}.attn.c_attn.bias", L)       # [L, 3D]
    blocks = {
        "ln1_scale": _stack(raw, pre + "h.{}.ln_1.weight", L),
        "ln1_bias": _stack(raw, pre + "h.{}.ln_1.bias", L),
        "ln2_scale": _stack(raw, pre + "h.{}.ln_2.weight", L),
        "ln2_bias": _stack(raw, pre + "h.{}.ln_2.bias", L),
        "wq": qkv[:, :, :D],
        "wk": qkv[:, :, D : 2 * D],
        "wv": qkv[:, :, 2 * D :],
        "bq": qkv_b[:, :D],
        "bk": qkv_b[:, D : 2 * D],
        "bv": qkv_b[:, 2 * D :],
        "wo": _stack(raw, pre + "h.{}.attn.c_proj.weight", L),
        "bo": _stack(raw, pre + "h.{}.attn.c_proj.bias", L),
        "w_up": _stack(raw, pre + "h.{}.mlp.c_fc.weight", L),
        "b_up": _stack(raw, pre + "h.{}.mlp.c_fc.bias", L),
        "w_down": _stack(raw, pre + "h.{}.mlp.c_proj.weight", L),
        "b_down": _stack(raw, pre + "h.{}.mlp.c_proj.bias", L),
    }
    return {
        "tok_emb": raw[pre + "wte.weight"],
        "pos_emb": raw[pre + "wpe.weight"],
        "blocks": blocks,
        "lnf_scale": raw[pre + "ln_f.weight"],
        "lnf_bias": raw[pre + "ln_f.bias"],
    }


def _map_llama_attn(raw: Dict[str, np.ndarray], spec: ModelSpec,
                    pre: str) -> Dict[str, Any]:
    """The Llama-family tree minus the MLP weights (shared with Mixtral)."""
    L = spec.n_layers
    blocks = {
        "ln1_scale": _stack(raw, pre + "layers.{}.input_layernorm.weight", L),
        "ln2_scale": _stack(raw, pre + "layers.{}.post_attention_layernorm.weight", L),
        "wq": _stack(raw, pre + "layers.{}.self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(raw, pre + "layers.{}.self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(raw, pre + "layers.{}.self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(raw, pre + "layers.{}.self_attn.o_proj.weight", L, transpose=True),
    }
    if spec.qkv_bias:   # Qwen2: biases on q/k/v only
        blocks["bq"] = _stack(raw, pre + "layers.{}.self_attn.q_proj.bias", L)
        blocks["bk"] = _stack(raw, pre + "layers.{}.self_attn.k_proj.bias", L)
        blocks["bv"] = _stack(raw, pre + "layers.{}.self_attn.v_proj.bias", L)
    emb_key = (pre + "embed_tokens.weight") if pre else "embed_tokens.weight"
    params = {
        "tok_emb": raw[emb_key],
        "blocks": blocks,
        "lnf_scale": raw[pre + "norm.weight"],
    }
    if "lm_head.weight" in raw and not spec.tie_embeddings:
        params["lm_head"] = raw["lm_head.weight"].T
    elif not spec.tie_embeddings:
        params["lm_head"] = raw[emb_key].T   # HF tied checkpoints omit lm_head
    return params


def _map_llama(raw: Dict[str, np.ndarray], spec: ModelSpec) -> Dict[str, Any]:
    L = spec.n_layers
    pre = "" if "model.embed_tokens.weight" not in raw else "model."
    params = _map_llama_attn(raw, spec, pre)
    params["blocks"].update({
        "w_gate": _stack(raw, pre + "layers.{}.mlp.gate_proj.weight", L, transpose=True),
        "w_up": _stack(raw, pre + "layers.{}.mlp.up_proj.weight", L, transpose=True),
        "w_down": _stack(raw, pre + "layers.{}.mlp.down_proj.weight", L, transpose=True),
    })
    return params


def _map_mixtral(raw: Dict[str, np.ndarray], spec: ModelSpec) -> Dict[str, Any]:
    """HF Mixtral naming: the attention/norm tree is Llama's; the MLP is
    ``block_sparse_moe.gate`` (router) + per-expert ``w1``(gate)/``w2``(down)/
    ``w3``(up) linears, stacked here onto a leading expert axis [L, E, ...]."""
    L, E = spec.n_layers, spec.n_experts
    pre = "" if "model.embed_tokens.weight" not in raw else "model."
    tree = _map_llama_attn(raw, spec, pre)

    def experts(w: str, transpose: bool) -> np.ndarray:
        per_layer = []
        for layer in range(L):
            mats = []
            for e in range(E):
                name = (f"{pre}layers.{layer}.block_sparse_moe."
                        f"experts.{e}.{w}.weight")
                if name not in raw:
                    raise KeyError(f"checkpoint missing tensor {name}")
                mats.append(raw[name].T if transpose else raw[name])
            per_layer.append(np.stack(mats))
        return np.stack(per_layer)                       # [L, E, ...]

    tree["blocks"].update({
        "w_router": _stack(
            raw, pre + "layers.{}.block_sparse_moe.gate.weight", L,
            transpose=True),                              # [L, D, E]
        "w_gate": experts("w1", transpose=True),          # [L, E, D, F]
        "w_down": experts("w2", transpose=True),          # [L, E, F, D]
        "w_up": experts("w3", transpose=True),            # [L, E, D, F]
    })
    return tree


def load_checkpoint(path: str, spec: ModelSpec) -> Params:
    """Load a local HF checkpoint dir into the stacked param tree, cast to
    ``spec.dtype``."""
    p = pathlib.Path(path)
    raw = _load_raw(p)
    if any(k.endswith("wte.weight") for k in raw):
        tree = _map_gpt2(raw, spec)
    elif any("block_sparse_moe" in k for k in raw):
        tree = _map_mixtral(raw, spec)
    elif any(k.endswith("embed_tokens.weight") for k in raw):
        tree = _map_llama(raw, spec)
    else:
        raise ValueError(f"unrecognized checkpoint naming in {path}")
    dt = spec.jnp_dtype

    def cast(x):
        a = np.asarray(x)
        if a.dtype == np.uint16:   # bf16 tensors surfaced as raw bit patterns
            import ml_dtypes

            a = a.view(ml_dtypes.bfloat16)
        return jnp.asarray(a).astype(dt)

    import jax

    return jax.tree.map(cast, tree)


def _llama_like(cfg: Dict[str, Any], **quirks: Any) -> ModelSpec:
    """Common spec kwargs for every Llama-shaped HF config (llama, mixtral,
    qwen2, mistral, gemma); the family branches pass only their
    distinguishing flags so a shared fix lands in one place."""
    base: Dict[str, Any] = dict(
        vocab_size=cfg["vocab_size"],
        d_model=cfg["hidden_size"],
        n_layers=cfg["num_hidden_layers"],
        n_heads=cfg["num_attention_heads"],
        n_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
        d_ff=cfg["intermediate_size"],
        max_seq_len=cfg.get("max_position_embeddings", 4096),
        pos_emb="rope",
        norm="rmsnorm",
        mlp="swiglu",
        use_bias=False,
        tie_embeddings=cfg.get("tie_word_embeddings", False),
        rope_theta=cfg.get("rope_theta", 10000.0),
        norm_eps=cfg.get("rms_norm_eps", 1e-5),
    )
    base.update(quirks)
    return ModelSpec(**base).validate()


def spec_from_hf_config(path: str, cfg: Optional[dict] = None) -> ModelSpec:
    """Build a ModelSpec from a HF ``config.json``.

    Matches on ``model_type`` (authoritative in HF configs) with the
    architectures[] string as fallback. Unsupported relatives that share a
    name prefix (gemma2/gemma3, qwen3, ...) must NOT fall through to a
    near-miss spec — loading e.g. a Gemma-2 checkpoint as Gemma-1 would run
    without error and generate garbage — so matching is exact.
    ``cfg``: pass the already-parsed config.json dict to skip the read
    (callers that also need other fields, e.g. eos_token_id)."""
    if cfg is None:
        cfg = json.loads((pathlib.Path(path) / "config.json").read_text())
    arch = (cfg.get("architectures") or [""])[0].lower()
    mt = cfg.get("model_type", "")

    def is_(family: str) -> bool:
        return mt == family or arch == f"{family}forcausallm"

    if mt == "gpt2" or "gpt2" in arch:
        return ModelSpec(
            vocab_size=cfg["vocab_size"],
            d_model=cfg["n_embd"],
            n_layers=cfg["n_layer"],
            n_heads=cfg["n_head"],
            n_kv_heads=cfg["n_head"],
            d_ff=4 * cfg["n_embd"],
            max_seq_len=cfg.get("n_positions", 1024),
            pos_emb="learned",
            norm="layernorm",
            mlp="gelu",
            use_bias=True,
            tie_embeddings=True,
            norm_eps=cfg.get("layer_norm_epsilon", 1e-5),
        ).validate()
    if is_("mixtral"):
        return _llama_like(
            cfg,
            max_seq_len=cfg.get("max_position_embeddings", 32768),
            rope_theta=cfg.get("rope_theta", 1e6),
            n_experts=cfg["num_local_experts"],
            experts_per_token=cfg.get("num_experts_per_tok", 2),
        )
    if is_("qwen2"):
        return _llama_like(
            cfg,
            max_seq_len=cfg.get("max_position_embeddings", 32768),
            rope_theta=cfg.get("rope_theta", 1e6),
            norm_eps=cfg.get("rms_norm_eps", 1e-6),
            qkv_bias=True,
        )
    if is_("mistral"):
        return _llama_like(
            cfg,
            max_seq_len=cfg.get("max_position_embeddings", 32768),
            sliding_window=cfg.get("sliding_window") or 0,
        )
    if is_("gemma"):
        return _llama_like(
            cfg,
            max_seq_len=cfg.get("max_position_embeddings", 8192),
            mlp="geglu",
            tie_embeddings=True,   # Gemma always ties; HF omits lm_head
            norm_eps=cfg.get("rms_norm_eps", 1e-6),
            head_dim_override=cfg.get("head_dim", 0),
            emb_scale=True,
            norm_plus_one=True,
        )
    if is_("llama"):
        return _llama_like(cfg)
    raise ValueError(f"unsupported architecture in {path}: "
                     f"model_type={mt!r} architectures={arch!r}")


def save_checkpoint_gpt2(path: str, params: Params, spec: ModelSpec) -> None:
    """Write params back out in HF GPT-2 naming (test fixture / export)."""
    from safetensors.numpy import save_file

    b = params["blocks"]
    L, D = spec.n_layers, spec.d_model
    raw: Dict[str, np.ndarray] = {
        "wte.weight": np.asarray(params["tok_emb"], dtype=np.float32),
        "wpe.weight": np.asarray(params["pos_emb"], dtype=np.float32),
        "ln_f.weight": np.asarray(params["lnf_scale"], dtype=np.float32),
        "ln_f.bias": np.asarray(params["lnf_bias"], dtype=np.float32),
    }
    qkv = np.concatenate(
        [np.asarray(b["wq"]), np.asarray(b["wk"]), np.asarray(b["wv"])], axis=-1
    ).astype(np.float32)
    qkv_b = np.concatenate(
        [np.asarray(b["bq"]), np.asarray(b["bk"]), np.asarray(b["bv"])], axis=-1
    ).astype(np.float32)
    for l in range(L):
        raw[f"h.{l}.attn.c_attn.weight"] = qkv[l]
        raw[f"h.{l}.attn.c_attn.bias"] = qkv_b[l]
        for ours, theirs in (
            ("ln1_scale", "ln_1.weight"), ("ln1_bias", "ln_1.bias"),
            ("ln2_scale", "ln_2.weight"), ("ln2_bias", "ln_2.bias"),
            ("wo", "attn.c_proj.weight"), ("bo", "attn.c_proj.bias"),
            ("w_up", "mlp.c_fc.weight"), ("b_up", "mlp.c_fc.bias"),
            ("w_down", "mlp.c_proj.weight"), ("b_down", "mlp.c_proj.bias"),
        ):
            raw[f"h.{l}.{theirs}"] = np.asarray(b[ours][l], dtype=np.float32)
    save_file(raw, str(pathlib.Path(path) / "model.safetensors"))
