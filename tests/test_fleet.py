"""Fleet-scale serving tests (-m fleet): prefix-affinity routing over a
live multi-worker fake fleet, affinity rebind across drain / supervisor
respawn / stream failover, and the disaggregated prefill+decode path
through the coordinator.

Same determinism discipline as the chaos suite: the fake continuous
engine's next token is a crc32 chain over the FULL context, so whichever
worker — or sequence of workers, after a rebind — serves a request, the
output is checkable token-for-token. Replicated (non-sharded) deploys use
``deploy_model(register_shards=False)``, the mode where the LOAD BALANCER
(not the registry's consistent hashing) places every request and the
``prefix_affinity`` strategy engages.
"""

import asyncio

import pytest

from distributed_inference_engine_tpu.api.coordinator import (
    Coordinator,
    CoordinatorConfig,
)
from distributed_inference_engine_tpu.cluster.load_balancer import (
    LoadBalancer,
    LoadBalancerStrategy,
)
from distributed_inference_engine_tpu.cluster.worker import WorkerServer
from distributed_inference_engine_tpu.config import (
    HealthConfig,
    ModelConfig,
    ServerConfig,
)
from distributed_inference_engine_tpu.models.fake import _chain

pytestmark = pytest.mark.fleet

VOCAB = 997


def expected_tokens(prompt, n, vocab=VOCAB):
    st = 0
    for t in prompt:
        st = _chain(st, t)
    out = []
    for _ in range(n):
        nxt = st % vocab
        st = _chain(st, nxt)
        out.append(nxt)
    return out


PREFIX = [7, 7, 7, 7]           # one full affinity page (page_size=4)


def prompt_with_tail(i):
    return PREFIX + [100 + i]


async def start_affinity_fleet(n_workers, strategy="prefix_affinity",
                               model_meta=None, **coord_overrides):
    """Coordinator with LB-placed (non-sharded) replicas of the fake."""
    kw = dict(lb_strategy=strategy, affinity_page_size=4, affinity_pages=2,
              retry_seed=7, retry_backoff_base_s=0.01)
    kw.update(coord_overrides)
    coord = Coordinator(CoordinatorConfig(**kw))
    await coord.start()
    meta = {"continuous": 1, "max_slots": 4}
    meta.update(model_meta or {})
    cfg = ModelConfig(name="m", architecture="fake", metadata=meta)
    workers = {}
    for i in range(n_workers):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=f"w{i}"))
        host, port = await w.start()
        workers[f"w{i}"] = w
        coord.add_worker(f"w{i}", host, port)
    await coord.deploy_model(cfg, register_shards=False)
    return coord, workers, cfg


async def stop_fleet(coord, workers):
    await coord.stop()
    for w in workers.values():
        try:
            await w.stop()
        except Exception:
            pass


async def served_counts(coord, workers):
    """Per-worker request counts from each live worker's engine metrics."""
    out = {}
    for wid in workers:
        if wid not in coord.router.workers:
            continue
        m = await coord.router.client_for(wid).metrics()
        out[wid] = int(m["models"]["m"]["total_requests"])
    return out


# ----------------------------------------------------- affinity placement

async def test_same_prefix_lands_on_same_worker():
    """Every same-prefix request must land on the one worker whose cache
    is warm; the LB's hit/miss counters must account for each pick."""
    coord, workers, _ = await start_affinity_fleet(4)
    try:
        n = 10
        for i in range(n):
            r = await coord.submit("m", prompt=prompt_with_tail(i),
                                   max_new_tokens=6, no_cache=True)
            assert r["tokens"] == expected_tokens(prompt_with_tail(i), 6)
        counts = await served_counts(coord, workers)
        hot = [wid for wid, c in counts.items() if c]
        assert hot == [hot[0]] * len(hot) and counts[hot[0]] == n, \
            f"same-prefix requests scattered: {counts}"
        lb = coord.lb.get_all_stats()
        assert lb["affinity_misses"] == 1          # first sight binds
        assert lb["affinity_hits"] == n - 1        # the rest ride it
        assert lb["affinity_bindings"] == 1
    finally:
        await stop_fleet(coord, workers)


async def test_distinct_prefixes_get_distinct_bindings():
    """Cold prefixes fall back to least-connections — concurrent distinct
    prefixes spread instead of piling onto one replica."""
    coord, workers, _ = await start_affinity_fleet(4)
    try:
        prompts = [[p, p, p, p, 9] for p in range(1, 9)]
        results = await asyncio.gather(*[
            coord.submit("m", prompt=p, max_new_tokens=6, no_cache=True)
            for p in prompts])
        for p, r in zip(prompts, results):
            assert r["tokens"] == expected_tokens(p, 6)
        lb = coord.lb.get_all_stats()
        assert lb["affinity_bindings"] == len(prompts)
        bound_workers = set(coord.lb._affinity.values())
        assert len(bound_workers) > 1, \
            "8 cold prefixes all bound to one worker"
    finally:
        await stop_fleet(coord, workers)


async def test_short_prompt_has_no_affinity_key():
    """Prompts shorter than one affinity page carry no key and spread
    via the keyless fallback — no binding-table pollution."""
    coord, workers, _ = await start_affinity_fleet(2)
    try:
        for i in range(4):
            r = await coord.submit("m", prompt=[i], max_new_tokens=4,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens([i], 4)
        assert coord.lb.get_all_stats()["affinity_bindings"] == 0
    finally:
        await stop_fleet(coord, workers)


# --------------------------------------------------------- rebind: drain

async def test_affinity_rebinds_after_drain_without_drops():
    """Draining the bound worker must move its bindings off it (the KV
    fabric hands them to a survivor rather than dropping them cold);
    follow-up same-prefix traffic lands there and stays token-exact."""
    coord, workers, _ = await start_affinity_fleet(3)
    try:
        for i in range(4):
            await coord.submit("m", prompt=prompt_with_tail(i),
                               max_new_tokens=6, no_cache=True)
        bound = next(iter(coord.lb._affinity.values()))
        await coord.drain_worker(bound)
        assert bound not in coord.lb._affinity.values(), \
            "drain must move the drained worker's bindings off it"
        lb0 = coord.lb.get_all_stats()
        assert lb0["affinity_handoffs"] + lb0["affinity_rebinds"] >= 1
        for i in range(4, 10):
            p = prompt_with_tail(i)
            r = await coord.submit("m", prompt=p, max_new_tokens=6,
                                   no_cache=True)
            assert r["tokens"] == expected_tokens(p, 6)
        rebound = next(iter(coord.lb._affinity.values()))
        assert rebound != bound
        counts = await served_counts(coord, workers)
        assert counts[rebound] >= 6
    finally:
        await stop_fleet(coord, workers)


# ----------------------------------------- rebind: supervisor kill/respawn

async def test_affinity_rebinds_after_supervisor_respawn():
    """Hard-kill the bound worker mid-load with the supervisor on: every
    request still completes token-exact (retry + failover), the stale
    binding is invalidated, and the respawned worker rejoins the fleet."""
    coord, workers, cfg = await start_affinity_fleet(
        2, model_meta={"step_latency_s": 0.005},
        health=HealthConfig(check_interval=0.05, check_timeout=0.5,
                            max_consecutive_failures=2),
        supervisor_interval_s=0.05, supervisor_backoff_base_s=0.02,
        supervisor_backoff_max_s=0.1)
    spawned = []

    async def restart_hook(worker_id, info):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=worker_id))
        host, port = await w.start()
        spawned.append(w)
        return host, port

    coord.start_supervisor(restart_hook)
    try:
        r = await coord.submit("m", prompt=prompt_with_tail(0),
                               max_new_tokens=6, no_cache=True)
        assert r["tokens"] == expected_tokens(prompt_with_tail(0), 6)
        bound = next(iter(coord.lb._affinity.values()))

        prompts = [prompt_with_tail(1 + i) for i in range(12)]
        tasks = [asyncio.ensure_future(
            coord.submit("m", prompt=p, max_new_tokens=8, no_cache=True))
            for p in prompts]
        await asyncio.sleep(0.05)
        await workers.pop(bound).stop()

        results = await asyncio.gather(*tasks, return_exceptions=True)
        ok = sum(1 for p, r in zip(prompts, results)
                 if isinstance(r, dict)
                 and r["tokens"] == expected_tokens(p, 8))
        assert ok == len(prompts), \
            f"dropped requests across respawn: {ok}/{len(prompts)}"
        assert bound not in coord.lb._affinity.values()
        # the supervisor may still be mid-respawn; wait for it
        for _ in range(100):
            if coord.get_stats()["supervisor_respawns"] >= 1:
                break
            await asyncio.sleep(0.05)
        assert coord.get_stats()["supervisor_respawns"] >= 1
    finally:
        await stop_fleet(coord, workers)
        for w in spawned:
            try:
                await w.stop()
            except Exception:
                pass


# ------------------------------------------- rebind: stream failover

async def test_stream_failover_invalidates_stale_binding():
    """Mid-stream kill of the bound worker: the replay resumes token-exact
    on a survivor AND the dead worker's binding is invalidated, so the
    next same-prefix request routes straight to a live replica."""
    coord, workers, _ = await start_affinity_fleet(
        2, model_meta={"step_latency_s": 0.01})
    try:
        got, killed = [], []

        def on_tokens(toks):
            got.append(list(toks))
            if len(got) == 3 and not killed:
                for wid, w in workers.items():
                    if w._request_count:
                        killed.append(wid)
                        asyncio.ensure_future(w.stop())

        prompt = PREFIX + [42]
        r = await coord.submit_stream("m", prompt=prompt, max_new_tokens=20,
                                      on_tokens=on_tokens)
        exp = expected_tokens(prompt, 20)
        assert killed, "the serving worker must have been killed mid-stream"
        assert r["tokens"] == exp
        assert [t for c in got for t in c] == exp
        dead = killed[0]
        assert dead not in coord.lb._affinity.values(), \
            "stream failover must invalidate the stale binding"
        assert coord.lb.get_all_stats()["affinity_rebinds"] >= 1
        # follow-up same-prefix request completes on a live replica (the
        # LB's own health view may lag the kill, so a dispatch retry is
        # permitted — what matters is the stale binding is gone)
        r2 = await coord.submit("m", prompt=PREFIX + [43], max_new_tokens=6,
                                no_cache=True)
        assert r2["tokens"] == expected_tokens(PREFIX + [43], 6)
        assert dead not in coord.lb._affinity.values()
        # once the key settles on a live worker, it stays there
        for i in (44, 45):
            r3 = await coord.submit("m", prompt=PREFIX + [i],
                                    max_new_tokens=6, no_cache=True)
            assert r3["tokens"] == expected_tokens(PREFIX + [i], 6)
        survivors = set(coord.lb._affinity.values())
        assert survivors and dead not in survivors
    finally:
        await stop_fleet(coord, workers)


# ------------------------------------- disaggregated pools via coordinator

async def test_disagg_pools_token_exact_through_coordinator():
    """Prefill pool + decode pool over real framed RPC: results must be
    chain-exact (first token from the handoff, continuation decode-side),
    the prefill pool must actually ship KV bytes, and worker roles must
    be visible in coordinator stats."""
    coord = Coordinator(CoordinatorConfig(retry_seed=7,
                                          retry_backoff_base_s=0.01))
    await coord.start()
    cfg = ModelConfig(name="m", architecture="fake",
                      metadata={"continuous": 1, "max_slots": 4})
    workers = {}
    for wid in ("p0", "d0", "d1"):
        w = WorkerServer(ServerConfig(host="127.0.0.1", port=0,
                                      worker_id=wid))
        host, port = await w.start()
        workers[wid] = w
        coord.add_worker(wid, host, port)
    try:
        n_pre, n_dec = await coord.deploy_model_disaggregated(
            cfg, ["p0"], ["d0", "d1"])
        assert (n_pre, n_dec) == (1, 2)
        roles = coord.get_stats()["worker_roles"]
        assert roles == {"p0": "prefill", "d0": "decode", "d1": "decode"}

        prompts = [[200 + i, i % 5, 3, 8] for i in range(8)]
        results = await asyncio.gather(*[
            coord.submit("m", prompt=p, max_new_tokens=8, no_cache=True)
            for p in prompts])
        for p, r in zip(prompts, results):
            assert r["tokens"] == expected_tokens(p, 8)
            assert r["metadata"]["prefill_worker"] == "p0"
            assert r["metadata"]["decode_worker"] in ("d0", "d1")
        m = await coord.router.client_for("p0").metrics()
        assert m["handoff_bytes_shipped"] > 0
        assert m["models"]["m"]["role"] == "prefill"
    finally:
        await stop_fleet(coord, workers)


# --------------------------------------------------- LB unit-level checks

def _lb(strategy=LoadBalancerStrategy.PREFIX_AFFINITY, capacity=4096):
    lb = LoadBalancer(strategy=strategy, affinity_capacity=capacity)
    for i in range(3):
        lb.register_worker(f"w{i}", "127.0.0.1", 9000 + i)
    return lb


def test_lb_affinity_hit_miss_rebind_counters():
    lb = _lb()
    first = lb.get_worker(affinity="k1")
    assert lb.get_worker(affinity="k1").worker_id == first.worker_id
    stats = lb.get_all_stats()
    assert (stats["affinity_misses"], stats["affinity_hits"]) == (1, 1)
    lb.unregister_worker(first.worker_id)
    again = lb.get_worker(affinity="k1")
    assert again.worker_id != first.worker_id
    stats = lb.get_all_stats()
    # one rebind from the invalidation; the re-pick is a fresh miss
    assert stats["affinity_rebinds"] == 1
    assert stats["affinity_misses"] == 2


def test_lb_affinity_lru_capacity():
    lb = _lb(capacity=2)
    for k in ("a", "b", "c"):
        lb.get_worker(affinity=k)
    stats = lb.get_all_stats()
    assert stats["affinity_bindings"] == 2
    assert "a" not in lb._affinity and "c" in lb._affinity


def test_lb_affinity_quarantine_invalidates():
    lb = _lb()
    s = lb.get_worker(affinity="k")
    lb.quarantine(s.worker_id)
    assert s.worker_id not in lb._affinity.values()
    assert lb.get_all_stats()["affinity_rebinds"] == 1


def test_lb_keyless_requests_fall_back():
    lb = _lb()
    picks = {lb.get_worker().worker_id for _ in range(6)}
    assert len(picks) >= 1              # keyless path stays functional
    assert lb.get_all_stats()["affinity_bindings"] == 0
