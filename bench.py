"""Benchmark entry point — run by the driver on real TPU hardware.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
Diagnostics go to stderr.

Default rung (BASELINE.md ladder rung 3-4, VERDICT r1 item 1): steady-state
decode throughput of an **8B-class Llama-shaped model, packed-int4 weights
(the fastest measured config — stacked Mosaic kernel with fused
qkv/gate+up payloads, per-shape tuned blocks, and a vocab-padded
lm_head; 5,458 tok/s r5), continuous engine with paged KV at bs128** on
one v5e chip — random-init (weights' values don't change the FLOP/byte
counts; zero-egress environment has no checkpoint on disk). Alongside tok/s it reports the HBM roofline:
``hbm_util`` = achieved bytes/s ÷ the chip's ~819 GB/s — decode is
bandwidth-bound, so this is the honest "how much headroom is left" number.

``vs_baseline``: the reference publishes no numbers (BASELINE.md — its
"model" is an asyncio sleep), so this repo's north star is the denominator:
BASELINE.json's ≥1,000 output tok/s target for the 8B class. (Round 1
divided by the mock's simulated 20 responses/s — a vacuous ratio, retired.)

Env knobs:
    BENCH_MODEL    spec name (default llama3-8b; gpt2 = round-1 rung)
    BENCH_QUANT    4 = packed int4 (default for 8B-class since r4 — the
                   fastest measured config, 5,458 tok/s at bs128 via the
                   stacked Mosaic kernel + r5 fusions), 1/8 = int8,
                   0 = full precision (default for small models)
    BENCH_ENGINE   continuous (default) | static | serving
    BENCH_BATCH    decode slots (default 128 for the 8B int4 continuous
                   flagship — the bs that int4's freed HBM affords, 5,453
                   tok/s measured; 64 otherwise)
    BENCH_PROMPT / BENCH_NEW_TOKENS   lengths (default 128 / 128)
    BENCH_KV_DTYPE paged-KV dtype (continuous; default bfloat16)
    BENCH_ATTN     attention impl: xla (default) | pallas |
                   pallas-decode (fused flash-decode kernel: paged prefix
                   + side window in one pallas_call per layer,
                   ops/flash_decode.py) | pallas-decode-fw (same + fresh-KV
                   side writeback in the kernel epilogue)
    BENCH_DECODE_MODE  window | inline (default: window for 8B-class,
                   inline for small-KV models — the measured crossover)
    BENCH_FUSED    1 (default) = fused decode megastep: RMSNorm+matmul and
                   attn-out/MLP-down+residual-add run as single Pallas
                   kernels on the decode path (ops/fused_decode.py);
                   0 = unfused reference path (bit-identical tokens)
    BENCH_OVERLAP  1 (default) = serving mode overlaps pump batch formation
                   with in-flight device steps (engine.overlap_hook);
                   0 = drain the inbox only at the top of the pump loop
    BENCH_KV_OFFLOAD   1 = host-RAM KV tier (continuous engine;
                   engine/kv_offload.py): evicted prefix pages offload to
                   host instead of dropping, admission prefetches them
                   back, pool exhaustion swaps decode victims instead of
                   finishing them; BENCH_KV_OFFLOAD_BYTES caps the host
                   store (default 1 GiB)
    BENCH_ENGINE=speculative: draft = the target's own first
                   BENCH_DRAFT_LAYERS layers (default 8), k=BENCH_SPEC_K
                   (default 4) — deterministic acceptance from shared
                   structure (engine.speculative.truncated_draft)
    serving mode:  BENCH_RATE (req/s Poisson, default 16),
                   BENCH_REQUESTS (default 64), BENCH_STEPS (chunk, def 16),
                   BENCH_MAX_WAITING (queue cap, default 4x slots; 0 = off),
                   BENCH_DEADLINE_S (queue deadline shed, default 10; 0 = off),
                   BENCH_ADMIT_MIN (hold admissions until this many waiters,
                   default 0 = off), BENCH_ADMIT_HOLD (max admission hold
                   seconds, default 0.25)
    BENCH_RUNS     timed repetitions, best-of reported (default 3)
    BENCH_DEFER    1 = defer_sync: overlap each chunk's packed readback
                   with the next chunk's execution (serving-mode lever)
    BENCH_STREAM   1 = sub-chunk streaming: streaming-flagged slots decode
                   in BENCH_STREAM_STEPS-step chunks (pow2-bucketed,
                   default 2) and emit through the device->host token
                   ring; pure-batch slots keep the full megastep
    BENCH_MIX_EVERY / BENCH_MIX_PROMPT   mixed workload: every Nth serving
                   request carries a BENCH_MIX_PROMPT-token prompt
                   (default 0 = off / 2048)
    BENCH_FORCE_CPU  1 = skip the TPU probe and emit the CPU-fallback
                   result line (driver smoke-testing)
    fleet sweep (examples/fleet_sweep.py — fake-fleet goodput scaling
                   through the coordinator; the constants are read HERE so
                   the knob catalog stays one file):
                   BENCH_FLEET_DIR (per-leg fleet JSON output dir, default
                   bench_obs; "0" disables), BENCH_FLEET_NS (fleet sizes,
                   default 1,2,4), BENCH_FLEET_REQUESTS (requests per
                   worker per leg, default 160), BENCH_FLEET_RATE (offered
                   req/s per worker, default 120 — ~20% past a fake
                   worker's capacity so the scaling legs measure sustained
                   goodput, not offered load), BENCH_FLEET_NEW_TOKENS
                   (default 16), BENCH_FLEET_STEP_MS (fake decode step
                   latency, default 5), BENCH_FLEET_SLOTS (fake decode
                   slots, default 8), BENCH_FLEET_SEED (arrivals + retry
                   jitter, default 1234), BENCH_FLEET_TINY (1 = run the
                   llama-tiny disaggregated token-exactness leg, default 1),
                   BENCH_FLEET_MIN (autoscale leg min fleet, default 1),
                   BENCH_FLEET_MAX (autoscale leg max fleet, default 3),
                   BENCH_FLEET_BURST (autoscale leg mid-run load
                   multiplier vs one worker's capacity, default 3.5 —
                   keep it ABOVE BENCH_FLEET_MAX so the burst saturates
                   even the full fleet: every smaller fleet is clearly
                   insufficient and the full one never reads as idle
                   mid-burst, which keeps the decision sequence
                   replay-stable)
    The sweep's non-BENCH knobs (SWEEP_* family, shared naming with
    examples/serving_sweep.py): serving_sweep reads SWEEP_RATES /
    SWEEP_REQUESTS / SWEEP_TRIALS / SWEEP_SHAPE; fleet_sweep reads
    SWEEP_LEGS (comma list to run a subset of
    replicated,disagg,affinity,kill,kvfabric,stream,autoscale,upgrade,
    multimodel,long,tiny; SWEEP_SHAPE=long raises the long leg's
    prompts from 2k to 8k, SWEEP_SHAPE=moe points serving_sweep at the
    capacity-bound int4 mixtral-16g rung).
"""

import json
import math
import os
import subprocess
import sys
import time

# Benchmark runs on the real chip — do NOT import tests/conftest (which pins
# CPU). Keep XLA cache warm across runs where the driver allows it.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

V5E_HBM_GBPS = 819.0          # v5e peak HBM bandwidth
NORTH_STAR_TOKS = 1000.0      # BASELINE.json: >=1k output tok/s, 8B class

MODEL = os.environ.get("BENCH_MODEL", "llama3-8b")
IS_BIG = "8b" in MODEL or "7b" in MODEL
# BENCH_QUANT: 0 = full precision, 1/8 = int8 weight-only, 4 = packed int4.
# Default for the 8B class is int4 — the fastest measured config since the
# r4 stacked Mosaic kernel (4,254 tok/s vs int8's 3,661 at bs64). The
# default is DOWNGRADED to int8 by resolve_quant() when the Mosaic kernel
# cannot engage (MoE expert weights are 4-D; multi-device processes kept
# the XLA path until r5's shard_map wrapper): the pure-XLA int4 path
# measured 1,584 tok/s — a silent 2.3x loss vs int8 (ADVICE r4).
_Q_EXPLICIT = "BENCH_QUANT" in os.environ
_Q = os.environ.get("BENCH_QUANT", "4" if IS_BIG else "0")
QUANT = _Q not in ("0", "")
QUANT_BITS = 4 if _Q == "4" else 8


def resolve_quant(spec) -> None:
    """Finalize the quant default once the model spec is known (ADVICE
    r4): a DEFAULTED int4 drops to int8 when the Mosaic kernel cannot
    take the weights under ANY mode — i.e. MoE specs, whose 4-D expert
    payloads the stacked kernel rejects. Multi-device processes no
    longer downgrade: sharded int4 params flip the kernel to its
    GSPMD-partitionable "cp" mode at engine init (r5). An EXPLICIT
    BENCH_QUANT=4 on a MoE spec is honored but logged."""
    global QUANT_BITS, BATCH
    if not (QUANT and QUANT_BITS == 4) or not spec.n_experts:
        return
    if _Q_EXPLICIT:
        log("WARNING: BENCH_QUANT=4 on a MoE spec — expert weights are "
            "4-D, the Mosaic kernel disengages, and the XLA int4 path "
            "measured 2.3x slower than int8")
    else:
        log("int4 default downgraded to int8: MoE expert weights are 4-D")
        QUANT_BITS = 8
        if _BIG_INT4_CONT and "BENCH_BATCH" not in os.environ:
            # the bs128 default rode the int4 assumption (int8 bs128
            # with bf16 KV does not fit a 16 GB chip — README table);
            # re-derive alongside the quant downgrade
            BATCH = 64
            log("batch default re-derived to 64 (int8 bs128 needs fp8 KV)")
ENGINE_KIND = os.environ.get("BENCH_ENGINE", "continuous")
# default slots: the throughput-serving configuration. The 8B int4
# continuous flagship moved to bs128 in r5 — int4 frees enough HBM that
# bs128 fits with bf16 KV, and weights amortize over 2x the tokens:
# 5,315 tok/s vs 4,639 at bs64 (fp8 KV at bs128 measured SLOWER, 4,634 —
# the convert overhead now outweighs the saved KV bandwidth, so fp8 KV
# is a capacity lever only on this engine). Other configs keep bs64
# (batch sweep in README — aggregate tok/s scales ~5x from bs8 while
# TTFT stays sub-second).
_BIG_INT4_CONT = IS_BIG and _Q == "4" and \
    os.environ.get("BENCH_ENGINE", "continuous") == "continuous"
BATCH = int(os.environ.get("BENCH_BATCH", "128" if _BIG_INT4_CONT else "64"))
PROMPT_LEN = int(os.environ.get("BENCH_PROMPT", "128"))
NEW_TOKENS = int(os.environ.get("BENCH_NEW_TOKENS", "128"))
RUNS = int(os.environ.get("BENCH_RUNS", "3"))
# mixed workload (ISSUE 3): every BENCH_MIX_EVERY-th serving request
# carries a BENCH_MIX_PROMPT-token prompt instead of PROMPT_LEN — a steady
# decode stream with periodic long-prompt admissions, the shape whose ITL
# cliff the ragged mixed step exists to flatten. 0 disables.
MIX_EVERY = int(os.environ.get("BENCH_MIX_EVERY", "0"))
MIX_PROMPT = int(os.environ.get("BENCH_MIX_PROMPT", "2048"))
MAX_PROMPT = max(PROMPT_LEN, MIX_PROMPT) if MIX_EVERY else PROMPT_LEN


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pct(xs, q: float):
    """Nearest-rank percentile (shared with examples/serving_sweep.py)."""
    return (sorted(xs)[min(len(xs) - 1, math.ceil(q * len(xs)) - 1)]
            if xs else 0.0)


# Fleet-sweep knobs (examples/fleet_sweep.py imports these; docstring above
# documents them — reading them here keeps every BENCH_* knob in one file
# for the knob-drift check). Shapes the fake fleet and its offered load.
FLEET_DIR = os.environ.get("BENCH_FLEET_DIR", "bench_obs")
FLEET_NS = [int(n) for n in
            os.environ.get("BENCH_FLEET_NS", "1,2,4").split(",")]
FLEET_REQUESTS = int(os.environ.get("BENCH_FLEET_REQUESTS", "160"))
FLEET_RATE = float(os.environ.get("BENCH_FLEET_RATE", "120"))
FLEET_NEW_TOKENS = int(os.environ.get("BENCH_FLEET_NEW_TOKENS", "16"))
FLEET_STEP_MS = float(os.environ.get("BENCH_FLEET_STEP_MS", "5"))
FLEET_SLOTS = int(os.environ.get("BENCH_FLEET_SLOTS", "8"))
FLEET_SEED = int(os.environ.get("BENCH_FLEET_SEED", "1234"))
FLEET_TINY = os.environ.get("BENCH_FLEET_TINY", "1") not in ("0", "")
FLEET_MIN = int(os.environ.get("BENCH_FLEET_MIN", "1"))
FLEET_MAX = int(os.environ.get("BENCH_FLEET_MAX", "3"))
FLEET_BURST = float(os.environ.get("BENCH_FLEET_BURST", "3.5"))


def _probe_tpu(timeout_s: float = 120.0) -> bool:
    """Device discovery over a tunnelled TPU plugin can hang indefinitely
    when the tunnel is down; probe it in a throwaway subprocess so the
    benchmark itself can fall back to CPU instead of stalling the driver."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        backend = (proc.stdout or "").strip().splitlines()[-1:]
        return proc.returncode == 0 and backend != ["cpu"]
    except (subprocess.TimeoutExpired, OSError):
        return False


def _spec():
    from distributed_inference_engine_tpu.models import spec_for_architecture

    return spec_for_architecture(MODEL)


def _build_params(spec, quant: bool):
    import jax

    from distributed_inference_engine_tpu.ops.quant import (
        random_quantized_params,
    )

    if not quant:
        return None                      # engine does its own random init
    return random_quantized_params(spec, jax.random.key(0),
                                   bits=QUANT_BITS)


def _engine(spec, params, kind: str, batch: int, steps: int):
    from distributed_inference_engine_tpu.config import EngineConfig

    cfg = EngineConfig(
        max_slots=batch,
        max_seq_len=min(spec.max_seq_len, MAX_PROMPT + NEW_TOKENS),
        prefill_buckets=sorted({PROMPT_LEN, MAX_PROMPT}),
        decode_steps_per_call=steps,
    )
    if os.environ.get("BENCH_KV_DTYPE"):
        cfg.kv_dtype = os.environ["BENCH_KV_DTYPE"]
    if os.environ.get("BENCH_ATTN"):
        cfg.attention_impl = os.environ["BENCH_ATTN"]
    if os.environ.get("BENCH_DEFER"):
        # overlap each chunk's packed readback with the next chunk's
        # execution (serving-mode lever: the round trip is ~100 ms on a
        # tunnelled chip vs a ~300 ms 16-step chunk)
        cfg.defer_sync = True
    if os.environ.get("BENCH_STREAM", "") not in ("", "0"):
        # sub-chunk streaming (ISSUE 13): while any live slot has a
        # stream callback, clamp decode chunks to BENCH_STREAM_STEPS
        # (pow2-bucketed) so the token ring emits at sub-chunk cadence;
        # pure-batch waves keep the full megastep
        cfg.stream_chunk_steps = int(
            os.environ.get("BENCH_STREAM_STEPS", "2"))
    if kind == "static":
        from distributed_inference_engine_tpu.engine.engine import Engine

        return Engine(spec, params=params, config=cfg)
    if kind == "speculative":
        import jax

        from distributed_inference_engine_tpu.engine.speculative import (
            SpeculativeEngine,
            truncated_draft,
        )

        if params is None:
            from distributed_inference_engine_tpu.models.base import (
                init_params,
            )

            params = init_params(spec, jax.random.key(0))
        d_spec, d_params = truncated_draft(
            spec, params, int(os.environ.get("BENCH_DRAFT_LAYERS", "8")))
        return SpeculativeEngine(
            spec, d_spec, params=params, draft_params=d_params, config=cfg,
            speculate_k=int(os.environ.get("BENCH_SPEC_K", "4")))
    from distributed_inference_engine_tpu.engine.continuous import (
        ContinuousEngine,
    )

    cfg.page_size = 128
    per_seq = -(-(PROMPT_LEN + NEW_TOKENS) // cfg.page_size)  # ceil
    cfg.num_pages = max(64, batch * per_seq + 8)
    # measured crossover (README table): dense-ctx window chunks win when
    # weight streaming dominates (8B: 3661 r3 vs 1038 for per-step pool
    # scatter); small-KV models keep the inline scatter (GPT-2: 10673 vs
    # 7169)
    if os.environ.get("BENCH_DECODE_MODE"):
        cfg.decode_mode = os.environ["BENCH_DECODE_MODE"]
    elif not IS_BIG:
        cfg.decode_mode = "inline"
    # fused decode megastep (ISSUE 5a): fold RMSNorm into the qkv /
    # gate+up matmuls and the residual add into attn-out / MLP-down —
    # closes the elementwise seams between the big weight streams.
    # Token-identical to the unfused path (tests/test_fused_decode.py).
    cfg.decode_fused = os.environ.get("BENCH_FUSED", "1") not in ("0", "")
    if os.environ.get("BENCH_PREFILL_CHUNK"):
        # chunked prefill: long prompts prefill in page-aligned chunks
        # interleaved with decode (bounds the admission stall on live
        # decodes). Mirror the engine's page rounding when building the
        # bucket set, or every chunk pads to the raw (unrounded) bucket
        raw = int(os.environ["BENCH_PREFILL_CHUNK"])
        cfg.prefill_chunk = raw
        chunk = max(cfg.page_size, raw // cfg.page_size * cfg.page_size)
        cfg.prefill_buckets = sorted({chunk, PROMPT_LEN, MAX_PROMPT})
    if os.environ.get("BENCH_MIXED_TOKENS"):
        # Sarathi-style prefill budget per mixed ragged step (takes effect
        # with BENCH_ATTN=pallas-ragged and BENCH_PREFILL_CHUNK set)
        cfg.mixed_step_tokens = int(os.environ["BENCH_MIXED_TOKENS"])
    if os.environ.get("BENCH_KV_OFFLOAD", "") not in ("", "0"):
        # host-RAM KV tier: evicted prefix pages offload instead of
        # dropping, admission prefetches host hits back, pool exhaustion
        # swaps decode victims out and resumes them (engine/kv_offload.py)
        cfg.kv_offload = True
        cfg.kv_offload_bytes = int(
            os.environ.get("BENCH_KV_OFFLOAD_BYTES", str(1 << 30)))
    return ContinuousEngine(spec, params=params, config=cfg)


def _roofline(spec, params, batch: int, toks_per_s: float,
              kv_dtype_bytes: int) -> dict:
    """Streamed bytes per decode step → fraction of the chip's HBM peak.

    Weights stream fully each step EXCEPT the token embedding (a gather of
    ``batch`` rows; when embeddings are tied the unembed matmul streams the
    table, so it counts). KV reads grow with context: mean over the decode
    phase ≈ prompt + new/2 tokens per slot.
    """
    from distributed_inference_engine_tpu.ops.quant import param_bytes

    total = param_bytes(params)
    emb_bytes = 0
    if not spec.tie_embeddings:
        emb = params["tok_emb"]
        emb_bytes = emb.size * emb.dtype.itemsize
    kv_per_token = (2 * spec.n_layers * spec.n_kv_heads * spec.head_dim
                    * kv_dtype_bytes)
    mean_ctx = PROMPT_LEN + NEW_TOKENS / 2
    step_bytes = (total - emb_bytes) + batch * mean_ctx * kv_per_token
    steps_per_s = toks_per_s / batch
    gbps = step_bytes * steps_per_s / 1e9
    return {
        "param_gib": round(total / (1 << 30), 2),
        "step_mb": round(step_bytes / 1e6, 1),
        "achieved_gbps": round(gbps, 1),
        "hbm_util": round(gbps / V5E_HBM_GBPS, 3),
    }


def _matmul_flops_per_token(spec) -> float:
    """2 × (matmul weight elements) per token — the dense-forward FLOP
    count prefill MFU is judged against. Embedding gather is free; an
    untied lm_head is a real matmul and counts. Attention score/value
    FLOPs (≈ 4·ctx·H·dh per token, <0.1% at the bench prompt lengths)
    are excluded, which slightly UNDERSTATES MFU — conservative."""
    d, dh = spec.d_model, spec.head_dim
    per_layer = (d * spec.n_heads * dh              # wq
                 + 2 * d * spec.n_kv_heads * dh     # wk, wv
                 + spec.n_heads * dh * d            # wo
                 + 3 * d * spec.d_ff)               # gate, up, down
    total = spec.n_layers * per_layer
    if not spec.tie_embeddings:
        total += d * spec.vocab_size
    return 2.0 * total


V5E_BF16_TFLOPS = 197.0       # v5e peak dense bf16 (MXU)


def prime_pump(pump, spec, n: int) -> None:
    """Unmeasured priming trial (VERDICT r3 item 7): the first full-shape
    trial after engine init absorbs XLA cache lookups and tunnel setup and
    reads as a stall — burn one batch through the pump before the clock
    starts. Shared by serving_main and examples/serving_sweep.py."""
    import asyncio

    from distributed_inference_engine_tpu.engine.types import (
        EngineOverloadedError,
    )

    t0 = time.perf_counter()

    async def _prime():
        async def one(req):
            try:
                await pump.generate_streaming(req, lambda toks: None)
            except EngineOverloadedError:
                pass
        await asyncio.gather(*(one(r) for r in _requests(spec, 5, n)))

    asyncio.run(_prime())
    log(f"priming trial: {time.perf_counter() - t0:.1f}s (unmeasured)")


def _requests(spec, seed: int, n: int):
    import numpy as np

    from distributed_inference_engine_tpu.engine.types import (
        GenerationRequest,
    )

    rs = np.random.RandomState(seed)

    def _plen(i: int) -> int:
        # periodic long-prompt admissions into a steady short-prompt
        # stream (SWEEP_SHAPE=mixed); the first request stays short so
        # the decode stream establishes before the first admission burst
        if MIX_EVERY and i > 0 and i % MIX_EVERY == 0:
            return min(MIX_PROMPT, spec.max_seq_len - NEW_TOKENS)
        return PROMPT_LEN

    return [
        GenerationRequest(
            prompt=rs.randint(0, spec.vocab_size, size=_plen(i)).tolist(),
            max_new_tokens=NEW_TOKENS,
            temperature=0.0,
            request_id=f"bench-{seed}-{i}",
        )
        for i in range(n)
    ]


def dump_obs(engine, result_rows, label, pump=None) -> None:
    """Drop a /metrics-equivalent registry snapshot, a per-request trace
    JSONL, and the engine's step timeline (Perfetto-loadable) next to the
    BENCH json. ``BENCH_OBS_DIR`` picks the directory (default bench_obs;
    "0" disables)."""
    out_dir = os.environ.get("BENCH_OBS_DIR", "bench_obs")
    if out_dir in ("0", ""):
        return
    try:
        from distributed_inference_engine_tpu.obs import (
            collectors as obs_collectors,
        )
        from distributed_inference_engine_tpu.obs.registry import (
            MetricsRegistry,
        )

        os.makedirs(out_dir, exist_ok=True)
        reg = MetricsRegistry()
        obs_collectors.ensure_families(reg)
        obs_collectors.apply_engine(reg, engine.get_metrics(),
                                    model=MODEL, worker_id="bench")
        if pump is not None:
            ps = {k: v for k, v in pump.get_stats().items()
                  if k != "engine"}
            obs_collectors.apply_pump(reg, ps, model=MODEL,
                                      worker_id="bench")
        with open(os.path.join(out_dir, f"bench_metrics_{label}.prom"),
                  "w") as f:
            f.write(reg.render())
        # only terminal traces are dumped: a row with no finish_reason is
        # a request that never completed (cancelled mid-run / in flight at
        # teardown) and its latency fields are garbage — skipping beats
        # poisoning downstream percentile tooling with partial marks
        terminal = [r for r in result_rows if r.get("finish_reason")]
        skipped = len(result_rows) - len(terminal)
        with open(os.path.join(out_dir, f"bench_traces_{label}.jsonl"),
                  "w") as f:
            for row in terminal:
                f.write(json.dumps(row) + "\n")
        if skipped:
            log(f"obs dump: skipped {skipped} non-terminal trace(s)")
        tl = getattr(engine, "timeline", None)
        if tl is not None and len(tl):
            tl.dump(os.path.join(out_dir, f"bench_timeline_{label}.json"))
        log(f"obs dump -> {out_dir}/bench_*_{label}.*")
    except Exception as e:             # observability must not fail the rung
        log(f"obs dump failed: {e}")


def _result_row(res) -> dict:
    return {
        "request_id": res.request_id,
        "tokens": len(res.tokens),
        "finish_reason": res.finish_reason,
        "ttft_s": round(float(res.ttft_s), 6),
        "decode_s": round(float(res.decode_s), 6),
    }


def decode_main() -> None:
    """Batch-decode throughput rung (static or continuous engine)."""
    spec = _spec()
    resolve_quant(spec)
    # continuous default chunk 128 (= NEW_TOKENS): with the round-3 dense-
    # ctx chunk scheme the whole decode runs as ONE chunk — one ctx gather,
    # one host sync — measuring 3623 tok/s at 8B bs64 vs 3173 at chunk 64
    # (each extra chunk pays a tunnel round trip + a re-gather; the round-2
    # side-window scheme peaked at chunk 64 because its side buffer grew
    # with the chunk). Serving keeps small chunks (admission cadence).
    default_steps = (min(128, NEW_TOKENS) if ENGINE_KIND == "continuous"
                     else NEW_TOKENS)
    steps = int(os.environ.get("BENCH_STEPS", str(default_steps)))
    t0 = time.perf_counter()
    params = _build_params(spec, QUANT)
    engine = _engine(spec, params, ENGINE_KIND, BATCH, steps)
    # drop the pre-fusion tree reference: the engine's prepare_params
    # replaced qkv/gate+up members with fused payloads, and holding the
    # originals alive here pins ~2.2 GB of dead HBM — enough to OOM the
    # int4 bs128 rung on a 16 GB chip (engine.params is the live tree)
    params = None
    log(f"engine init ({MODEL}, {ENGINE_KIND}, "
        f"quant={QUANT_BITS if QUANT else 0}): "
        f"{time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    engine.generate(_requests(spec, 1, BATCH))   # compile all programs
    log(f"warmup (compile): {time.perf_counter() - t0:.1f}s")

    best_toks = 0.0
    ttfts = []
    t_measure = time.perf_counter()   # host-gap split covers measured runs
    for r in range(RUNS):
        t0 = time.perf_counter()
        results = engine.generate(_requests(spec, 100 + r, BATCH))
        wall = time.perf_counter() - t0
        gen = sum(len(x.tokens) for x in results)
        decode_s = results[0].decode_s
        toks = (gen - len(results)) / decode_s   # first token is prefill's
        ttfts.append(results[0].ttft_s)
        log(f"run {r}: {gen} tokens, e2e {wall:.2f}s "
            f"({gen / wall:.1f} tok/s e2e), decode {decode_s:.2f}s -> "
            f"{toks:.1f} tok/s (ttft {results[0].ttft_s * 1e3:.1f} ms)")
        best_toks = max(best_toks, toks)

    kv_bytes = 1 if getattr(engine.config, "kv_dtype", "") == "float8_e4m3fn" \
        else 2
    roof = _roofline(spec, engine.params, BATCH, best_toks, kv_bytes)
    # decompose the roofline gap (ISSUE 5): hbm_util divides streamed bytes
    # by WALL time, so host bubbles between dispatches read as missing
    # bandwidth. Split the measured window into kernel-time vs host-bubble
    # from the step timeline; hbm_util_kernel rescales to dispatch-bracket
    # time only — "what the kernels achieve when they are actually running".
    tl = getattr(engine, "timeline", None)
    if tl is not None and len(tl):
        from distributed_inference_engine_tpu.obs.timeline import (
            busy_gap_split,
        )

        split = busy_gap_split(tl.events(since=t_measure))
        roof["host_bubble_frac"] = round(split["bubble_frac"], 3)
        denom = 1.0 - split["bubble_frac"]
        roof["hbm_util_kernel"] = round(
            min(1.0, roof["hbm_util"] / denom) if denom > 0
            else roof["hbm_util"], 3)
        log(f"host-gap split over {split['n_events']} dispatches: "
            f"busy {split['busy_s']:.2f}s gap {split['gap_s']:.2f}s "
            f"(bubble {split['bubble_frac']:.1%})")
    ttft_ms = sorted(ttfts)[len(ttfts) // 2] * 1e3
    # prefill efficiency (VERDICT r3 item 4): prefill is compute-bound, so
    # judge it as MFU over the whole-batch TTFT (submit -> first token:
    # includes sampling + the packed readback, so this is a lower bound)
    prefill_flops = _matmul_flops_per_token(spec) * BATCH * PROMPT_LEN
    prefill_mfu = (prefill_flops / (ttft_ms / 1e3)
                   / (V5E_BF16_TFLOPS * 1e12)) if ttft_ms else 0.0
    log(f"p50 TTFT: {ttft_ms:.1f} ms; prefill MFU {prefill_mfu:.2f} "
        f"({prefill_flops / 1e12:.1f} TF batch); roofline: {roof}")
    suffix = "" if ENGINE_KIND == "continuous" else f"_{ENGINE_KIND}"
    row = {
        "metric": f"decode_throughput_{MODEL}"
                  f"{f'_int{QUANT_BITS}' if QUANT else ''}"
                  f"_bs{BATCH}{suffix}",
        "value": round(best_toks, 1),
        "unit": "tok/s",
        "vs_baseline": round(best_toks / NORTH_STAR_TOKS, 2),
        "hbm_util": roof["hbm_util"],
        "achieved_gbps": roof["achieved_gbps"],
        "ttft_p50_ms": round(ttft_ms, 1),
        "prefill_mfu": round(prefill_mfu, 3),
    }
    if "host_bubble_frac" in roof:
        row["host_bubble_frac"] = roof["host_bubble_frac"]
        row["hbm_util_kernel"] = roof["hbm_util_kernel"]
    m = engine.get_metrics()
    if "draft_acceptance_rate" in m:
        row["acceptance"] = round(m["draft_acceptance_rate"], 3)
        row["tokens_per_round"] = round(m["tokens_per_round"], 2)
        row["speculate_k"] = m["speculate_k"]
        # the roofline model assumes one weight pass per decode step per
        # token — speculation exists to break that assumption, so the
        # util fields would be nonsense here
        row.pop("hbm_util", None)
        row.pop("achieved_gbps", None)
    print(json.dumps(row), flush=True)
    dump_obs(engine, [_result_row(x) for x in results], "decode")


def serving_main() -> None:
    """Serving load test (VERDICT r1 item 5): Poisson arrivals through
    ``EnginePump`` — N independent clients, each streaming one request —
    measuring throughput, TTFT p50/p99 (from submit, queue wait included),
    streaming ITL p99, and decode-batch occupancy."""
    import asyncio

    import numpy as np

    from distributed_inference_engine_tpu.serving.pump import EnginePump

    from distributed_inference_engine_tpu.engine.types import (
        EngineOverloadedError,
    )

    spec = _spec()
    resolve_quant(spec)
    # default offered load ~near capacity: an 8B chip serves ~4 requests/s
    # of 128 fresh tokens; small models far more
    rate = float(os.environ.get("BENCH_RATE", "4" if IS_BIG else "16"))
    n_requests = int(os.environ.get("BENCH_REQUESTS", "64"))
    steps = int(os.environ.get("BENCH_STEPS", "16"))

    t0 = time.perf_counter()
    params = _build_params(spec, QUANT)
    engine = _engine(spec, params, "continuous", BATCH, steps)
    params = None                     # see decode_main: free pre-fusion tree
    # overload handling on by default in serving mode: past saturation the
    # engine sheds (typed error) instead of growing an unbounded queue, so
    # the latency curve has a knee instead of a cliff (VERDICT r2 item 2)
    engine.config.max_waiting = int(
        os.environ.get("BENCH_MAX_WAITING", str(4 * BATCH)))
    engine.config.queue_deadline_s = float(
        os.environ.get("BENCH_DEADLINE_S", "10"))
    engine.config.admission_min_batch = int(
        os.environ.get("BENCH_ADMIT_MIN", "0"))
    engine.config.admission_max_hold_s = float(
        os.environ.get("BENCH_ADMIT_HOLD", "0.25"))
    log(f"engine init ({MODEL}, serving, quant={QUANT_BITS if QUANT else 0}): "
        f"{time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    # Poisson arrivals admit in small bursts: EVERY pow2 admission bucket
    # must be compiled before the clock starts, not just bb=BATCH
    engine.warmup(max_new_tokens=2)
    log(f"warmup (compile all buckets): {time.perf_counter() - t0:.1f}s")

    # batch-formation overlap (ISSUE 5c): the pump wires engine.overlap_hook
    # so inbox draining (validation, submit, prefetch probes) runs in the
    # shadow of in-flight device steps instead of the host gap between them
    overlap = os.environ.get("BENCH_OVERLAP", "1") not in ("0", "")
    pump = EnginePump(engine, idle_wait_s=0.01, overlap_forms=overlap)
    prime_pump(pump, spec, min(BATCH, n_requests))
    reqs = _requests(spec, 7, n_requests)
    itls: list = []
    ttfts: list = []
    # occupancy must cover the MEASURED window only — warmup ticks the
    # engine's cumulative counters too
    m0 = engine.get_metrics()
    steps0 = m0["engine_steps"]
    occ_sum0 = m0["batch_occupancy"] * steps0 * engine.max_slots
    dispatch0 = m0.get("dispatch_s_total", 0.0)
    gap0 = m0.get("host_gap_s_total", 0.0)

    rejected = [0]                     # queue-full + deadline sheds

    trace_rows: list = []

    async def client(req):
        marks = []

        def on_tokens(toks):
            marks.append((time.perf_counter(), len(toks)))

        try:
            res = await pump.generate_streaming(req, on_tokens)
        except EngineOverloadedError:
            rejected[0] += 1
            return 0
        trace_rows.append(_result_row(res))
        ttfts.append(res.ttft_s)
        prev = None
        for t, k in marks:
            if prev is not None:
                itls.append(t - prev)      # chunk gap: the consumer-visible
                itls.extend([0.0] * (k - 1))   # intra-chunk tokens co-arrive
            prev = t
        return len(res.tokens)

    async def run():
        rs = np.random.RandomState(3)
        tasks = []
        t_start = time.perf_counter()
        for req in reqs:
            tasks.append(asyncio.create_task(client(req)))
            await asyncio.sleep(float(rs.exponential(1.0 / rate)))
        counts = await asyncio.gather(*tasks)
        wall = time.perf_counter() - t_start
        await pump.stop()
        return sum(counts), wall

    total_toks, wall = asyncio.run(run())
    m = engine.get_metrics()
    toks_per_s = total_toks / wall
    ttft_p50, ttft_p99 = pct(ttfts, 0.5) * 1e3, pct(ttfts, 0.99) * 1e3
    # p50 next to p99: the mixed-step claim is about the TAIL (admissions
    # must not cliff p99 above ~2x the steady-state median), so both ends
    # of the ITL distribution are first-class outputs
    itl_p50 = pct(itls, 0.5) * 1e3
    itl_p99 = pct(itls, 0.99) * 1e3
    d_steps = m["engine_steps"] - steps0
    occ = ((m["batch_occupancy"] * m["engine_steps"] * engine.max_slots
            - occ_sum0) / (d_steps * engine.max_slots)) if d_steps else 0.0
    rej_rate = rejected[0] / len(reqs) if reqs else 0.0
    # host-gap split over the measured window (same delta idiom as
    # occupancy): dispatch = inside device-dispatch brackets, gap = host
    # time between them — attributes a goodput shortfall to the scheduler
    # side vs the kernel side
    d_dispatch = m.get("dispatch_s_total", 0.0) - dispatch0
    d_gap = m.get("host_gap_s_total", 0.0) - gap0
    bubble = d_gap / (d_dispatch + d_gap) if (d_dispatch + d_gap) > 0 else 0.0
    overlap_admitted = pump.get_stats().get("overlap_admitted", 0)
    log(f"served {len(reqs)} reqs ({total_toks} tokens) in {wall:.1f}s at "
        f"offered rate {rate}/s -> {toks_per_s:.1f} tok/s goodput; "
        f"rejected {rejected[0]} ({rej_rate:.0%}); TTFT p50 "
        f"{ttft_p50:.0f} ms p99 {ttft_p99:.0f} ms; ITL p50 {itl_p50:.1f} ms "
        f"p99 {itl_p99:.1f} ms; occupancy {occ:.2f}; host bubble "
        f"{bubble:.1%} (dispatch {d_dispatch:.1f}s gap {d_gap:.1f}s, "
        f"{overlap_admitted} overlap-admitted)")
    print(json.dumps({
        "metric": f"serving_throughput_{MODEL}"
                  f"{f'_int{QUANT_BITS}' if QUANT else ''}"
                  f"_rate{rate:g}",
        "value": round(toks_per_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(toks_per_s / NORTH_STAR_TOKS, 2),
        "ttft_p50_ms": round(ttft_p50, 1),
        "ttft_p99_ms": round(ttft_p99, 1),
        "itl_p50_ms": round(itl_p50, 2),
        "itl_p99_ms": round(itl_p99, 2),
        "occupancy": round(occ, 3),
        "rejected": rejected[0],
        "rejection_rate": round(rej_rate, 3),
        "host_bubble_frac": round(bubble, 3),
        "dispatch_s": round(d_dispatch, 2),
        "host_gap_s": round(d_gap, 2),
        "overlap_admitted": overlap_admitted,
    }), flush=True)
    dump_obs(engine, trace_rows, "serving", pump=pump)


def main() -> None:
    if os.environ.get("BENCH_FORCE_CPU") or not _probe_tpu():
        log("TPU backend unreachable (or BENCH_FORCE_CPU set) — "
            "falling back to CPU")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    log(f"devices: {jax.devices()}")
    if ENGINE_KIND == "serving":
        serving_main()
    else:
        decode_main()


if __name__ == "__main__":
    main()
